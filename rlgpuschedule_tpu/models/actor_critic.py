"""Actor-critic heads (L3).

Capability parity: SURVEY.md §2 "Actor/critic heads" — action logits over
the scheduling action space (job-select × placement + no-op) and a value
head, with infeasible actions masked to -inf before sampling (SURVEY.md §7
step 4 "action masking via -inf logits").
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import flax.linen as nn

from .encoders import MLPEncoder, CNNEncoder, GNNEncoder

NEG_INF = -1e9


def mask_logits(logits: jax.Array, mask: jax.Array) -> jax.Array:
    return jnp.where(mask, logits, NEG_INF)


class ActorCritic(nn.Module):
    """Pooled-trunk actor-critic (MLP and CNN encoders).

    ``apply(params, obs, mask) -> (masked_logits f32, value f32)``."""
    encoder: nn.Module
    n_actions: int

    @nn.compact
    def __call__(self, obs: jax.Array, mask: jax.Array
                 ) -> tuple[jax.Array, jax.Array]:
        h = self.encoder(obs)
        logits = nn.Dense(self.n_actions, dtype=jnp.float32,
                          kernel_init=nn.initializers.orthogonal(0.01),
                          name="policy")(h)
        value = nn.Dense(1, dtype=jnp.float32,
                         kernel_init=nn.initializers.orthogonal(1.0),
                         name="value")(h)
        return mask_logits(logits.astype(jnp.float32), mask), value.squeeze(-1)


class GNNActorCritic(nn.Module):
    """Graph actor-critic (config 4): per-queue-slot logits come from each
    slot's own node embedding (slots are graph nodes N..N+K-1), so the
    policy is equivariant over queue slots; with ``n_placements`` > 1 each
    slot head emits pack/spread logits (the factored gang-scheduling +
    placement action space). With ``preempt_len`` > 0, per-running-slot
    preempt logits come from the running-slot nodes N+K..N+K+R-1 the same
    way. The no-op logit and value come from the pooled graph embedding."""
    encoder: GNNEncoder
    n_cluster_nodes: int
    queue_len: int
    n_placements: int = 1
    preempt_len: int = 0

    @nn.compact
    def __call__(self, obs: jax.Array, adj: jax.Array, mask: jax.Array
                 ) -> tuple[jax.Array, jax.Array]:
        h = self.encoder(obs, adj)                       # [..., V, D]
        pooled = h.mean(axis=-2)
        slots = h[..., self.n_cluster_nodes:
                  self.n_cluster_nodes + self.queue_len, :]  # [..., K, D]
        slot_logits = nn.Dense(self.n_placements, dtype=jnp.float32,
                               kernel_init=nn.initializers.orthogonal(0.01),
                               name="slot_policy")(slots)
        parts = [slot_logits.reshape(*slot_logits.shape[:-2], -1)]  # [..., K*P]
        if self.preempt_len:
            run0 = self.n_cluster_nodes + self.queue_len
            runs = h[..., run0:run0 + self.preempt_len, :]   # [..., R, D]
            pre = nn.Dense(1, dtype=jnp.float32,
                           kernel_init=nn.initializers.orthogonal(0.01),
                           name="preempt_policy")(runs)
            parts.append(pre.squeeze(-1))                    # [..., R]
        noop = nn.Dense(1, dtype=jnp.float32,
                        kernel_init=nn.initializers.orthogonal(0.01),
                        name="noop_policy")(pooled)
        parts.append(noop)
        logits = jnp.concatenate(parts, axis=-1)
        value = nn.Dense(1, dtype=jnp.float32,
                         kernel_init=nn.initializers.orthogonal(1.0),
                         name="value")(pooled)
        return mask_logits(logits.astype(jnp.float32), mask), value.squeeze(-1)


def make_policy(obs_kind: str, n_actions: int, *, n_cluster_nodes: int = 0,
                queue_len: int = 0, n_placements: int = 1,
                preempt_len: int = 0, dtype=jnp.bfloat16) -> nn.Module:
    """Encoder-selection factory matching EnvParams.obs_kind."""
    if obs_kind == "flat":
        return ActorCritic(MLPEncoder(dtype=dtype), n_actions)
    if obs_kind == "grid":
        return ActorCritic(CNNEncoder(dtype=dtype), n_actions)
    if obs_kind == "graph":
        return GNNActorCritic(GNNEncoder(dtype=dtype), n_cluster_nodes,
                              queue_len, n_placements, preempt_len)
    raise ValueError(f"unknown obs_kind {obs_kind!r}")
