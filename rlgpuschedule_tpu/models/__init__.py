"""L3 policy/value networks: Flax encoders + actor-critic heads."""
from .encoders import MLPEncoder, CNNEncoder, GNNEncoder
from .actor_critic import (ActorCritic, GNNActorCritic, make_policy,
                           mask_logits, NEG_INF)
from .hier import HierActorCritic

__all__ = ["MLPEncoder", "CNNEncoder", "GNNEncoder", "ActorCritic",
           "GNNActorCritic", "make_policy", "mask_logits", "NEG_INF",
           "HierActorCritic"]
