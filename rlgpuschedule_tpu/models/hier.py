"""Hierarchical actor-critic (L3) — config 5's policy.

Capability parity: SURVEY.md §2 "Hierarchical multi-agent" / §3.5 "top
scheduler ↔ per-pod schedulers": one Flax module holds the top-level
router head and the per-pod placement head. The pod trunk's weights are
SHARED across pods (flax ``Dense`` broadcasts over the pod axis, so all P
pod forwards are one batched MXU matmul — the TPU-native replacement for
the reference's per-pod agent processes); the router sees its own summary
observation plus the pooled pod embeddings. A single critic values the
joint state (the factored heads optimize one joint PPO objective via
``algos.action_dist``).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import flax.linen as nn

from .actor_critic import mask_logits
from .encoders import MLPEncoder


class HierActorCritic(nn.Module):
    """``apply(params, obs, mask) -> (logits, value)`` with
    ``obs = {"top": [*B, Dt], "pods": [*B, P, Dp]}``,
    ``mask = {"top": [*B, P+1], "pods": [*B, P, A]}``,
    ``logits = {"top": [*B, P+1], "pods": [*B, P, A]}`` (see
    algos.action_dist for the stacked-head convention)."""
    n_top_actions: int
    n_pod_actions: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, obs: dict, mask: dict
                 ) -> tuple[dict, jax.Array]:
        top_h = MLPEncoder(dtype=self.dtype, name="top_trunk")(obs["top"])
        pod_h = MLPEncoder(dtype=self.dtype, name="pod_trunk")(obs["pods"])
        pooled = pod_h.mean(axis=-2)
        joint = jnp.concatenate([top_h, pooled], axis=-1)
        top_logits = nn.Dense(self.n_top_actions, dtype=jnp.float32,
                              kernel_init=nn.initializers.orthogonal(0.01),
                              name="top_policy")(joint)
        pod_logits = nn.Dense(self.n_pod_actions, dtype=jnp.float32,
                              kernel_init=nn.initializers.orthogonal(0.01),
                              name="pod_policy")(pod_h)
        value = nn.Dense(1, dtype=jnp.float32,
                         kernel_init=nn.initializers.orthogonal(1.0),
                         name="value")(joint)
        logits = {
            "top": mask_logits(top_logits.astype(jnp.float32), mask["top"]),
            "pods": mask_logits(pod_logits.astype(jnp.float32),
                                mask["pods"]),
        }
        return logits, value.squeeze(-1)
