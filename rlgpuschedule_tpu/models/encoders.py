"""Cluster-state encoders (L3): MLP, CNN over the occupancy grid, GNN over
the topology graph.

Capability parity: SURVEY.md §2 "MLP encoder" / "CNN encoder" / "GNN
encoder" — the reference's PyTorch policy trunks become Flax modules
compiled by XLA (SURVEY.md §1 TPU restatement).

TPU notes: all trunks expose a ``dtype`` knob (bfloat16 activations by
default keep the matmuls on the MXU's native precision; params stay f32).
The GNN uses **dense masked adjacency matmuls** instead of scatter/gather
message passing — cluster graphs are small (N + K ≤ a few hundred nodes),
so one [V,V]×[V,D] matmul per layer is both simpler and faster on the MXU
than segment ops.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import flax.linen as nn


class MLPEncoder(nn.Module):
    """Dense trunk for flat observations (config 1)."""
    features: Sequence[int] = (256, 256)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x.astype(self.dtype)
        for f in self.features:
            x = nn.Dense(f, dtype=self.dtype)(x)
            x = nn.LayerNorm(dtype=self.dtype)(x)
            x = nn.silu(x)
        return x


class CNNEncoder(nn.Module):
    """Conv trunk over the [H, W, C] occupancy image (config 2).

    The first layer keeps full resolution; later layers stride 2 along the
    node axis only (H halves per layer, e.g. 64→16 nodes over 3 layers),
    while the narrow GPU axis (W≈8) stays full-width throughout. XLA fuses
    the LayerNorm/silu chain into the convs."""
    features: Sequence[int] = (32, 64, 64)
    dense: int = 256
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x.astype(self.dtype)
        for i, f in enumerate(self.features):
            x = nn.Conv(f, (3, 3), strides=(2, 1) if i else (1, 1),
                        dtype=self.dtype)(x)
            x = nn.LayerNorm(dtype=self.dtype)(x)
            x = nn.silu(x)
        x = x.reshape(*x.shape[:-3], -1)
        x = nn.Dense(self.dense, dtype=self.dtype)(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        return nn.silu(x)


class GNNEncoder(nn.Module):
    """Dense message-passing trunk over the cluster-topology graph
    (config 4). Returns per-node embeddings [V, D].

    Each layer: h' = silu(LN(Â h W_msg + h W_self)) with Â the
    degree-normalized adjacency — a pair of MXU matmuls per layer. The
    adjacency is a static constant (topology never changes; see
    env.obs.build_adjacency), passed in as an argument so one module works
    for any topology."""
    features: Sequence[int] = (128, 128, 128)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, adj: jax.Array) -> jax.Array:
        # x: [..., V, F], adj: [V, V] (0/1, self-loops included)
        deg = jnp.maximum(adj.sum(-1, keepdims=True), 1.0)
        a_norm = (adj / deg).astype(self.dtype)
        h = x.astype(self.dtype)
        for f in self.features:
            msg = nn.Dense(f, dtype=self.dtype, name=None)(h)
            agg = jnp.einsum("vw,...wd->...vd", a_norm, msg)
            self_h = nn.Dense(f, use_bias=False, dtype=self.dtype)(h)
            h = nn.silu(nn.LayerNorm(dtype=self.dtype)(agg + self_h))
        return h
