"""contract-drift: emitted names and their consumers, in lockstep.

Three observability/wire contracts cross every surface of this repo,
and nothing type-checks them: **metric names** registered on the obs
``Registry`` (``counter``/``gauge``/``histogram``) and then grepped out
of Prometheus text by ci.sh stages and test assertions; **event kinds**
emitted on the ``EventBus`` and matched by ``e["kind"] == ...`` checks
in chaos gates and ``obs/report.py``; and the **wire frame constants**
(``MAGIC``/``VERSION``/``struct`` prefix) that tests pin as golden
bytes. A renamed metric silently turns a CI grep into a tautology; a
retired event kind leaves a chaos gate asserting against a kind nothing
emits; a wire-format edit that forgets the golden bytes ships a
protocol break with green tests.

The rule is **cross-file** (``cross_file=True`` — never cached): it
activates only on the three anchor modules and audits the whole repo
from there, each finding landing in the file whose edit fixes it.

**Anchors** (name-based, the repo's contract): the module defining
``class Registry`` owns the metric surface; ``class EventBus`` owns the
kind surface; a module assigning ``MAGIC = b"..."`` and building a
``struct.Struct`` owns the wire surface. The repo root is the nearest
ancestor directory containing ``ci.sh`` (fixture trees carry their own
``ci.sh`` so they self-root).

**Emitters** — every non-test module under the root. Extraction is
literal-first but follows the repo's indirections: first args of
``.counter(...)``/``.gauge(...)``/``.histogram(...)`` and
``.emit(...)``/``_emit(...)`` calls; ``IfExp`` picks both branches
(the ``ckpt_crc_reject``/``ckpt_reject`` pattern); ``Name`` args
resolve through simple string bindings (``SPAN_BEGIN = "span_begin"``);
f-strings become wildcard patterns with one-hop variable resolution
(``stem = f"matrix_{rname}_{sched}"`` then ``f"{stem}_avg_jct"``).

**Consumers** — ``ci.sh`` (raw text plus parsed ``<<'EOF'`` heredocs,
which are pure Python in this repo), every ``tests/**.py`` (fixtures
are skipped by the tree walk), every ``report.py`` under the root, and
``README.md`` (consumption-witness only).

**Direction A (ghost reference)**: a consumer names a metric no code
registers — any token matching the metric grammar whose *family*
(first ``_`` segment) is an emitted family and whose last segment is a
known metric suffix must match an emitted literal or f-string pattern
(a histogram registration also covers the ``_bucket``/``_count``/
``_sum`` series the Prometheus exposition synthesizes for it).
A kind no code emits — matched structurally (``x["kind"] == lit``,
``.get("kind")``, ``*KINDS*`` tuples, ``for k in (...): assert k in
kinds`` loops over a kind-set comprehension). Fires at the consumer
line. Registration first-args inside consumer files are exempt (tests
registering their own metrics are not references).

**Direction B (orphan emission)**: an emitted literal that appears in
no consumer text and is not allowlisted fires at the emission site.
The allowlist is a module-level ``CONTRACT_ALLOWLIST`` tuple in the
owning anchor module (``ast.literal_eval``'d, no import) — the
sanctioned channel for metrics that exist for operators rather than
gates; per-line ``# jsan: disable`` cannot cover cross-file findings.

**Wire**: every ``tests/**`` assignment whose target contains
``GOLDEN`` and whose value is a bytes literal is validated against the
anchor: length equals ``struct.calcsize`` of the prefix format, the
``MAGIC`` prefix matches, the version byte matches ``VERSION``. A wire
anchor with *no* golden witness anywhere in tests fires on the
``MAGIC`` line — pinning the bytes is the contract, not an option.
"""
from __future__ import annotations

import ast
import os
import re
import struct

from . import Rule
from ..engine import Finding, ModuleContext, SourceFile, iter_py_files

_NAME_RE = re.compile(r"[a-z][a-z0-9]*(?:_[a-z0-9]+)+")
_HEREDOC_RE = re.compile(
    r"<<-?\s*'?([A-Za-z_][A-Za-z0-9_]*)'?[^\n]*\n(.*?)\n\1[ \t]*$",
    re.S | re.M)
_REG_METHODS = {"counter", "gauge", "histogram"}
_EMIT_NAMES = {"emit", "_emit"}
# last-segment gate for metric-shaped tokens, beyond suffixes derived
# from the emitted set itself (catches a last-segment typo of a common
# Prometheus suffix even when nothing emits that suffix yet)
_EXTRA_SUFFIXES = {"total", "seconds", "count", "sum", "bucket", "ms"}


# ---------------------------------------------------------------------------
# small AST helpers (no imports of scanned code — lint stays JAX-free)

def _parse(path: str) -> ast.AST | None:
    try:
        with open(path, encoding="utf-8") as f:
            return ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError, ValueError):
        return None


def _read(path: str) -> str:
    try:
        with open(path, encoding="utf-8") as f:
            return f.read()
    except OSError:
        return ""


def _assigned_literal(tree: ast.AST, name: str):
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            if any(isinstance(t, ast.Name) and t.id == name
                   for t in node.targets):
                try:
                    return ast.literal_eval(node.value), node.value
                except ValueError:
                    return None, None
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if isinstance(node.target, ast.Name) and node.target.id == name:
                try:
                    return ast.literal_eval(node.value), node.value
                except ValueError:
                    return None, None
    return None, None


def _str_bindings(tree: ast.AST) -> dict[str, "str | ast.JoinedStr"]:
    """Every simple ``name = "literal"`` / ``name = f"..."`` binding in
    the module (module level and function locals pooled — good enough
    to resolve the SPAN_*/stem indirections without scope analysis)."""
    out: dict[str, str | ast.JoinedStr] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            v = node.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                out[node.targets[0].id] = v.value
            elif isinstance(v, ast.JoinedStr):
                out[node.targets[0].id] = v
    return out


def _fstring_pattern(node: ast.JoinedStr, bindings, depth=0) -> str | None:
    """Regex source for an f-string emission; formatted holes become
    ``[a-z0-9_]+`` unless a one-hop binding pins them."""
    parts: list[str] = []
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(re.escape(v.value))
        elif isinstance(v, ast.FormattedValue):
            sub = None
            if depth < 2 and isinstance(v.value, ast.Name):
                bound = bindings.get(v.value.id)
                if isinstance(bound, str):
                    sub = re.escape(bound)
                elif isinstance(bound, ast.JoinedStr):
                    sub = _fstring_pattern(bound, bindings, depth + 1)
            parts.append(sub if sub is not None else r"[a-z0-9_]+")
        else:
            return None
    return "".join(parts) or None


def _call_attr(call: ast.Call) -> str | None:
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


def _first_arg_names(call: ast.Call, bindings) -> tuple[list[str], list[str]]:
    """(literals, patterns) the call's first argument can emit."""
    if not call.args:
        return [], []
    arg = call.args[0]
    lits: list[str] = []
    pats: list[str] = []

    def resolve(a: ast.AST) -> None:
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            lits.append(a.value)
        elif isinstance(a, ast.IfExp):
            resolve(a.body)
            resolve(a.orelse)
        elif isinstance(a, ast.Name):
            bound = bindings.get(a.id)
            if isinstance(bound, str):
                lits.append(bound)
            elif isinstance(bound, ast.JoinedStr):
                pat = _fstring_pattern(bound, bindings)
                if pat:
                    pats.append(pat)
        elif isinstance(a, ast.JoinedStr):
            pat = _fstring_pattern(a, bindings)
            if pat:
                pats.append(pat)

    resolve(arg)
    return lits, pats


# ---------------------------------------------------------------------------
# repo scan: emissions + consumers, memoized per root on stat signature

class _Scan:
    def __init__(self) -> None:
        self.sig: tuple = ()
        # name -> (path, lineno, col) of the first emission site
        self.metric_lits: dict[str, tuple[str, int, int]] = {}
        self.kind_lits: dict[str, tuple[str, int, int]] = {}
        # Prometheus histograms expose derived series the exposition
        # format synthesizes (name_bucket/_count/_sum) — consumers
        # legitimately reference those without any matching
        # registration literal
        self.metric_derived: set[str] = set()
        self.metric_pats: list[re.Pattern] = []
        self.kind_pats: list[re.Pattern] = []
        self.texts: dict[str, str] = {}           # path -> source text
        # consumer python units: (path, tree, line_offset)
        self.py_units: list[tuple[str, ast.AST, int]] = []
        self.ci_path: str | None = None
        self.ci_stripped: str = ""                # heredocs blanked
        self.consumed_text: str = ""              # union for direction B


def _find_root(path: str) -> str:
    d = os.path.dirname(os.path.abspath(path))
    cur = d
    while True:
        if os.path.isfile(os.path.join(cur, "ci.sh")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return d
        cur = parent


def _emitter_files(root: str) -> list[str]:
    out = []
    tests = os.path.join(root, "tests")
    for p in iter_py_files([root]):
        ap = os.path.abspath(p)
        if ap == tests or ap.startswith(tests + os.sep):
            continue
        out.append(ap)
    return out


def _consumer_files(root: str) -> list[str]:
    out = []
    tests = os.path.join(root, "tests")
    if os.path.isdir(tests):
        out.extend(os.path.abspath(p) for p in iter_py_files([tests]))
    for p in _emitter_files(root):
        if os.path.basename(p) == "report.py":
            out.append(p)
    return out


def _signature(root: str) -> tuple:
    entries = []
    for p in (_emitter_files(root) + _consumer_files(root)
              + [os.path.join(root, "ci.sh"),
                 os.path.join(root, "README.md")]):
        try:
            st = os.stat(p)
            entries.append((p, st.st_mtime_ns, st.st_size))
        except OSError:
            entries.append((p, -1, -1))
    return tuple(sorted(set(entries)))


_SCANS: dict[str, _Scan] = {}


def _scan(root: str) -> _Scan:
    sig = _signature(root)
    cached = _SCANS.get(root)
    if cached is not None and cached.sig == sig:
        return cached
    scan = _Scan()
    scan.sig = sig
    # -- emissions ---------------------------------------------------------
    for path in _emitter_files(root):
        tree = _parse(path)
        if tree is None:
            continue
        scan.texts[path] = _read(path)
        bindings = _str_bindings(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            attr = _call_attr(node)
            if attr in _REG_METHODS:
                dst_l, dst_p = scan.metric_lits, scan.metric_pats
            elif attr in _EMIT_NAMES:
                dst_l, dst_p = scan.kind_lits, scan.kind_pats
            else:
                continue
            lits, pats = _first_arg_names(node, bindings)
            site = (path, node.lineno, node.col_offset)
            for lit in lits:
                dst_l.setdefault(lit, site)
                if attr == "histogram":
                    scan.metric_derived.update(
                        f"{lit}_{d}" for d in ("bucket", "count", "sum"))
            for pat in pats:
                try:
                    dst_p.append(re.compile(pat))
                except re.error:
                    pass
    # -- consumers ---------------------------------------------------------
    consumed = []
    for path in _consumer_files(root):
        tree = _parse(path)
        text = _read(path)
        scan.texts[path] = text
        consumed.append(text)
        if tree is not None:
            scan.py_units.append((path, tree, 0))
    ci = os.path.join(root, "ci.sh")
    if os.path.isfile(ci):
        scan.ci_path = ci
        text = _read(ci)
        scan.texts[ci] = text
        consumed.append(text)
        stripped = text
        for m in _HEREDOC_RE.finditer(text):
            body = m.group(2)
            offset = text[:m.start(2)].count("\n")
            try:
                tree = ast.parse(body)
            except (SyntaxError, ValueError):
                continue
            scan.py_units.append((ci, tree, offset))
            # blank the heredoc body in the raw view so its tokens are
            # not double-reported by the raw-text pass
            stripped = (stripped[:m.start(2)]
                        + "\n" * body.count("\n")
                        + stripped[m.end(2):])
        scan.ci_stripped = stripped
    readme = os.path.join(root, "README.md")
    if os.path.isfile(readme):
        consumed.append(_read(readme))
    scan.consumed_text = "\n".join(consumed)
    _SCANS[root] = scan
    return scan


# ---------------------------------------------------------------------------
# consumer-side extraction

def _local_registrations(tree: ast.AST) -> tuple[set[int], set[str]]:
    """Constant-node ids that are first args of registration/emit calls,
    plus the literal names those calls register.  A test registering its
    own metric is not a reference, and once registered the name exists at
    runtime — other mentions of it in the same file are not ghosts."""
    ids: set[int] = set()
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and _call_attr(node) in (_REG_METHODS | _EMIT_NAMES) \
                and node.args:
            for sub in ast.walk(node.args[0]):
                ids.add(id(sub))
                if isinstance(sub, ast.Constant) \
                        and isinstance(sub.value, str):
                    names.add(sub.value)
    return ids, names


def _is_kind_expr(e: ast.AST) -> bool:
    if isinstance(e, ast.Subscript):
        s = e.slice
        return isinstance(s, ast.Constant) and s.value == "kind"
    if isinstance(e, ast.Call) and _call_attr(e) == "get" and e.args:
        a = e.args[0]
        return isinstance(a, ast.Constant) and a.value == "kind"
    if isinstance(e, ast.Name):
        return e.id == "kind"
    if isinstance(e, ast.Attribute):
        return e.attr == "kind"
    return False


def _str_elts(node: ast.AST) -> list[ast.Constant]:
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return [e for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


def _kind_refs(tree: ast.AST) -> list[tuple[str, ast.AST]]:
    """(kind, node) for every structural kind reference in a consumer."""
    refs: list[tuple[str, ast.AST]] = []
    kindset_vars = {"kinds"}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name, value = node.targets[0].id, node.value
            if isinstance(value, (ast.SetComp, ast.ListComp,
                                  ast.GeneratorExp)):
                if any(isinstance(s, ast.Constant) and s.value == "kind"
                       for s in ast.walk(value)):
                    kindset_vars.add(name)
            elif "KINDS" in name.upper():
                refs.extend((e.value, e) for e in _str_elts(value))
    for node in ast.walk(tree):
        if isinstance(node, ast.Compare) and len(node.comparators) == 1:
            left, comp = node.left, node.comparators[0]
            if _is_kind_expr(left):
                if isinstance(comp, ast.Constant) \
                        and isinstance(comp.value, str):
                    refs.append((comp.value, comp))
                refs.extend((e.value, e) for e in _str_elts(comp))
            elif isinstance(node.ops[0], ast.In) \
                    and isinstance(comp, ast.Name) \
                    and comp.id in kindset_vars \
                    and isinstance(left, ast.Constant) \
                    and isinstance(left.value, str):
                refs.append((left.value, left))
        elif isinstance(node, ast.For) and isinstance(node.target, ast.Name):
            elts = _str_elts(node.iter)
            if not elts:
                continue
            loops_into_kinds = any(
                isinstance(c, ast.Compare) and len(c.ops) == 1
                and isinstance(c.ops[0], ast.In)
                and isinstance(c.left, ast.Name)
                and c.left.id == node.target.id
                and isinstance(c.comparators[0], ast.Name)
                and c.comparators[0].id in kindset_vars
                for b in node.body for c in ast.walk(b))
            if loops_into_kinds:
                refs.extend((e.value, e) for e in elts)
    return refs


# ---------------------------------------------------------------------------
# findings

def _display(path: str) -> str:
    rel = os.path.relpath(path)
    if rel.startswith(".."):
        rel = path
    return rel.replace(os.sep, "/")


def _line_of(text: str, lineno: int) -> str:
    lines = text.splitlines()
    if 1 <= lineno <= len(lines):
        return lines[lineno - 1].strip()
    return ""


def _xfinding(scan: _Scan, path: str, line: int, col: int,
              message: str) -> Finding:
    snippet = _line_of(scan.texts.get(path, ""), line)
    return Finding(path=_display(path), line=line, col=col,
                   rule=RULE.name, message=message, snippet=snippet,
                   end_line=line, end_col=max(col + 1, len(snippet)))


def _allowlist(tree: ast.AST) -> set[str]:
    value, _ = _assigned_literal(tree, "CONTRACT_ALLOWLIST")
    if isinstance(value, (tuple, list, set)):
        return {v for v in value if isinstance(v, str)}
    return set()


def _check_metrics(src: SourceFile, ctx: ModuleContext,
                   scan: _Scan) -> list[Finding]:
    allow = _allowlist(ctx.tree)
    families = {n.split("_", 1)[0] for n in scan.metric_lits}
    families |= {p.pattern.split("_", 1)[0] for p in scan.metric_pats
                 if not p.pattern.startswith("[")}
    suffixes = ({n.rsplit("_", 1)[-1] for n in scan.metric_lits}
                | _EXTRA_SUFFIXES)

    def known(tok: str) -> bool:
        return (tok in scan.metric_lits or tok in scan.metric_derived
                or tok in allow
                or any(p.fullmatch(tok) for p in scan.metric_pats))

    def gated(tok: str) -> bool:
        return (tok.split("_", 1)[0] in families
                and tok.rsplit("_", 1)[-1] in suffixes)

    findings: list[Finding] = []
    seen: set[tuple[str, int, str]] = set()

    def ghost(path: str, line: int, col: int, tok: str) -> None:
        if (path, line, tok) in seen:
            return
        seen.add((path, line, tok))
        findings.append(_xfinding(
            scan, path, line, col,
            f"consumer references metric {tok!r} but no code registers "
            f"it: the grep/assert matches nothing and passes or fails "
            f"vacuously — fix the name, register the metric, or add it "
            f"to CONTRACT_ALLOWLIST in the Registry module"))

    # direction A: raw ci.sh tokens (heredocs handled as python below)
    if scan.ci_path is not None:
        for i, raw in enumerate(scan.ci_stripped.splitlines(), start=1):
            for m in _NAME_RE.finditer(raw):
                tok = m.group(0)
                if gated(tok) and not known(tok):
                    ghost(scan.ci_path, i, m.start(), tok)
    # direction A: string constants in consumer python units
    for path, tree, offset in scan.py_units:
        skip, local = _local_registrations(tree)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)) or id(node) in skip:
                continue
            for m in _NAME_RE.finditer(node.value):
                tok = m.group(0)
                if gated(tok) and tok not in local and not known(tok):
                    ghost(path, offset + node.lineno,
                          node.col_offset, tok)
    # direction B: orphan registrations
    for name, (path, line, col) in sorted(scan.metric_lits.items()):
        if name in allow or name in scan.consumed_text:
            continue
        findings.append(_xfinding(
            scan, path, line, col,
            f"metric {name!r} is registered but no ci.sh stage, test, "
            f"report consumer, or README mentions it: either wire a "
            f"gate/doc to it or add it to CONTRACT_ALLOWLIST in the "
            f"Registry module to mark it operator-only"))
    return findings


def _check_kinds(src: SourceFile, ctx: ModuleContext,
                 scan: _Scan) -> list[Finding]:
    allow = _allowlist(ctx.tree)

    def known(kind: str) -> bool:
        return (kind in scan.kind_lits or kind in allow
                or any(p.fullmatch(kind) for p in scan.kind_pats))

    findings: list[Finding] = []
    seen: set[tuple[str, int, str]] = set()
    for path, tree, offset in scan.py_units:
        _, local = _local_registrations(tree)
        for kind, node in _kind_refs(tree):
            if known(kind) or kind in local:
                continue
            line = offset + getattr(node, "lineno", 1)
            if (path, line, kind) in seen:
                continue
            seen.add((path, line, kind))
            findings.append(_xfinding(
                scan, path, line, getattr(node, "col_offset", 0),
                f"consumer matches event kind {kind!r} but no code "
                f"emits it: the gate asserts against a kind that can "
                f"never arrive — fix the name, emit the kind, or add "
                f"it to CONTRACT_ALLOWLIST in the EventBus module"))
    for kind, (path, line, col) in sorted(scan.kind_lits.items()):
        if kind in allow or kind in scan.consumed_text:
            continue
        findings.append(_xfinding(
            scan, path, line, col,
            f"event kind {kind!r} is emitted but no ci.sh gate, test, "
            f"or report consumer matches it: either assert on it "
            f"somewhere or add it to CONTRACT_ALLOWLIST in the "
            f"EventBus module to mark it operator-only"))
    return findings


def _check_wire(src: SourceFile, ctx: ModuleContext, scan: _Scan,
                root: str) -> list[Finding]:
    magic_val, magic_node = _assigned_literal(ctx.tree, "MAGIC")
    if not isinstance(magic_val, bytes) or magic_node is None:
        return []
    version_val, _ = _assigned_literal(ctx.tree, "VERSION")
    fmt = None
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and _call_attr(node) == "Struct" \
                and node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            fmt = node.args[0].value
            break
    try:
        size = struct.calcsize(fmt) if fmt else None
    except struct.error:
        size = None

    goldens: list[tuple[str, int, int, str, bytes]] = []
    tests = os.path.join(root, "tests")
    if os.path.isdir(tests):
        for path in iter_py_files([tests]):
            tree = _parse(path)
            if tree is None:
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and "GOLDEN" in node.targets[0].id \
                        and isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, bytes):
                    scan.texts.setdefault(path, _read(path))
                    goldens.append((os.path.abspath(path), node.lineno,
                                    node.col_offset, node.targets[0].id,
                                    node.value.value))
    findings: list[Finding] = []
    if not goldens:
        findings.append(src.finding(
            magic_node, RULE.name,
            f"wire frame constants (MAGIC={magic_val!r}) have no "
            f"golden-bytes witness: no tests/** assignment pins the "
            f"exact frame prefix as a bytes literal (a *GOLDEN* name), "
            f"so a format edit ships a protocol break with green tests "
            f"— pin the prefix bytes in a test"))
        return findings
    for path, line, col, name, value in goldens:
        errs = []
        if size is not None and len(value) != size:
            errs.append(f"length {len(value)} != struct prefix size "
                        f"{size} ({fmt!r})")
        if not value.startswith(magic_val):
            errs.append(f"does not start with MAGIC {magic_val!r}")
        elif isinstance(version_val, int) and len(value) > len(magic_val) \
                and value[len(magic_val)] != version_val:
            errs.append(f"version byte {value[len(magic_val)]} != "
                        f"VERSION {version_val}")
        if errs:
            scan.texts.setdefault(path, _read(path))
            findings.append(_xfinding(
                scan, path, line, col,
                f"golden wire bytes {name} disagree with the frame "
                f"constants: {'; '.join(errs)} — the pinned prefix and "
                f"the wire module must change together"))
    return findings


# ---------------------------------------------------------------------------
# anchors

def _has_class(tree: ast.AST, name: str, methods: set[str]) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == name:
            defined = {n.name for n in node.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))}
            if methods <= defined:
                return True
    return False


def _is_wire_anchor(tree: ast.AST) -> bool:
    magic, _ = _assigned_literal(tree, "MAGIC")
    if not isinstance(magic, bytes):
        return False
    return any(isinstance(n, ast.Call) and _call_attr(n) == "Struct"
               for n in ast.walk(tree))


def _check(src: SourceFile, ctx: ModuleContext) -> list[Finding]:
    is_metrics = _has_class(ctx.tree, "Registry", _REG_METHODS)
    is_events = _has_class(ctx.tree, "EventBus", {"emit"})
    is_wire = _is_wire_anchor(ctx.tree)
    if not (is_metrics or is_events or is_wire):
        return []
    root = _find_root(src.path)
    scan = _scan(root)
    findings: list[Finding] = []
    if is_metrics:
        findings.extend(_check_metrics(src, ctx, scan))
    if is_events:
        findings.extend(_check_kinds(src, ctx, scan))
    if is_wire:
        findings.extend(_check_wire(src, ctx, scan, root))
    return findings


RULE = Rule(
    name="contract-drift",
    summary="metric/kind/wire names out of lockstep between emitters "
            "and their ci.sh, test, and report consumers",
    check=_check,
    cross_file=True)
