"""hung-future: unbounded waits on futures/queues in threaded modules.

The failure class PR 16's drain contract eliminates: a caller parks on
``future.result()`` (no timeout) while the thread that would resolve it
is gone — the dispatcher died, the server drained, the engine was
ejected. Nothing crashes; the request path just stops, and on a CI rig
that reads as a 600s timeout with no stack. ``blocking-under-lock``
catches the two-party deadlock variant (wait while HOLDING a lock);
this rule catches the one-party variant that needs no lock at all.

Fires in modules that visibly do threading (``threading`` /
``concurrent.futures`` imports — the same convention gate the
concurrency model arms its dispatcher-loop roots with) on:

- ``<future>.result()`` with no arguments and no ``timeout=`` — wait
  bounded by nothing but the process's lifetime;
- ``<queue>.get(...)`` on a tracked queue object without ``timeout=``
  (and not ``block=False``; ``get_nowait`` never matches).

Sites already inside a held lock region are skipped — those are
``blocking-under-lock`` findings (one finding per defect).

The sanctioned shapes: ``result(timeout=...)`` / ``get(timeout=...)``
(bounded — a stuck wait becomes a loud TimeoutError), or hand the
future to an event loop via ``asyncio.wrap_future`` and ``await`` it,
as ``serve.frontend`` does on the wire request path.
"""
from __future__ import annotations

import ast

from . import Rule
from ..concurrency import _CONVENTION_GATE, model_for
from ..engine import Finding, ModuleContext, SourceFile


def _kw(call: ast.Call, name: str) -> ast.expr | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _check(src: SourceFile, ctx: ModuleContext) -> list[Finding]:
    if not any(a in _CONVENTION_GATE or a.startswith("concurrent.")
               or a.startswith("threading")
               for a in ctx.aliases.values()):
        return []
    model = model_for(ctx)
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        attr = node.func.attr
        if attr not in ("get", "result"):
            continue
        if model.locks_at(node):
            continue                  # blocking-under-lock's finding
        if attr == "get":
            tok = model.value_token(node.func.value, node)
            if tok is None or tok not in model.queue_tokens:
                continue
            block = _kw(node, "block")
            if isinstance(block, ast.Constant) and block.value is False:
                continue
            if _kw(node, "timeout") is not None:
                continue              # bounded wait
            what = "queue .get() with no timeout"
        else:
            if node.args or node.keywords:
                continue              # result(timeout=...) is bounded
            what = "future .result() with no timeout"
        findings.append(src.finding(
            node, RULE.name,
            f"{what} in a threaded module: if the resolving thread is "
            f"gone (dispatcher died, server drained, engine ejected) "
            f"this waits forever with no stack — bound it with "
            f"timeout=..., or await it via asyncio.wrap_future on an "
            f"event loop"))
    return findings


RULE = Rule(
    name="hung-future",
    summary="unbounded future.result() or queue.get() in a threaded "
            "module (hang with no stack if the resolver dies)",
    check=_check)
