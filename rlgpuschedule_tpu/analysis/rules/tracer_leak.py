"""tracer-leak: Python control flow on traced values.

``if jnp.any(mask):`` inside a jitted function is not a device branch —
it concretizes the tracer (error) or, on a concrete capture, freezes one
branch into the compiled program forever. The device-side forms are
``jnp.where`` / ``lax.cond`` / ``lax.select``. The rule fires on
``if`` / ``while`` / ``assert`` / conditional-expression tests inside
traced regions whose test expression contains a jax/jnp/np call or an
array-reduction method call (``.any()``, ``.all()``, ``.sum()``, ...) —
deliberately conservative: ``if config.bf16_update:`` (static Python
config) is the dominant legitimate branch idiom in this codebase and
never matches.
"""
from __future__ import annotations

import ast

from . import Rule
from ..engine import Finding, ModuleContext, SourceFile

_REDUCTIONS = {"any", "all", "sum", "min", "max", "mean", "item"}
_TRACED_PREFIXES = ("jax.", "numpy.")


def _test_is_traced(ctx: ModuleContext, test: ast.AST) -> bool:
    for node in ast.walk(test):
        if not isinstance(node, ast.Call):
            continue
        name = ctx.resolve_call(node)
        if name and (name.startswith(_TRACED_PREFIXES) or name == "jax"):
            return True
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _REDUCTIONS and not node.args:
            return True
    return False


def _check(src: SourceFile, ctx: ModuleContext) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        test = None
        kind = None
        if isinstance(node, ast.If):
            test, kind = node.test, "if"
        elif isinstance(node, ast.While):
            test, kind = node.test, "while"
        elif isinstance(node, ast.Assert):
            test, kind = node.test, "assert"
        elif isinstance(node, ast.IfExp):
            test, kind = node.test, "conditional expression"
        if test is None or not ctx.in_traced_region(node):
            continue
        if _test_is_traced(ctx, test):
            findings.append(src.finding(
                node, RULE.name,
                f"Python {kind} on a traced expression inside a "
                f"trace-reachable function: this concretizes the tracer "
                f"(error) or freezes one branch at trace time; use "
                f"jnp.where / lax.cond / lax.select"))
    return findings


RULE = Rule(
    name="tracer-leak",
    summary="Python if/while/assert on traced expressions in jitted code",
    check=_check)
