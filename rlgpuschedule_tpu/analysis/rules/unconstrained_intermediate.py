"""unconstrained-intermediate: mesh-era batch building with no layout pin.

Under a mesh, GSPMD picks the layout of every intermediate the program
does not pin. For the big batch-shaped builders — ``jnp.stack`` /
``concatenate`` / ``tile`` / ``repeat`` / ``broadcast_to`` — the
unconstrained choice is frequently full replication (or a gather back
to one shard), which silently multiplies memory by the mesh size and
inserts all-to-all traffic right where the program is widest. The
partition-rule engine's discipline (``parallel.sharding``) is that
trajectory-shaped intermediates get an explicit
``with_sharding_constraint`` (or the repo's ``constrain`` /
``constrain_tree`` wrappers) naming the data axis.

It fires INSIDE traced regions, and only in modules with mesh evidence
— a ``Mesh`` / ``make_mesh`` / ``make_unified_mesh`` / ``unified_mesh``
/ ``NamedSharding`` construction, or a ``jax.jit`` call passing
``in_shardings``/``out_shardings`` — so single-device code (tests,
host utilities) never pays the rule. A builder result that flows
through a constrainer in the same function, or is built directly
inside a constrainer call, is pinned and never flagged: the fix for a
finding is also its silencer.

A deliberately replicated intermediate is a one-line suppression with
the reason inline::

    table = jnp.tile(base, (n, 1))  # jsan: disable=unconstrained-intermediate -- small lookup table, replication intended
"""
from __future__ import annotations

import ast

from . import Rule
from ..engine import Finding, ModuleContext, SourceFile

# the module-level evidence that a mesh governs this code at all:
# terminal names of mesh/sharding constructors (terminal so both
# `jax.sharding.Mesh` and the repo's `parallel.mesh.make_unified_mesh`
# count, however they were imported)
_MESH_TERMINALS = {"Mesh", "make_mesh", "make_unified_mesh",
                   "unified_mesh", "NamedSharding"}
_JIT_CALLS = {"jax.jit", "jax.pmap", "equinox.filter_jit"}
_SHARDING_KWARGS = {"in_shardings", "out_shardings"}

# batch-shaped builders whose unconstrained GSPMD layout is the hazard
_BUILDERS = {"jax.numpy.stack", "jax.numpy.concatenate",
             "jax.numpy.tile", "jax.numpy.repeat",
             "jax.numpy.broadcast_to"}

# anything that pins a layout (terminal names: jax.lax.
# with_sharding_constraint and the repo's parallel.sharding wrappers)
_CONSTRAINERS = {"with_sharding_constraint", "constrain",
                 "constrain_tree"}


def _terminal_of(name: "str | None") -> "str | None":
    return name.split(".")[-1] if name else None


def _has_mesh_evidence(ctx: ModuleContext) -> bool:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = ctx.resolve_call(node)
        if _terminal_of(name) in _MESH_TERMINALS:
            return True
        if name in _JIT_CALLS and any(kw.arg in _SHARDING_KWARGS
                                      for kw in node.keywords):
            return True
    return False


def _root_name(node: ast.AST) -> "str | None":
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _target_names(target: ast.AST) -> list[str]:
    if isinstance(target, (ast.Tuple, ast.List)):
        out: list[str] = []
        for elt in target.elts:
            out.extend(_target_names(elt))
        return out
    if isinstance(target, ast.Starred):
        return _target_names(target.value)
    name = _root_name(target)
    return [name] if name else []


def _constrained_names(fn: ast.AST, ctx: ModuleContext) -> set[str]:
    """Names that pass through a constrainer anywhere in ``fn`` —
    line-order is deliberately ignored (the reassignment idiom
    ``x = constrain(x, ...)`` and pin-at-the-end both count)."""
    pinned: set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        if _terminal_of(ctx.resolve_call(node)) not in _CONSTRAINERS:
            continue
        for arg in node.args:
            name = _root_name(arg)
            if name:
                pinned.add(name)
    return pinned


def _inside_constrainer(ctx: ModuleContext, node: ast.AST) -> bool:
    for parent in ctx.ancestors(node):
        if isinstance(parent, ast.Call) \
                and _terminal_of(ctx.resolve_call(parent)) \
                in _CONSTRAINERS:
            return True
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
            return False
    return False


def _check(src: SourceFile, ctx: ModuleContext) -> list[Finding]:
    if not _has_mesh_evidence(ctx):
        return []
    findings: list[Finding] = []
    pinned_by_fn: dict[ast.AST, set[str]] = {}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assign) \
                or not isinstance(node.value, ast.Call) \
                or not ctx.in_traced_region(node):
            continue
        call = node.value
        name = ctx.resolve_call(call)
        if name not in _BUILDERS:
            continue
        if _inside_constrainer(ctx, call):
            continue
        fn = ctx.enclosing_function(node)
        if fn not in pinned_by_fn:
            pinned_by_fn[fn] = _constrained_names(fn, ctx)
        targets = [t for tgt in node.targets
                   for t in _target_names(tgt)]
        if targets and all(t in pinned_by_fn[fn] for t in targets):
            continue
        findings.append(src.finding(
            node, RULE.name,
            f"{name}() builds a batch-shaped intermediate in traced "
            f"code under a mesh without a sharding constraint — GSPMD "
            f"is free to replicate it (memory x mesh size) or gather "
            f"it to one shard; pin it with with_sharding_constraint / "
            f"parallel.sharding.constrain, or suppress with the reason "
            f"replication is intended"))
    return findings


RULE = Rule(
    name="unconstrained-intermediate",
    summary="mesh-traced batch builders with no sharding constraint",
    check=_check)
