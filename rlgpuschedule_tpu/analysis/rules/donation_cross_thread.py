"""donation-cross-thread: one donated program, two executing threads.

The PR-8 postmortem's second crash class: a jitted program with
``donate_argnums`` frees its input buffers on dispatch. Two threads
executing the SAME donated program can race the donation — the second
dispatch consumes buffers the first already invalidated, which on
XLA:CPU corrupts the heap (observed as int32 ``-1`` poison in
checkpoint arrays and hard interpreter crashes, never a clean Python
error). Locking narrows but does not close the window across backends,
so the contract is structural: ONE executing thread per donated
program. The async engine splits its work into ``self._rollout``
(actor thread) and ``self._learn`` (learner/main thread) for exactly
this reason.

Fires once per tracked donated program (``jax.jit(...,
donate_argnums=...)`` and its ``.lower().compile()`` chains) that is
executed from two or more distinct entry points — thread roots, with
the main thread counting as one entry when construction-path code also
calls it.
"""
from __future__ import annotations

import ast

from . import Rule
from ..concurrency import MAIN, model_for
from ..engine import Finding, ModuleContext, SourceFile


def _check(src: SourceFile, ctx: ModuleContext) -> list[Finding]:
    model = model_for(ctx)
    if not model.thread_roots or not model.donated:
        return []
    exec_roots: dict[tuple, set] = {}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        tok = model.value_token(node.func, node)
        if tok is None or tok not in model.donated:
            continue
        roots = model.roots_reaching(node)
        exec_roots.setdefault(tok, set()).update(roots or {MAIN})
    findings: list[Finding] = []
    for tok, roots in sorted(exec_roots.items(),
                             key=lambda kv: model.donated[kv[0]].lineno):
        if len(roots) < 2:
            continue
        labels = ", ".join(sorted(
            model.thread_roots.get(r, "the main thread") for r in roots))
        findings.append(src.finding(
            model.donated[tok], RULE.name,
            f"donated program {model.lock_name(tok)} is executed from "
            f"{len(roots)} entry points ({labels}): concurrent dispatch "
            f"races the buffer donation and corrupts the heap (PR-8 "
            f"class) — give each thread its own compiled program or "
            f"drop donate_argnums"))
    return findings


RULE = Rule(
    name="donation-cross-thread",
    summary="a donated (donate_argnums) program executable from >= 2 "
            "thread entry points",
    check=_check)
