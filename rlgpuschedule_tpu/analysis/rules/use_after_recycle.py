"""use-after-recycle: reading a view after its storage was reclaimed.

The arena pump's contract is strictly ordered: take a block, build slab
views, dispatch, scatter, resolve futures, THEN ``ring.recycle(blk)``.
Recycling hands the slab to the next batch's memcpys — any read of the
block (or a view derived from it) after that point races the producer
and returns torn or foreign rows. The same shape exists on the wire
path: ``np.frombuffer(buf)`` views die the moment the next
``recv_into(buf)`` / ``readinto(buf)`` lands in the same buffer object
(rebinding ``buf = sock.recv(n)`` is safe — the old bytes object stays
alive under the old view; in-place reuse is not).

Fires on every use the lifetime model (:mod:`..lifetime`) proves is
reachable after the kill point on the same control-flow path:

- a strong view (provable alias of the block / buffer): ANY use after
  the kill — subscript, call argument, return, iteration;
- a weak value (an opaque helper's result seeded by the block, e.g. a
  row count): only a data dereference (subscript/attribute) fires, so
  returning a count after the recycle stays clean.

Control flow is respected: a recycle inside an ``except`` handler that
re-raises does not poison the happy path after the ``try``. The fix is
to move the read before the kill, or copy what must survive it.
"""
from __future__ import annotations

from . import Rule
from ..engine import Finding, ModuleContext, SourceFile
from ..lifetime import model_for


def _check(src: SourceFile, ctx: ModuleContext) -> list[Finding]:
    model = model_for(ctx)
    findings: list[Finding] = []
    for use in model.dead_uses:
        kill_line = getattr(use.kill, "lineno", 0)
        findings.append(src.finding(
            use.node, RULE.name,
            f"use of {use.view.label} view after its storage was "
            f"reclaimed by `{use.kill_label}` (line {kill_line}): the "
            f"slab/buffer now belongs to the next batch, so this read "
            f"returns torn or foreign data — move the read before the "
            f"recycle, or copy what must survive it"))
    return findings


RULE = Rule(
    name="use-after-recycle",
    summary="reads of slab/frombuffer views reachable after their "
            "block recycle / buffer reuse point",
    check=_check)
