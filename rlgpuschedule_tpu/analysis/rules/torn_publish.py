"""torn-publish: handing a live slab view to another thread.

The arena block has exactly one sanctioned cross-thread handoff: the
submit path memcpys the request INTO the slab outside the lock, then
publishes the row with a GIL-atomic ``published[i] = True`` flag the
pump checks before sealing (``serve/batching.py``). Anything else that
moves a slab/frombuffer view across a thread boundary — a ``.put()``
onto a queue the dispatcher drains, an executor ``submit`` closing over
the view, a ``Thread(target=...)`` capturing it — publishes memory
whose lifetime the receiving thread cannot see: the sender's frame
recycles the block on its own schedule, and the reader observes half of
batch N and half of batch N+1 (a torn read), or a fully foreign batch.

Fires, composing the lifetime model with the concurrency model's
thread roots, when a module that visibly runs threads publishes a
strong view through:

- ``queue.put(view)`` / ``put_nowait(view)`` (directly or inside a
  tuple/list payload);
- ``executor.submit(fn_or_lambda_closing_over_view)``;
- a ``Thread`` target closure capturing the view.

Modules with no thread roots never fire — a single-threaded pipeline
putting views on a local work list is lifetime-safe. The fix is to
publish a copy (``view.copy()`` ends the taint chain) or restructure so
the consumer reads the slab under the arena's published-flag protocol.
"""
from __future__ import annotations

from . import Rule
from ..engine import Finding, ModuleContext, SourceFile
from ..lifetime import model_for


def _check(src: SourceFile, ctx: ModuleContext) -> list[Finding]:
    model = model_for(ctx)
    if not model.cmodel.thread_roots:
        return []
    findings: list[Finding] = []
    for pub in model.publishes:
        findings.append(src.finding(
            pub.node, RULE.name,
            f"{pub.view.label} view published to another thread via "
            f"{pub.channel}: the receiver cannot see the buffer's "
            f"recycle schedule, so it reads torn or foreign batches — "
            f"publish a copy, or hand off through the arena's "
            f"published-flag protocol"))
    return findings


RULE = Rule(
    name="torn-publish",
    summary="slab/frombuffer views handed across threads via queues, "
            "executors, or Thread closures outside the arena protocol",
    check=_check)
