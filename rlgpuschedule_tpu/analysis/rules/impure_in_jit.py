"""impure-in-jit: side effects and host entropy inside traced code.

A jitted function body runs ONCE, at trace time. ``time.time()`` stamps
the trace, not the step; ``np.random.*`` draws one host sample and bakes
it into the compiled program as a constant (every subsequent call reuses
it — the classic silently-wrong rollout); ``print`` fires at trace time
only and then never again, which reads as "the code stopped running".
Use ``jax.random`` with threaded keys for randomness,
``jax.debug.print`` for tracing output, and host-side wall-clock timing
around the dispatch (``utils.profiling``), never inside it.
"""
from __future__ import annotations

import ast

from . import Rule
from ..engine import Finding, ModuleContext, SourceFile

_IMPURE_CALLS = {
    "time.time": "stamps trace time, not step time — time the dispatch "
                 "from the host instead",
    "time.perf_counter": "stamps trace time, not step time — time the "
                         "dispatch from the host instead",
    "time.monotonic": "stamps trace time, not step time",
    "print": "fires once at trace time and never again; use "
             "jax.debug.print",
    "open": "host I/O inside a traced function runs at trace time only",
    "input": "host I/O inside a traced function runs at trace time only",
}
_NP_RANDOM_PREFIX = "numpy.random."
_PY_RANDOM_PREFIX = "random."


def _check(src: SourceFile, ctx: ModuleContext) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not ctx.in_traced_region(node):
            continue
        name = ctx.resolve_call(node)
        if name is None:
            continue
        if name in _IMPURE_CALLS:
            findings.append(src.finding(
                node, RULE.name,
                f"{name}() in a trace-reachable function: "
                f"{_IMPURE_CALLS[name]}"))
        elif name.startswith(_NP_RANDOM_PREFIX) \
                or name.startswith(_PY_RANDOM_PREFIX):
            findings.append(src.finding(
                node, RULE.name,
                f"{name}() in a trace-reachable function draws ONE host "
                f"sample at trace time and bakes it into the compiled "
                f"program as a constant; thread a jax.random key instead"))
    return findings


RULE = Rule(
    name="impure-in-jit",
    summary="time/np.random/print/IO inside trace-reachable code",
    check=_check)
