"""donation-discipline: a jitted state-threading callable must donate.

The contract comes from ``algos/update.py``'s ``make_update_step``: a
function of the shape ``f(state, ...) -> (state', ...)`` that is jitted
*without* ``donate_argnums`` holds two live copies of the parameter +
optimizer buffers on every call — on a TPU that is the difference
between fitting the swept batch geometry in HBM and not. The rule fires
on ``jax.jit(f, ...)`` calls (and ``@jax.jit`` decorations) where ``f``
is resolvable in the module and *threads state*: some ``return``
statement returns a tuple whose first element is the function's first
parameter (rebinding the name along the way counts — that is exactly
the threading idiom).

Deliberate non-donation (e.g. the caller keeps the old state for a
rollback path) is a one-line suppression with the reason inline:
``jax.jit(step)  # jsan: disable=donation-discipline -- rollback keeps old state``
"""
from __future__ import annotations

import ast

from . import Rule
from ..engine import Finding, ModuleContext, SourceFile

_DONATE_KW = {"donate_argnums", "donate_argnames"}


def _first_param(fn: ast.AST) -> str | None:
    args = fn.args
    pos = args.posonlyargs + args.args
    if not pos:
        return None
    first = pos[0].arg
    # a method's self/cls is never the threaded state
    if first in ("self", "cls") and len(pos) > 1:
        return pos[1].arg
    return first if first not in ("self", "cls") else None


def threads_state(fn: ast.AST) -> bool:
    """True when some return statement's tuple leads with the function's
    first parameter name (the ``state, ... -> state', ...`` idiom)."""
    first = _first_param(fn)
    if first is None:
        return False
    if isinstance(fn, ast.Lambda):
        body = fn.body
        return (isinstance(body, ast.Tuple) and body.elts
                and isinstance(body.elts[0], ast.Name)
                and body.elts[0].id == first)
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not fn:
            continue  # nested scopes judged on their own
        if isinstance(node, ast.Return) and isinstance(node.value, ast.Tuple):
            elts = node.value.elts
            if elts and isinstance(elts[0], ast.Name) and elts[0].id == first:
                return True
    return False


def _check(src: SourceFile, ctx: ModuleContext) -> list[Finding]:
    findings: list[Finding] = []
    msg = ("jitted state-threading callable {name!r} does not donate its "
           "state: pass donate_argnums=(0,) (make_update_step contract) "
           "or suppress with the reason the old buffers must stay live")
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and ctx.resolve_call(node) == "jax.jit":
            if any(kw.arg in _DONATE_KW for kw in node.keywords):
                continue
            if not node.args:
                continue
            target = node.args[0]
            fns: list = []
            if isinstance(target, ast.Name):
                fns = ctx.functions_by_name.get(target.id, [])
                label = target.id
            elif isinstance(target, ast.Lambda):
                fns, label = [target], "<lambda>"
            if any(threads_state(f) for f in fns):
                findings.append(src.finding(node, RULE.name,
                                            msg.format(name=label)))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if (ctx._decorator_name(dec) == "jax.jit"
                        and not (isinstance(dec, ast.Call)
                                 and any(kw.arg in _DONATE_KW
                                         for kw in dec.keywords))
                        and threads_state(node)):
                    findings.append(src.finding(dec, RULE.name,
                                                msg.format(name=node.name)))
    return findings


RULE = Rule(
    name="donation-discipline",
    summary="jitted state-threading callables must pass donate_argnums",
    check=_check)
