"""sync-in-loop: per-iteration host materialization in a dispatch loop.

The driver loops' whole throughput contract — sync ``Experiment.run``
and doubly the async actor/learner engine — is keeping the host AHEAD
of the device: dispatch the next step, materialize scalars only at a
log cadence, in ONE batched ``jax.device_get``. A ``.item()`` /
``float()`` / ``np.asarray()`` on a device value INSIDE the driver loop
re-serializes every iteration: the host blocks on the device before it
can dispatch again, and the dispatch pipeline (or the actor/learner
overlap) is gone. This is the host-side complement of ``host-sync``,
which only fires inside traced regions.

It fires in NON-traced code, inside a ``for``/``while`` body, on values
whose device provenance is locally evident: a name assigned from
calling a ``jax.jit(...)``/``jax.pmap(...)`` result or a ``make_*``
factory product (the repo's step-function convention — the factories
return callables that are jitted at the call site). Values pulled
through ``jax.device_get`` are host copies — the blessed batched
materialization — and are never flagged, so the fix for a finding is
also its silencer: batch the pulls into one ``device_get`` per cadence.

A deliberate per-iteration sync (e.g. a convergence check that gates
the loop) is a one-line suppression with the reason inline::

    loss = float(m["loss"])  # jsan: disable=sync-in-loop -- stop criterion needs the scalar
"""
from __future__ import annotations

import ast

from . import Rule
from ..engine import Finding, ModuleContext, SourceFile

# assigning the result of one of these produces a dispatch callable
_JIT_CALLS = {"jax.jit", "jax.pmap", "equinox.filter_jit"}

_SYNC_METHODS = {"item", "tolist"}
_SYNC_CALLS = {"numpy.asarray", "numpy.array", "numpy.float32",
               "numpy.float64", "numpy.int32", "numpy.int64"}
_CAST_BUILTINS = {"float", "int", "bool"}


def _terminal(node: ast.AST) -> str | None:
    """The last identifier of a Name/Attribute chain (``self._step`` ->
    ``_step``) — dispatch callables are tracked by terminal name so the
    ``self._rollout = jax.jit(...)`` memoization idiom still counts."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _target_names(target: ast.AST) -> list[str]:
    if isinstance(target, (ast.Tuple, ast.List)):
        out: list[str] = []
        for elt in target.elts:
            out.extend(_target_names(elt))
        return out
    if isinstance(target, ast.Starred):
        return _target_names(target.value)
    name = _terminal(target)
    return [name] if name else []


def _is_factory_call(ctx: ModuleContext, call: ast.Call) -> bool:
    name = ctx.resolve_call(call)
    return name is not None and name.split(".")[-1].startswith("make_")


def _collect(ctx: ModuleContext):
    """(dispatch names, device-valued names, host-copy names) from the
    module's assignments. One flat namespace per module — line-order and
    scope are deliberately ignored (precision over soundness; reusing a
    name across roles is its own smell)."""
    dispatch: set[str] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assign) \
                or not isinstance(node.value, ast.Call):
            continue
        call = node.value
        if ctx.resolve_call(call) in _JIT_CALLS \
                or _is_factory_call(ctx, call):
            for t in node.targets:
                dispatch.update(_target_names(t))
    device: set[str] = set()
    host: set[str] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assign) \
                or not isinstance(node.value, ast.Call):
            continue
        call = node.value
        names = [n for t in node.targets for n in _target_names(t)]
        if ctx.resolve_call(call) == "jax.device_get":
            host.update(names)
        elif _terminal(call.func) in dispatch:
            device.update(names)
    return dispatch, device, host


def _root(node: ast.AST) -> ast.AST:
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node


def _roots_at_device(node: ast.AST, device: set[str],
                     host: set[str]) -> bool:
    root = _root(node)
    return (isinstance(root, ast.Name) and root.id in device
            and root.id not in host)


def _in_loop(ctx: ModuleContext, node: ast.AST) -> bool:
    for parent in ctx.ancestors(node):
        if isinstance(parent, (ast.For, ast.While)):
            return True
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
            return False
    return False


def _check(src: SourceFile, ctx: ModuleContext) -> list[Finding]:
    _, device, host = _collect(ctx)
    if not device:
        return []
    findings: list[Finding] = []
    fix = ("batch the pulls into one jax.device_get at a log cadence, "
           "or suppress with the reason the loop needs the scalar")
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not _in_loop(ctx, node) \
                or ctx.in_traced_region(node):
            continue
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _SYNC_METHODS and not node.args \
                and _roots_at_device(node.func.value, device, host):
            findings.append(src.finding(
                node, RULE.name,
                f".{node.func.attr}() on a dispatch result inside the "
                f"driver loop blocks the host every iteration; {fix}"))
            continue
        name = ctx.resolve_call(node)
        if len(node.args) == 1 and (name in _SYNC_CALLS
                                    or name in _CAST_BUILTINS) \
                and _roots_at_device(node.args[0], device, host):
            findings.append(src.finding(
                node, RULE.name,
                f"{name}() materializes a dispatch result inside the "
                f"driver loop — a host<->device sync per iteration that "
                f"serializes the pipeline; {fix}"))
    return findings


RULE = Rule(
    name="sync-in-loop",
    summary="per-iteration host sync on dispatch results in driver loops",
    check=_check)
