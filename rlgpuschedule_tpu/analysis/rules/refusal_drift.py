"""refusal-drift: the mode-refusal table and the CLI guards, in lockstep.

``configs.MODE_REFUSALS`` is THE pairwise mode-combination contract
(one table, one error format — PR 9), and the ROADMAP's refusal-matrix
burn-down depends on it describing what the code actually refuses.
Nothing enforced that until now: a row nobody guards is dead weight
that reads as a live constraint, and a CLI that exposes two refusable
mode flags without calling ``validate_mode_combination`` silently runs
(or silently ignores) a combination the table says must refuse — both
drift classes existed in this tree when the rule first ran (the
shard_map rows had no guard; ``evaluate`` and ``bench.py`` exposed
refusable pairs unguarded).

Both directions are checked, each finding landing in the file whose
edit fixes it:

**Analyzing the defining module** (the file assigning ``MODE_REFUSALS``
and ``MODE_FLAGS``): every refusal row ``(a, b, why)`` must have at
least one guard — a ``validate_mode_combination({...})`` call in the
package tree around it whose literal dict keys cover both ``a`` and
``b``. A row with no such guard fires on the row.

**Analyzing a CLI/caller module** (locating the defining ``configs.py``
next to it — same directory, a parent, or an immediate subdirectory):

- every literal key passed to ``validate_mode_combination`` must be a
  ``MODE_FLAGS`` mode (a typo'd key would KeyError at runtime — flag it
  at lint time);
- ``raise ModeCombinationError(...)`` outside the defining module is an
  ad-hoc refusal that bypasses the table's single error format;
- a module that ``add_argument``-exposes BOTH flags of a refused pair
  (matching ``MODE_FLAGS`` values' leading ``--token``) must have a
  guard covering that pair — otherwise the refused combination parses
  and runs unchecked.

Everything is literal-extracted (``ast.literal_eval`` on the table,
dict-literal keys on the guards) — no imports, keeping the lint stage's
no-JAX contract.
"""
from __future__ import annotations

import ast
import os

from . import Rule
from ..engine import Finding, ModuleContext, SourceFile, iter_py_files

_GUARD = "validate_mode_combination"
_ERROR = "ModeCombinationError"
_TABLE = "MODE_REFUSALS"
_FLAGS = "MODE_FLAGS"


def _assigned_literal(tree: ast.AST, name: str):
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            if any(isinstance(t, ast.Name) and t.id == name
                   for t in node.targets):
                try:
                    return ast.literal_eval(node.value), node.value
                except ValueError:
                    return None, None
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if isinstance(node.target, ast.Name) and node.target.id == name:
                try:
                    return ast.literal_eval(node.value), node.value
                except ValueError:
                    return None, None
    return None, None


def _call_name(func: ast.AST) -> str | None:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _guard_key_sets(tree: ast.AST) -> list[tuple[ast.Call, set[str]]]:
    """Every validate_mode_combination call with its literal dict keys."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _call_name(node.func) == _GUARD \
                and node.args and isinstance(node.args[0], ast.Dict):
            keys = {k.value for k in node.args[0].keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
            out.append((node, keys))
    return out


def _defines_table(tree: ast.AST) -> bool:
    return _assigned_literal(tree, _TABLE)[0] is not None


def _parse_sibling(path: str) -> ast.AST | None:
    try:
        with open(path, encoding="utf-8") as f:
            return ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError, ValueError):
        return None


def _find_configs(path: str) -> ast.AST | None:
    """The defining module near ``path``: ``configs.py`` in the file's
    directory, up to two parents, or an immediate subdirectory (covers
    package modules, ``serve/__main__.py``, and repo-root ``bench.py``)."""
    d = os.path.dirname(os.path.abspath(path))
    candidates = [os.path.join(d, "configs.py"),
                  os.path.join(d, os.pardir, "configs.py"),
                  os.path.join(d, os.pardir, os.pardir, "configs.py")]
    try:
        candidates += sorted(
            os.path.join(d, sub, "configs.py")
            for sub in os.listdir(d)
            if os.path.isdir(os.path.join(d, sub)))
    except OSError:
        pass
    for cand in candidates:
        if os.path.isfile(cand):
            tree = _parse_sibling(cand)
            if tree is not None and _defines_table(tree):
                return tree
    return None


def _check_defining_module(src: SourceFile,
                           ctx: ModuleContext) -> list[Finding]:
    refusals, table_node = _assigned_literal(ctx.tree, _TABLE)
    if not isinstance(refusals, tuple) or table_node is None:
        return []
    # collect every guard's key set from the package tree around the
    # defining module (the defining module itself contributes none —
    # its only mention of the guard is the def)
    own = os.path.abspath(src.path)
    key_sets: list[set[str]] = []
    for path in iter_py_files([os.path.dirname(own) or "."]):
        if os.path.abspath(path) == own:
            continue
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        except OSError:
            continue
        if _GUARD not in text:
            continue
        tree = _parse_sibling(path)
        if tree is not None:
            key_sets.extend(keys for _, keys in _guard_key_sets(tree))
    findings: list[Finding] = []
    rows = [elt for elt in table_node.elts
            if isinstance(elt, ast.Tuple)] \
        if isinstance(table_node, ast.Tuple) else []
    for row in rows:
        lits = [e.value for e in row.elts[:2]
                if isinstance(e, ast.Constant)]
        if len(lits) != 2:
            continue
        a, b = lits
        if not any({a, b} <= keys for keys in key_sets):
            findings.append(src.finding(
                row, RULE.name,
                f"refusal row ({a!r}, {b!r}) has no reachable guard: no "
                f"{_GUARD} call in the package covers both modes, so "
                f"the table claims a refusal the code never enforces — "
                f"add the pair to a CLI/entry-point guard or delete "
                f"the row"))
    return findings


def _exposed_flags(tree: ast.AST) -> dict[str, ast.Call]:
    """--flag -> its add_argument call, for every literal option."""
    out: dict[str, ast.Call] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and _call_name(node.func) == "add_argument" \
                and node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str) \
                and node.args[0].value.startswith("--"):
            out.setdefault(node.args[0].value, node)
    return out


def _check_caller_module(src: SourceFile,
                         ctx: ModuleContext) -> list[Finding]:
    findings: list[Finding] = []
    guards = _guard_key_sets(ctx.tree)
    defines_error = any(isinstance(n, ast.ClassDef) and n.name == _ERROR
                        for n in ast.walk(ctx.tree))
    # ad-hoc refusals bypass the table's single error format
    if not defines_error:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Raise) \
                    and isinstance(node.exc, ast.Call) \
                    and _call_name(node.exc.func) == _ERROR:
                findings.append(src.finding(
                    node, RULE.name,
                    f"ad-hoc raise of {_ERROR} outside the defining "
                    f"module: refusals must come from {_GUARD} so the "
                    f"table stays the single source of truth — add a "
                    f"row to {_TABLE} and call the guard"))
    flags_exposed = _exposed_flags(ctx.tree)
    if not guards and not flags_exposed:
        return findings
    configs = _find_configs(src.path)
    if configs is None:
        return findings
    mode_flags, _ = _assigned_literal(configs, _FLAGS)
    refusals, _ = _assigned_literal(configs, _TABLE)
    if not isinstance(mode_flags, dict) or not isinstance(refusals, tuple):
        return findings
    for call, keys in guards:
        unknown = sorted(keys - set(mode_flags))
        if unknown:
            findings.append(src.finding(
                call, RULE.name,
                f"guard passes unknown mode name(s) {unknown}: not in "
                f"{_FLAGS} (this raises KeyError at runtime — fix the "
                f"key or add the mode to the table)"))
    # a CLI exposing both flags of a refused pair must guard the pair
    mode_by_flag = {spelling.split()[0]: mode
                    for mode, spelling in mode_flags.items()
                    if isinstance(spelling, str)
                    and spelling.startswith("--")}
    exposed_modes = {mode_by_flag[f] for f in flags_exposed
                     if f in mode_by_flag}
    for row in refusals:
        if not (isinstance(row, tuple) and len(row) >= 2):
            continue
        a, b = row[0], row[1]
        if a not in exposed_modes or b not in exposed_modes:
            continue
        if any({a, b} <= keys for _, keys in guards):
            continue
        anchor = flags_exposed[mode_flags[a].split()[0]]
        findings.append(src.finding(
            anchor, RULE.name,
            f"CLI exposes {mode_flags[a].split()[0]} and "
            f"{mode_flags[b].split()[0]} but no {_GUARD} call covers "
            f"the refused pair ({a!r}, {b!r}): the combination parses "
            f"and runs unchecked — add both modes to this module's "
            f"guard dict"))
    return findings


def _check(src: SourceFile, ctx: ModuleContext) -> list[Finding]:
    if _defines_table(ctx.tree):
        return _check_defining_module(src, ctx)
    return _check_caller_module(src, ctx)


RULE = Rule(
    name="refusal-drift",
    summary="MODE_REFUSALS rows without a reachable guard; CLI guards "
            "with unknown modes, ad-hoc refusals, or unguarded "
            "refusable flag pairs",
    check=_check,
    cross_file=True)
