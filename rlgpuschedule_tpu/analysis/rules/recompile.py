"""recompile-hazard: jit cache misses by construction.

``jax.jit``'s cache is keyed on the *function object* plus abstract
argument signature. Two constructions defeat it outright:

- ``jax.jit(lambda ...: ...)`` (or a nested ``def``) evaluated inside a
  function body: every call of the enclosing function builds a fresh
  function object, so every call compiles from scratch — the hazard
  ``parallel/pbt.py``'s ``_GATHER_CACHE`` exists to avoid.
- any ``jax.jit(...)`` call inside a ``for``/``while`` loop body: one
  compile per loop iteration.

Caching the jitted callable exempts the pattern: an assignment whose
target includes an attribute or subscript (``self._fused_jit = ...``,
``_CACHE[key] = ...``) is recognized as the memoization idiom. The
stealthier recompile causes — unhashable/Python-scalar closure captures,
shape-unstable arguments — are not statically decidable here; the
runtime compile-count sentinel (``analysis.sentinels.CompileCounter``)
owns that half of the contract.
"""
from __future__ import annotations

import ast

from . import Rule
from ..engine import Finding, ModuleContext, SourceFile

_JIT_CALLS = {"jax.jit", "jax.pmap"}


def _is_cached_assignment(ctx: ModuleContext, call: ast.Call) -> bool:
    """True when the jit result is stored through an attribute/subscript
    target (memoized on an object or in a cache dict)."""
    node = call
    for parent in ctx.ancestors(call):
        if isinstance(parent, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (parent.targets if isinstance(parent, ast.Assign)
                       else [parent.target])
            return any(isinstance(t, (ast.Attribute, ast.Subscript))
                       for t in targets)
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda, ast.Module)):
            return False
        node = parent
    return False


def _in_loop(ctx: ModuleContext, node: ast.AST) -> bool:
    for parent in ctx.ancestors(node):
        if isinstance(parent, (ast.For, ast.While)):
            return True
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
            # a loop *outside* the enclosing function doesn't re-run this
            # statement per iteration unless the function is re-called —
            # which the fresh-function-object check already covers
            return False
    return False


def _nested_defs(fn: ast.AST) -> set[str]:
    return {n.name for n in ast.walk(fn)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n is not fn}


def _check(src: SourceFile, ctx: ModuleContext) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) \
                or ctx.resolve_call(node) not in _JIT_CALLS:
            continue
        if _in_loop(ctx, node):
            findings.append(src.finding(
                node, RULE.name,
                "jax.jit inside a loop body compiles once per iteration; "
                "hoist the jit out of the loop"))
            continue
        fn = ctx.enclosing_function(node)
        if fn is None or _is_cached_assignment(ctx, node):
            continue
        target = node.args[0] if node.args else None
        fresh = isinstance(target, ast.Lambda) or (
            isinstance(target, ast.Name) and target.id in _nested_defs(fn))
        if fresh:
            findings.append(src.finding(
                node, RULE.name,
                "jax.jit of a function object created per call (lambda / "
                "nested def) defeats the jit cache: every call of the "
                "enclosing function recompiles; hoist the target to "
                "module scope or memoize the jitted callable"))
    return findings


RULE = Rule(
    name="recompile-hazard",
    summary="jit-of-fresh-lambda / jit-in-loop defeats the compile cache",
    check=_check)
