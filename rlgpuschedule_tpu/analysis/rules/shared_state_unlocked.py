"""shared-state-unlocked: racing writers with no common lock region.

The long-tail concurrency bug class behind the keep-best-checkpoint
flake (PR 8): two threads writing the same instance attribute with no
shared lock. Any interleaving "works" until the one that doesn't —
a half-published failure flag, a counter that loses increments, a
carry swapped mid-read.

The check is write-centric and per-attribute: every write to a
``self.<attr>`` outside ``__init__``-family methods is attributed to
the thread roots that reach its method (the main thread when none do),
with the lock set held there — lexically plus the caller-side fixpoint
(:mod:`..concurrency`), so ``PolicyServer._shed_expired`` (only ever
called under ``self._lock``) counts as locked, and writes under
``self._wake`` (a ``Condition(self._lock)``) alias to the same region.
An attribute written from two or more distinct roots whose write-site
lock sets share NO common lock fires once, at the first write.

Reads are deliberately out of scope (flagging every unlocked read of a
monotonic gauge would bury the true positives); a read-side tear that
matters shows up as a write somewhere else.
"""
from __future__ import annotations

import ast

from . import Rule
from ..concurrency import MAIN, model_for
from ..engine import Finding, ModuleContext, SourceFile

_CTOR_METHODS = {"__init__", "__post_init__", "__new__", "__set_name__"}


def _check(src: SourceFile, ctx: ModuleContext) -> list[Finding]:
    model = model_for(ctx)
    if not model.thread_roots:
        return []
    # (class, attr) -> list of (node, roots, locks)
    writes: dict[tuple, list] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        else:
            continue
        for t in targets:
            if not (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                continue
            fn = ctx.enclosing_function(node)
            if fn is None or getattr(fn, "name", "") in _CTOR_METHODS:
                continue
            cls = model.class_of(fn)
            if cls is None:
                continue
            roots = frozenset(model.roots_reaching(node)) or \
                frozenset({MAIN})
            writes.setdefault((id(cls), cls.name, t.attr), []).append(
                (node, roots, model.locks_at(node)))
    findings: list[Finding] = []
    for (_, cls_name, attr), sites in sorted(
            writes.items(), key=lambda kv: kv[1][0][0].lineno):
        all_roots = frozenset().union(*(r for _, r, _ in sites))
        if len(all_roots) < 2 or not (all_roots - {MAIN}):
            continue
        common = sites[0][2]
        for _, _, locks in sites[1:]:
            common &= locks
        if common:
            continue
        first = min((n for n, _, _ in sites), key=lambda n: n.lineno)
        labels = ", ".join(sorted(
            model.thread_roots.get(r, "the main thread")
            for r in all_roots))
        findings.append(src.finding(
            first, RULE.name,
            f"self.{attr} ({cls_name}) is written from {len(all_roots)} "
            f"entry points ({labels}) with no common lock across the "
            f"writes: protect every write with one shared lock (a "
            f"Condition wrapping it counts) or confine the attribute "
            f"to one thread"))
    return findings


RULE = Rule(
    name="shared-state-unlocked",
    summary="an instance attribute written from >= 2 thread roots with "
            "no common lock region",
    check=_check)
