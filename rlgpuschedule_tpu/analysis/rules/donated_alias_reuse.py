"""donated-alias-reuse: touching a host alias after its buffer donated.

``jax.jit(..., donate_argnums=...)`` is the Podracer memory trick this
repo leans on at every dispatch boundary (serve engine, async rollout/
learn, fused update steps): XLA reuses the donated input's pages for
the outputs. The flip side is a contract on the CALLER: after the
dispatch, the Python name passed at a donated position refers to a
deleted buffer. Reading it does not reliably raise — on some backends
it returns whatever the output computation left in those pages, which
is exactly the silent-corruption class ``checkpoint._fresh_copy``
documents for restored trees.

The blessed idiom rebinds through the dispatch — ``state =
self._step(state, batch)`` — which this rule recognizes: a name that
the donating call's own assignment rebinds is never flagged. What fires
is the alias that survives: dispatch WITHOUT rebinding the donated
name, then any later read of it on the same control-flow path (logging
the old state, re-dispatching it, computing a metric from it).

Donation positions come from the ``donate_argnums`` literal on the
tracked ``jax.jit`` site (the concurrency model carries compiled/
donated-ness through one assignment hop); splatted call sites
(``self._step(*args)``) are skipped — positions are unknowable there,
and the engine's warmup/steady split owns that discipline at runtime.
The sibling rule ``donation-cross-thread`` covers the two-thread
version of this hazard; this one is the same-frame version.
"""
from __future__ import annotations

from . import Rule
from ..engine import Finding, ModuleContext, SourceFile
from ..lifetime import model_for


def _check(src: SourceFile, ctx: ModuleContext) -> list[Finding]:
    model = model_for(ctx)
    findings: list[Finding] = []
    for use in model.donated_uses:
        dispatch_line = getattr(use.dispatch, "lineno", 0)
        findings.append(src.finding(
            use.node, RULE.name,
            f"{use.name!r} was donated to the jitted dispatch on line "
            f"{dispatch_line} (donate_argnums) and read again here: "
            f"its buffer now backs the outputs, so the read returns "
            f"garbage without raising — rebind the result over the "
            f"donated name (state = step(state)) or keep a pre-"
            f"dispatch copy"))
    return findings


RULE = Rule(
    name="donated-alias-reuse",
    summary="host reads of a name after it was passed at a "
            "donate_argnums position of a jitted dispatch",
    check=_check)
