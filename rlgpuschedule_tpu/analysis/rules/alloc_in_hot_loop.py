"""alloc-in-hot-loop: fresh ndarray construction on dispatcher paths.

The arena data plane's whole contract (ISSUE 17) is that serving's
steady state allocates ZERO new host ndarrays per batch: requests land
in preallocated slabs, padding is slice assignment into the slab tail,
and scatter returns views into the one device-fetched actions buffer.
An ``np.zeros``/``np.empty``/``np.concatenate``/``np.stack`` that
creeps into code reachable from a dispatcher loop quietly reintroduces
per-batch allocation churn — the host-path regression BENCH_r09 exists
to measure — long before any benchmark notices.

Fires on those four constructors inside any function reachable (via the
module's call graph) from a thread root the concurrency model knows:
``threading.Thread`` targets, executor-submitted callables, and the
``loop``/``*_loop``/``*_worker`` dispatcher convention. Main-thread-only
helpers (warmup, benches, construction-time sizing) never fire — slab
construction is exactly where those calls belong.

A deliberate allocation on a hot path (a cold-path branch, a
rare-rollover grow) is a one-line suppression with the reason inline::

    slab = np.zeros(shape)  # jsan: disable=alloc-in-hot-loop -- ring growth, amortized
"""
from __future__ import annotations

import ast

from . import Rule
from ..concurrency import model_for
from ..engine import Finding, ModuleContext, SourceFile

_ALLOC_CALLS = {"numpy.zeros", "numpy.empty", "numpy.concatenate",
                "numpy.stack"}


def _check(src: SourceFile, ctx: ModuleContext) -> list[Finding]:
    model = model_for(ctx)
    if not model.thread_roots:
        return []
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = ctx.resolve_call(node)
        if name not in _ALLOC_CALLS:
            continue
        roots = model.roots_reaching(node)
        if not roots:
            continue
        labels = ", ".join(model.root_labels(roots))
        short = name.split(".")[-1]
        findings.append(src.finding(
            node, RULE.name,
            f"np.{short}() allocates a fresh ndarray on a path "
            f"reachable from {labels}: dispatcher hot paths must reuse "
            f"preallocated slabs (write into an arena slot / slice-"
            f"assign the tail) — or suppress with the reason the "
            f"allocation is cold or amortized"))
    return findings


RULE = Rule(
    name="alloc-in-hot-loop",
    summary="np.zeros/empty/concatenate/stack in functions reachable "
            "from dispatcher loops",
    check=_check)
