"""device-dispatch-unlocked: thread-side device work without a lock.

The PR-13 postmortem class: XLA:CPU's client is not thread-safe, so
every device interaction from a non-main thread — executing a compiled
program, ``jax.device_put`` / ``jax.device_get`` transfers,
``jax.block_until_ready`` — must be serialized behind a dispatch lock.
The repo's idiom is a conditional lock that only costs anything on the
unsafe backend::

    self._dispatch_lock = (threading.Lock() if on_cpu
                           else contextlib.nullcontext())
    ...
    with tracer.span("actor"), ..., self._dispatch_lock:
        out = self._rollout(params, carry)

Fires on dispatch calls (tracked compiled-object executions and the
``jax.device_put/device_get/block_until_ready`` trio) whose enclosing
function is thread-reachable with NO recognized lock held — lexically
or via the caller-side lock fixpoint (:mod:`..concurrency`). Which lock
is not checked (device identity is runtime knowledge); any recognized
lock region satisfies the rule.
"""
from __future__ import annotations

import ast

from . import Rule
from ..concurrency import model_for
from ..engine import Finding, ModuleContext, SourceFile

_DISPATCH_CALLS = {"jax.device_put", "jax.device_get",
                   "jax.block_until_ready"}


def _check(src: SourceFile, ctx: ModuleContext) -> list[Finding]:
    model = model_for(ctx)
    if not model.thread_roots:
        return []
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = ctx.resolve_call(node)
        what = None
        if name in _DISPATCH_CALLS:
            what = name
        else:
            tok = model.value_token(node.func, node)
            if tok is not None and tok in model.compiled:
                what = f"compiled program {model.lock_name(tok)}"
        if what is None:
            continue
        roots = model.roots_reaching(node)
        if not roots or model.locks_at(node):
            continue
        labels = ", ".join(model.thread_roots[r] for r in sorted(
            roots, key=lambda f: f.lineno))
        findings.append(src.finding(
            node, RULE.name,
            f"{what} dispatched from {labels} with no dispatch lock "
            f"held: XLA:CPU device access must be serialized across "
            f"threads (PR-13 class) — wrap in the engine's dispatch "
            f"lock (threading.Lock() if on_cpu else nullcontext())"))
    return findings


RULE = Rule(
    name="device-dispatch-unlocked",
    summary="thread-reachable device dispatch (compiled call / "
            "device_put / device_get) outside any recognized lock",
    check=_check)
