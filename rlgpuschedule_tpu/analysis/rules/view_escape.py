"""view-escape: a slab/frombuffer view outliving its function frame.

The arena data plane (``serve/batching.py``, ISSUE 17) trades copies
for aliasing discipline: ``_arena_views`` returns slices of a recycled
slab, ``scatter_results`` returns rows of one batched actions buffer,
and the HTTP front door parses requests as ``np.frombuffer`` views over
the received body. All of that is correct ONLY while the view stays
inside the frame that knows the buffer's lifetime. The moment a view is
*stored* — on ``self``, in a module-level container, inside a returned
closure — its backing storage can be recycled (or the recv buffer
reused) under it, and the reader sees someone else's batch with no
exception anywhere near the bug.

Fires on every escape of a strong view the lifetime model
(:mod:`..lifetime`) proves aliases a tracked source:

- stored on a ``self`` attribute or appended/inserted into a ``self``
  container (or a module-level global);
- returned — UNLESS the function's docstring documents the view
  contract (contains the word "view"), which is this repo's convention
  for deliberate zero-copy returns (``_arena_views``: "(views, never
  copies)"); an undocumented view return is indistinguishable from an
  accidental one at every call site;
- captured by a nested function that is itself returned or stored.

The fix is one of: copy at the boundary (``view.copy()`` /
``np.array(view)`` end the taint chain), or document the contract in
the docstring so callers know they hold borrowed memory.
"""
from __future__ import annotations

from . import Rule
from ..engine import Finding, ModuleContext, SourceFile
from ..lifetime import model_for


def _check(src: SourceFile, ctx: ModuleContext) -> list[Finding]:
    model = model_for(ctx)
    findings: list[Finding] = []
    for esc in model.escapes:
        if esc.how == "returned" and esc.documented:
            continue
        if esc.how == "returned":
            hint = ("return a copy (view.copy() / np.array(view)) or "
                    "document the zero-copy contract in the docstring "
                    "(the word 'view' marks it, like _arena_views)")
        else:
            hint = ("copy at the boundary — the stored reference "
                    "outlives the frame that knows the buffer's "
                    "lifetime")
        findings.append(src.finding(
            esc.node, RULE.name,
            f"{esc.view.label} view {esc.how}: the backing buffer can "
            f"be recycled under it and the holder reads another "
            f"batch's data — {hint}"))
    return findings


RULE = Rule(
    name="view-escape",
    summary="slab/frombuffer/scatter views stored beyond their frame "
            "or returned without a documented view contract",
    check=_check)
