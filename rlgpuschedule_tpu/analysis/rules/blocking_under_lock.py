"""blocking-under-lock: unbounded blocking calls inside a lock region.

The classic two-party deadlock: thread A holds the dispatch lock and
blocks on ``queue.get()``; thread B must take the same lock to ``put``
the item A is waiting for. Nobody crashes — the engine just stops, and
on a CI rig that reads as a timeout with no stack. The serving stack's
discipline is the model: ``PolicyServer.pump`` drains its queue under
``self._lock`` but always waits on the Condition (which RELEASES the
lock) or with a bounded timeout, and joins its dispatcher threads
outside the lock.

Fires on these calls when any recognized lock is held at the site
(lexically or via the caller-side fixpoint):

- ``<queue>.get(...)`` / ``<queue>.put(...)`` on a tracked queue
  object, unless ``block=False`` or an explicit ``timeout=`` bounds it
  (``get_nowait``/``put_nowait`` are different attributes and never
  match);
- ``<future>.result()`` with no timeout;
- ``<thread>.join()`` with no arguments (``sep.join(parts)`` has an
  argument and never matches; ``join(timeout=...)`` is bounded).

``Condition.wait`` is exempt by construction — it releases the lock it
waits on; that is the sanctioned way to block inside a region.
"""
from __future__ import annotations

import ast

from . import Rule
from ..concurrency import model_for
from ..engine import Finding, ModuleContext, SourceFile


def _kw(call: ast.Call, name: str) -> ast.expr | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _check(src: SourceFile, ctx: ModuleContext) -> list[Finding]:
    model = model_for(ctx)
    if not model.lock_tokens:
        return []
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        attr = node.func.attr
        if attr not in ("get", "put", "result", "join"):
            continue
        held = model.locks_at(node)
        if not held:
            continue
        what = None
        if attr in ("get", "put"):
            tok = model.value_token(node.func.value, node)
            if tok is None or tok not in model.queue_tokens:
                continue
            block = _kw(node, "block")
            if isinstance(block, ast.Constant) and block.value is False:
                continue
            if _kw(node, "timeout") is not None:
                continue                      # bounded wait
            what = f"blocking queue .{attr}()"
        elif attr == "result":
            if node.args or _kw(node, "timeout") is not None:
                continue
            what = "future .result() with no timeout"
        elif attr == "join":
            if node.args or _kw(node, "timeout") is not None:
                continue
            what = ".join() with no timeout"
        locks = ", ".join(sorted(model.lock_name(t) for t in held))
        findings.append(src.finding(
            node, RULE.name,
            f"{what} while holding {locks}: the thread that would "
            f"unblock this call may need the same lock (deadlock "
            f"hazard) — move the wait outside the region, bound it "
            f"with a timeout, or wait on a Condition that releases "
            f"the lock"))
    return findings


RULE = Rule(
    name="blocking-under-lock",
    summary="unbounded queue get/put, future.result(), or join() "
            "inside a held lock region",
    check=_check)
