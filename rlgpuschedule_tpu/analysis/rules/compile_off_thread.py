"""compile-off-thread: jit compilation reachable from a thread root.

The PR-8 postmortem's first XLA:CPU crash class: ``jax.jit`` tracing /
compiling on a non-main thread corrupts the compile cache (and with
donation in the program, the heap — observed as checkpoint poison and
interpreter segfaults, not as a Python exception). The contract every
threaded engine in this repo follows is AOT-at-construction:
``jax.jit(f).lower(args).compile()`` on the construction (main) thread,
with the thread bodies calling the execute-only Compiled objects
(``async_engine.AsyncRunner.__init__`` builds ``self._rollout`` /
``self._learn`` exactly this way).

Fires on any ``jax.jit(...)`` / ``jax.pmap(...)`` call, or any
``<chain>.compile()`` AOT chain, whose enclosing function is reachable
from a thread entry point (:mod:`..concurrency`). Construction-time
compiles (``__init__``, module level, main-path helpers) are untouched.
"""
from __future__ import annotations

import ast

from . import Rule
from ..concurrency import model_for
from ..engine import Finding, ModuleContext, SourceFile

_JIT_CTORS = {"jax.jit", "jax.pmap"}


def _check(src: SourceFile, ctx: ModuleContext) -> list[Finding]:
    model = model_for(ctx)
    if not model.thread_roots:
        return []
    findings: list[Finding] = []
    seen_lines: set[int] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        is_jit = ctx.resolve_call(node) in _JIT_CTORS
        if not is_jit and not model._is_aot_compile_call(node):
            continue
        roots = model.roots_reaching(node)
        if not roots or node.lineno in seen_lines:
            continue
        seen_lines.add(node.lineno)
        labels = ", ".join(model.thread_roots[r] for r in sorted(
            roots, key=lambda f: f.lineno))
        findings.append(src.finding(
            node, RULE.name,
            f"jit compilation reachable from {labels}: XLA:CPU compile "
            f"off the main thread corrupts the compile cache (PR-8 "
            f"crash class) — AOT-compile at construction "
            f"(jit(f).lower(args).compile()) and call the Compiled "
            f"object from the thread"))
    return findings


RULE = Rule(
    name="compile-off-thread",
    summary="jit/AOT compilation reachable from a thread entry point "
            "(must compile at construction, execute-only in threads)",
    check=_check)
