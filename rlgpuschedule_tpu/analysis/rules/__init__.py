"""jsan rule registry. Each rule module exposes ``RULE``; the registry
is the single source of truth for ``--list-rules`` and the default run.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Iterable

from ..engine import Finding, ModuleContext, SourceFile


@dataclasses.dataclass(frozen=True)
class Rule:
    name: str
    summary: str
    check: Callable[[SourceFile, ModuleContext], Iterable[Finding]]
    # cross-file rules read sibling files (refusal tables, metric
    # consumers) whose edits a per-file cache key cannot see, so the
    # engine's --cache never stores their findings and always re-runs
    # them (engine.FindingCache)
    cross_file: bool = False


def all_rules() -> list[Rule]:
    from . import (alloc_in_hot_loop, blocking_under_lock,
                   compile_off_thread, contract_drift,
                   device_dispatch_unlocked, donated_alias_reuse, donation,
                   donation_cross_thread, host_sync, hung_future,
                   impure_in_jit, prng_reuse, recompile, refusal_drift,
                   shared_state_unlocked, sync_in_loop, torn_publish,
                   tracer_leak, unconstrained_intermediate,
                   use_after_recycle, view_escape)
    return [donation.RULE, host_sync.RULE, sync_in_loop.RULE,
            tracer_leak.RULE, impure_in_jit.RULE, recompile.RULE,
            prng_reuse.RULE, unconstrained_intermediate.RULE,
            compile_off_thread.RULE, device_dispatch_unlocked.RULE,
            donation_cross_thread.RULE, shared_state_unlocked.RULE,
            blocking_under_lock.RULE, hung_future.RULE,
            alloc_in_hot_loop.RULE, refusal_drift.RULE,
            view_escape.RULE, use_after_recycle.RULE,
            donated_alias_reuse.RULE, torn_publish.RULE,
            contract_drift.RULE]


def rule_names() -> list[str]:
    return [r.name for r in all_rules()]
