"""jsan rule registry. Each rule module exposes ``RULE``; the registry
is the single source of truth for ``--list-rules`` and the default run.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Iterable

from ..engine import Finding, ModuleContext, SourceFile


@dataclasses.dataclass(frozen=True)
class Rule:
    name: str
    summary: str
    check: Callable[[SourceFile, ModuleContext], Iterable[Finding]]


def all_rules() -> list[Rule]:
    from . import (alloc_in_hot_loop, blocking_under_lock,
                   compile_off_thread, device_dispatch_unlocked, donation,
                   donation_cross_thread, host_sync, hung_future,
                   impure_in_jit, prng_reuse, recompile, refusal_drift,
                   shared_state_unlocked, sync_in_loop, tracer_leak,
                   unconstrained_intermediate)
    return [donation.RULE, host_sync.RULE, sync_in_loop.RULE,
            tracer_leak.RULE, impure_in_jit.RULE, recompile.RULE,
            prng_reuse.RULE, unconstrained_intermediate.RULE,
            compile_off_thread.RULE, device_dispatch_unlocked.RULE,
            donation_cross_thread.RULE, shared_state_unlocked.RULE,
            blocking_under_lock.RULE, hung_future.RULE,
            alloc_in_hot_loop.RULE, refusal_drift.RULE]


def rule_names() -> list[str]:
    return [r.name for r in all_rules()]
