"""jsan rule registry. Each rule module exposes ``RULE``; the registry
is the single source of truth for ``--list-rules`` and the default run.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Iterable

from ..engine import Finding, ModuleContext, SourceFile


@dataclasses.dataclass(frozen=True)
class Rule:
    name: str
    summary: str
    check: Callable[[SourceFile, ModuleContext], Iterable[Finding]]


def all_rules() -> list[Rule]:
    from . import (donation, host_sync, impure_in_jit, prng_reuse,
                   recompile, sync_in_loop, tracer_leak,
                   unconstrained_intermediate)
    return [donation.RULE, host_sync.RULE, sync_in_loop.RULE,
            tracer_leak.RULE, impure_in_jit.RULE, recompile.RULE,
            prng_reuse.RULE, unconstrained_intermediate.RULE]


def rule_names() -> list[str]:
    return [r.name for r in all_rules()]
