"""host-sync-in-hot-path: device values pulled to the host inside
trace-reachable code.

``.item()`` / ``.tolist()`` / ``float()`` / ``np.asarray()`` on a traced
value either fails at trace time (ConcretizationTypeError) or — worse,
when it survives on a concrete closure capture — silently bakes a
host-device round trip or a trace-time constant into the compiled
program. Inside the rollout/update hot path (one fused dispatch per
iteration is the whole point — ``Experiment.run_fused``) a single such
sync serializes the pipeline: the host blocks on the device instead of
staying an iteration ahead.

Only fires inside traced regions (engine docstring) — host-loop code is
free to materialize scalars, that is where it belongs.
"""
from __future__ import annotations

import ast

from . import Rule
from ..engine import Finding, ModuleContext, SourceFile

_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_SYNC_CALLS = {"numpy.asarray", "numpy.array", "numpy.float32",
               "numpy.float64", "numpy.int32", "numpy.int64",
               "jax.device_get"}
_CAST_BUILTINS = {"float", "int", "bool"}


def _param_names(ctx: ModuleContext, node: ast.AST) -> set[str]:
    names: set[str] = set()
    fn = ctx.enclosing_function(node)
    while fn is not None:
        a = fn.args
        for arg in (a.posonlyargs + a.args + a.kwonlyargs
                    + ([a.vararg] if a.vararg else [])
                    + ([a.kwarg] if a.kwarg else [])):
            names.add(arg.arg)
        fn = ctx.enclosing_function(fn)
    return names


def _roots_at_param(node: ast.AST, params: set[str]) -> bool:
    """True when the expression is rooted at a function parameter (a
    Name, or an attribute/subscript chain off one) — the value the trace
    actually flows through."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return isinstance(node, ast.Name) and node.id in params


def _check(src: SourceFile, ctx: ModuleContext) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not ctx.in_traced_region(node):
            continue
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _SYNC_METHODS and not node.args:
            findings.append(src.finding(
                node, RULE.name,
                f".{node.func.attr}() inside a trace-reachable function "
                f"forces a host sync (or fails on a tracer); keep the "
                f"value on device and materialize in the host loop"))
            continue
        name = ctx.resolve_call(node)
        if name in _SYNC_CALLS:
            if node.args and isinstance(node.args[0], ast.Constant):
                continue  # np.float32(0.0)-style literals are host math
            findings.append(src.finding(
                node, RULE.name,
                f"{name}() materializes a device value inside a "
                f"trace-reachable function; use jnp (stays on device) or "
                f"hoist the host conversion out of the jit region"))
        elif name in _CAST_BUILTINS and len(node.args) == 1 \
                and _roots_at_param(node.args[0],
                                    _param_names(ctx, node)):
            findings.append(src.finding(
                node, RULE.name,
                f"{name}() on a traced argument is a host sync at best "
                f"and a ConcretizationTypeError at worst; use jnp casts "
                f"(.astype) to stay on device"))
    return findings


RULE = Rule(
    name="host-sync",
    summary="host materialization (.item/float/np.asarray) in jit-reachable code",
    check=_check)
