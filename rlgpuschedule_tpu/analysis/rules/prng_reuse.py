"""prng-key-reuse: the same PRNG key consumed by two jax.random calls.

Reusing a key makes two "independent" draws bit-identical — in an RL
trainer that means correlated action noise or identical minibatch
permutations across epochs, a bug that changes no shapes, raises no
error, and shifts training curves just enough to waste a tuning run.
The contract is one consumption per key: ``key, sub = jax.random.split
(key)`` then use ``sub`` exactly once.

Detection is a linear, source-order scan per function scope:

- a plain-Name first argument to any consuming ``jax.random.*`` call
  (everything except ``PRNGKey``/``key_data``/``wrap_key_data``/
  ``fold_in`` — fold_in derives without consuming) marks the name
  consumed;
- names assigned from a key-producing call (``PRNGKey``, ``split``,
  ``fold_in``, tuple-unpacked or not) are **key variables**: passing
  one to *any* call consumes it too — ``init_carry(..., key)`` followed
  by ``net.init(key, ...)`` hands both consumers the same stream even
  though neither is itself ``jax.random.*`` (the bug class this repo
  actually had, in the multihost dryrun);
- any rebinding of the name (assignment, tuple unpack, loop target)
  clears it;
- a second consumption while marked is a finding;
- additionally, a consumption *inside a loop body* of a key that the
  loop body never rebinds is a finding — the second consumption happens
  at runtime, one iteration later.

Attribute-rooted keys (``carry.key``) and cross-function flows are out
of scope (precision over recall; the rollout threads keys through
NamedTuples correctly and reads them back via split-and-rebind).
"""
from __future__ import annotations

import ast

from . import Rule
from ..engine import Finding, ModuleContext, SourceFile

_NON_CONSUMING = {"PRNGKey", "key_data", "wrap_key_data", "key_impl",
                  "fold_in", "clone"}
_KEY_PRODUCERS = {"PRNGKey", "split", "fold_in", "clone", "key"}
_FN = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _produces_key(ctx: ModuleContext, value: ast.AST) -> bool:
    """RHS expressions whose results are PRNG keys (possibly stacked)."""
    if not isinstance(value, ast.Call):
        return False
    name = ctx.resolve_call(value)
    return bool(name) and name.startswith("jax.random.") \
        and name.rsplit(".", 1)[-1] in _KEY_PRODUCERS


def _bound_names(target: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(target)
            if isinstance(n, ast.Name)
            and isinstance(n.ctx, (ast.Store, ast.Del))}


class _ScopeScanner:
    """Source-order scan of one function (or module) body, not descending
    into nested function scopes."""

    def __init__(self, src: SourceFile, ctx: ModuleContext):
        self.src = src
        self.ctx = ctx
        self.consumed: dict[str, ast.Call] = {}
        self.key_names: set[str] = set()
        self.findings: list[Finding] = []

    def _consumptions(self, call: ast.Call) -> list[str]:
        """Key names this call consumes."""
        name = self.ctx.resolve_call(call)
        if name and name.startswith("jax.random."):
            if name.rsplit(".", 1)[-1] in _NON_CONSUMING:
                return []
            if call.args and isinstance(call.args[0], ast.Name):
                return [call.args[0].id]
            for kw in call.keywords:
                if kw.arg == "key" and isinstance(kw.value, ast.Name):
                    return [kw.value.id]
            return []
        # generic call: any known key variable handed over is consumed by
        # whatever randomness the callee draws from it
        out = []
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            if isinstance(arg, ast.Name) and arg.id in self.key_names:
                out.append(arg.id)
        return out

    def scan_body(self, body: list[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, _FN):
            return
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            if stmt.value is not None:
                self._expr(stmt.value)
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            is_key = stmt.value is not None \
                and _produces_key(self.ctx, stmt.value)
            for t in targets:
                for name in _bound_names(t):
                    self.consumed.pop(name, None)
                    (self.key_names.add if is_key
                     else self.key_names.discard)(name)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter)
            loop_bound = _bound_names(stmt.target)
            for sub in ast.walk(stmt):
                if sub is not stmt and isinstance(
                        sub, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                    targets = (sub.targets if isinstance(sub, ast.Assign)
                               else [sub.target])
                    for t in targets:
                        loop_bound |= _bound_names(t)
            self._loop_body(stmt.body + stmt.orelse, loop_bound)
            return
        if isinstance(stmt, ast.While):
            self._expr(stmt.test)
            loop_bound: set[str] = set()
            for sub in ast.walk(stmt):
                if isinstance(sub, (ast.Assign, ast.AugAssign,
                                    ast.AnnAssign)):
                    targets = (sub.targets if isinstance(sub, ast.Assign)
                               else [sub.target])
                    for t in targets:
                        loop_bound |= _bound_names(t)
            self._loop_body(stmt.body + stmt.orelse, loop_bound)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        # generic statement: scan child statements recursively, child
        # expressions linearly
        for field in ast.iter_child_nodes(stmt):
            if isinstance(field, ast.stmt):
                self._stmt(field)
            elif isinstance(field, ast.expr):
                self._expr(field)

    def _loop_body(self, body: list[ast.stmt], loop_bound: set[str]) -> None:
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, _FN):
                    break
                if isinstance(node, ast.Call):
                    for key in self._consumptions(node):
                        if key not in loop_bound:
                            self.findings.append(self.src.finding(
                                node, RULE.name,
                                f"PRNG key {key!r} is consumed inside a "
                                f"loop body that never rebinds it: every "
                                f"iteration draws the SAME randomness; "
                                f"split the key per iteration"))
            self._stmt(stmt)

    def _expr(self, expr: ast.expr) -> None:
        for node in ast.walk(expr):
            if isinstance(node, _FN):
                continue
            if isinstance(node, ast.Call):
                for key in self._consumptions(node):
                    if key in self.consumed:
                        self.findings.append(self.src.finding(
                            node, RULE.name,
                            f"PRNG key {key!r} already consumed at line "
                            f"{self.consumed[key].lineno}; reusing it "
                            f"hands two consumers the same stream (the "
                            f"draws are bit-identical) — split first"))
                    else:
                        self.consumed[key] = node


def _check(src: SourceFile, ctx: ModuleContext) -> list[Finding]:
    findings: list[Finding] = []
    scopes: list[list[ast.stmt]] = [ctx.tree.body]
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scopes.append(node.body)
    seen: set[tuple[int, int]] = set()
    for body in scopes:
        scanner = _ScopeScanner(src, ctx)
        scanner.scan_body(body)
        for f in scanner.findings:
            if (f.line, f.col) not in seen:
                seen.add((f.line, f.col))
                findings.append(f)
    return findings


RULE = Rule(
    name="prng-key-reuse",
    summary="same PRNG key consumed by two jax.random calls without a split",
    check=_check)
