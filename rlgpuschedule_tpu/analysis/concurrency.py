"""jsan's thread-aware interprocedural model (ISSUE 15 tentpole).

The five concurrency rules share one per-module model built here:

**Thread roots** — the functions whose bodies run off the main thread:

1. any resolvable ``threading.Thread(target=...)`` target (a module
   function, a ``self.method``, a nested closure, a lambda);
2. the first argument of an executor-style ``*.submit(fn, ...)`` call,
   when it resolves to a local function (an opaque first argument is
   NOT a root — ``PolicyServer.submit(obs, mask)`` must not count);
3. the dispatcher/actor loop naming convention: ``loop``, ``*_loop``,
   ``*_worker`` — this repo's thread bodies (``_actor_loop``, the
   dispatcher ``loop``) follow it, and factoring a thread body into a
   helper must not silently drop it out of the model. Convention roots
   only arm in modules that import ``threading`` or
   ``concurrent.futures`` (so ``analysis/rules/_in_loop`` helpers and
   host-side ``fused_loop`` benchmarks stay out).

**Call reachability** — an intra-module call graph over ``f(...)``,
``self.m(...)``, and one hop of attribute tracking: ``self._q.put(...)``
resolves through ``self._q = LocalClass(...)`` to ``LocalClass.put``
(how the actor loop reaches ``TrajectoryQueue.put``). Cross-module
edges are out of scope, consistent with the engine's per-module
stance — every finding points at local evidence, and the runtime
sentinels backstop the recall gap.

**Lock regions** — a lock is any name/attribute assigned from
``threading.Lock/RLock/Semaphore/BoundedSemaphore/Condition``, with two
idioms this codebase relies on recognized explicitly:

- ``threading.Lock() if on_cpu else contextlib.nullcontext()`` — the
  conditional dispatch lock (``async_engine``, ``serve/router``);
- ``threading.Condition(self._lock)`` — a Condition *aliasing* the lock
  it wraps (``PolicyServer._wake`` IS ``PolicyServer._lock``), so code
  holding either holds the same region.

A ``with`` statement's items mark the lexically held region
(multi-item ``with a, b, lock:`` included). On top of that, a
**lock-protected-function fixpoint** computes each function's
*effective* locks — the intersection over every call site of the locks
held there plus the caller's own effective locks — so a helper only
ever called under ``self._lock`` (``PolicyServer._shed_expired``)
counts as locked without a lexical ``with`` of its own.

**Program tracking** — assignments of ``jax.jit(...)`` / ``jax.pmap``
results and ``.lower(...).compile()`` chains are tracked as compiled
executables (through one level of local-variable indirection:
``rollout_jit = jax.jit(...)`` then ``self._rollout =
rollout_jit.lower(...).compile()`` marks ``self._rollout``), with
``donate_argnums``/``donate_argnames`` donation-ness carried along.
Queue-typed attributes (``queue.Queue`` constructions or local classes
named ``*Queue*``) are tracked for the blocking rule.
"""
from __future__ import annotations

import ast

from .engine import ModuleContext

_FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

_LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Semaphore",
               "threading.BoundedSemaphore"}
_CONDITION = "threading.Condition"
_THREAD_CTORS = {"threading.Thread", "threading.Timer"}
_JIT_CTORS = {"jax.jit", "jax.pmap"}
_QUEUE_CTORS = {"queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
                "queue.SimpleQueue", "multiprocessing.Queue"}
_DONATE_KW = {"donate_argnums", "donate_argnames"}
_CONVENTION_GATE = {"threading", "concurrent.futures", "concurrent"}

# the main thread, as a pseudo-root for rules that compare writer
# threads (construction-time code and public entry points run here)
MAIN = "<main>"


def _outer_name(fn: ast.AST) -> str:
    return getattr(fn, "name", "<lambda>")


class ConcurrencyModel:
    """Thread roots, call reachability, lock regions, and tracked
    compiled/donated/queue objects for ONE module (built once per
    :class:`ModuleContext`, shared by every concurrency rule)."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self._class_of: dict[ast.AST, ast.ClassDef | None] = {}
        self.classes_by_name: dict[str, ast.ClassDef] = {
            n.name: n for n in ast.walk(ctx.tree)
            if isinstance(n, ast.ClassDef)}
        self._methods: dict[tuple[int, str], list[ast.AST]] = {}
        for cls in self.classes_by_name.values():
            for stmt in cls.body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._methods.setdefault(
                        (id(cls), stmt.name), []).append(stmt)
        self._build_locks()
        self._build_value_tokens()
        self._build_roots()
        self._build_edges()
        self._build_reach()
        self._build_effective_locks()

    # -- class binding ------------------------------------------------------
    def class_of(self, fn: ast.AST) -> ast.ClassDef | None:
        """The class whose ``self`` an enclosing-method chain binds (a
        closure inside a method still sees the method's ``self``)."""
        if fn in self._class_of:
            return self._class_of[fn]
        cls = None
        for anc in self.ctx.ancestors(fn):
            if isinstance(anc, ast.ClassDef):
                cls = anc
                break
        self._class_of[fn] = cls
        return cls

    # -- value tokens -------------------------------------------------------
    # identity for "the same object" across a module: ("attr", id(class),
    # name) for self-attributes, ("var", name) for plain names (scopes
    # merged — precision is recovered by the per-class attr key where it
    # matters)
    def value_token(self, expr: ast.AST, near: ast.AST):
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self":
            fn = near if isinstance(near, _FuncNode) \
                else self.ctx.enclosing_function(near)
            cls = self.class_of(fn) if fn is not None else None
            if cls is not None:
                return ("attr", id(cls), expr.attr)
            return None
        if isinstance(expr, ast.Name):
            return ("var", expr.id)
        return None

    # -- locks --------------------------------------------------------------
    def _lock_kind(self, expr: ast.AST):
        """None | "new" | ("alias", expr): classify an assigned value as
        a fresh lock, an alias of another lock (Condition(lock)), or not
        a lock at all."""
        if isinstance(expr, ast.Call):
            name = self.ctx.resolve(expr.func)
            if name in _LOCK_CTORS:
                return "new"
            if name == _CONDITION:
                return ("alias", expr.args[0]) if expr.args else "new"
            return None
        if isinstance(expr, ast.IfExp):
            # threading.Lock() if on_cpu else contextlib.nullcontext()
            if (self._lock_kind(expr.body) is not None
                    or self._lock_kind(expr.orelse) is not None):
                return "new"
        return None

    def _build_locks(self) -> None:
        defs: list[tuple[tuple, object]] = []
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                value = node.value
                if value is None:
                    continue
                kind = self._lock_kind(value)
                if kind is None:
                    continue
                for t in targets:
                    tok = self.value_token(t, node)
                    if tok is not None:
                        defs.append((tok, kind))
        self.lock_tokens: set[tuple] = {t for t, k in defs if k == "new"}
        self._canon: dict[tuple, tuple] = {t: t for t in self.lock_tokens}
        # resolve Condition(lock) aliases (possibly chained) to the
        # wrapped lock's token; an alias of something untracked is a
        # lock in its own right
        pending = [(t, k[1]) for t, k in defs if isinstance(k, tuple)]
        for _ in range(len(pending) + 1):
            rest = []
            for tok, target_expr in pending:
                ttok = self.value_token(target_expr, target_expr)
                if ttok in self._canon:
                    self._canon[tok] = self._canon[ttok]
                    self.lock_tokens.add(tok)
                else:
                    rest.append((tok, target_expr))
            done = len(rest) == len(pending)
            pending = rest
            if done:
                break
        for tok, target_expr in pending:
            self._canon[tok] = tok
            self.lock_tokens.add(tok)

    def canonical_lock(self, tok: tuple) -> tuple | None:
        return self._canon.get(tok)

    def held_at(self, node: ast.AST) -> frozenset[tuple]:
        """Canonical lock tokens lexically held at ``node`` — ``with``
        ancestors inside the node's own function (a ``with`` outside a
        nested ``def`` does not protect the def's later execution)."""
        held: set[tuple] = set()
        for anc in self.ctx.ancestors(node):
            if isinstance(anc, _FuncNode):
                break
            if isinstance(anc, (ast.With, ast.AsyncWith)):
                for item in anc.items:
                    tok = self.value_token(item.context_expr, node)
                    if tok is not None and tok in self._canon:
                        held.add(self._canon[tok])
        return frozenset(held)

    def locks_at(self, node: ast.AST) -> frozenset[tuple]:
        """Lexical locks at ``node`` plus the enclosing function's
        effective (caller-guaranteed) locks."""
        fn = node if isinstance(node, _FuncNode) \
            else self.ctx.enclosing_function(node)
        eff = self.effective_locks.get(fn, frozenset()) \
            if fn is not None else frozenset()
        return self.held_at(node) | eff

    def lock_name(self, tok: tuple) -> str:
        return f"self.{tok[2]}" if tok[0] == "attr" else tok[1]

    # -- tracked compiled / donated / queue objects -------------------------
    def _jit_call_in(self, expr: ast.AST) -> ast.Call | None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call) \
                    and self.ctx.resolve(node.func) in _JIT_CTORS:
                return node
        return None

    def _is_aot_compile_call(self, call: ast.Call) -> bool:
        """``<chain>.compile()`` where the chain is not a resolvable
        dotted name — ``jit(f).lower(x).compile()`` yes, ``re.compile``
        (resolves to a real module function) no."""
        return (isinstance(call.func, ast.Attribute)
                and call.func.attr == "compile"
                and self.ctx.resolve(call.func) is None)

    def _chain_root(self, expr: ast.AST) -> ast.AST:
        while True:
            if isinstance(expr, ast.Call):
                expr = expr.func
            elif isinstance(expr, ast.Attribute):
                expr = expr.value
            else:
                return expr

    def _build_value_tokens(self) -> None:
        self.compiled: dict[tuple, ast.AST] = {}
        self.donated: dict[tuple, ast.AST] = {}
        self.queue_tokens: set[tuple] = set()
        self.attr_class: dict[tuple, ast.ClassDef] = {}
        assigns = [n for n in ast.walk(self.ctx.tree)
                   if isinstance(n, (ast.Assign, ast.AnnAssign))
                   and (n.value is not None)]
        assigns.sort(key=lambda n: n.lineno)   # one-pass local propagation
        for node in assigns:
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            value = node.value
            toks = [t for t in (self.value_token(t, node) for t in targets)
                    if t is not None]
            if not toks:
                continue
            # instance tracking: self._q = LocalClass(...)
            if isinstance(value, ast.Call) and \
                    isinstance(value.func, ast.Name) and \
                    value.func.id in self.classes_by_name:
                for tok in toks:
                    self.attr_class[tok] = \
                        self.classes_by_name[value.func.id]
            # queue tracking
            if isinstance(value, ast.Call):
                ctor = self.ctx.resolve(value.func)
                local_cls = (value.func.id if isinstance(value.func, ast.Name)
                             else None)
                if ctor in _QUEUE_CTORS or (
                        local_cls is not None and "Queue" in local_cls):
                    self.queue_tokens.update(toks)
            # compiled-program tracking (with donation-ness)
            jit = self._jit_call_in(value)
            produces = jit is not None or (
                isinstance(value, ast.Call)
                and self._is_aot_compile_call(value))
            donated = jit is not None and any(
                kw.arg in _DONATE_KW for kw in jit.keywords)
            if produces and jit is None:
                # an AOT chain rooted at a tracked jit result inherits
                # its donation-ness: jitted.lower(args).compile()
                root = self._chain_root(value)
                rtok = self.value_token(root, node)
                donated = rtok in self.donated
            elif not produces:
                # one hop of indirection: a chain rooted at an already
                # tracked compiled token inherits compiled/donated-ness
                root = self._chain_root(value)
                rtok = self.value_token(root, node)
                if rtok in self.compiled and root is not value:
                    produces = True
                    donated = rtok in self.donated
            if produces:
                site = jit if jit is not None else value
                for tok in toks:
                    self.compiled[tok] = site
                    if donated:
                        self.donated[tok] = site

    # -- thread roots -------------------------------------------------------
    def _callable_targets(self, expr: ast.AST,
                          near: ast.AST) -> list[ast.AST]:
        if isinstance(expr, ast.Lambda):
            return [expr]
        if isinstance(expr, ast.Name):
            return list(self.ctx.functions_by_name.get(expr.id, ()))
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self":
            fn = self.ctx.enclosing_function(near)
            cls = self.class_of(fn) if fn is not None else None
            if cls is not None:
                return list(self._methods.get((id(cls), expr.attr), ()))
        return []

    def _build_roots(self) -> None:
        self.thread_roots: dict[ast.AST, str] = {}
        ctx = self.ctx
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = ctx.resolve_call(node)
            if name in _THREAD_CTORS:
                for kw in node.keywords:
                    if kw.arg == "target":
                        for fn in self._callable_targets(kw.value, node):
                            self.thread_roots.setdefault(
                                fn, f"thread target "
                                    f"{_outer_name(fn)!r}")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "submit" and node.args:
                for fn in self._callable_targets(node.args[0], node):
                    self.thread_roots.setdefault(
                        fn, f"executor-submitted {_outer_name(fn)!r}")
        # dispatcher/actor loop convention — armed only when the module
        # visibly does threading (module docstring)
        if any(a in _CONVENTION_GATE or a.startswith("concurrent.")
               or a.startswith("threading")
               for a in ctx.aliases.values()):
            for fns in ctx.functions_by_name.values():
                for fn in fns:
                    n = fn.name
                    if n == "loop" or n.endswith("_loop") \
                            or n.endswith("_worker"):
                        self.thread_roots.setdefault(
                            fn, f"dispatcher/actor loop {n!r}")

    # -- call graph + reachability ------------------------------------------
    def _build_edges(self) -> None:
        # callee -> [(caller_fn_or_None, call_node)]
        self.call_sites: dict[ast.AST, list[tuple]] = {}
        self._out_edges: dict[ast.AST, list[tuple]] = {}
        ctx = self.ctx
        for call in ast.walk(ctx.tree):
            if not isinstance(call, ast.Call):
                continue
            caller = ctx.enclosing_function(call)
            callees: list[ast.AST] = []
            f = call.func
            if isinstance(f, ast.Name):
                callees = list(ctx.functions_by_name.get(f.id, ()))
            elif isinstance(f, ast.Attribute):
                recv = f.value
                if isinstance(recv, ast.Name) and recv.id == "self":
                    cls = self.class_of(caller) if caller is not None \
                        else None
                    if cls is not None:
                        callees = list(self._methods.get(
                            (id(cls), f.attr), ()))
                else:
                    # one hop through a tracked instance attribute/var
                    rtok = self.value_token(recv, call)
                    cls = self.attr_class.get(rtok) if rtok else None
                    if cls is not None:
                        callees = list(self._methods.get(
                            (id(cls), f.attr), ()))
            for callee in callees:
                self.call_sites.setdefault(callee, []).append(
                    (caller, call))
                self._out_edges.setdefault(caller, []).append(
                    (callee, call))

    def _build_reach(self) -> None:
        self.reach: dict[ast.AST, set[ast.AST]] = {}
        for root in self.thread_roots:
            stack, seen = [root], {root}
            while stack:
                fn = stack.pop()
                self.reach.setdefault(fn, set()).add(root)
                for callee, _ in self._out_edges.get(fn, ()):
                    if callee not in seen:
                        seen.add(callee)
                        stack.append(callee)

    def roots_reaching(self, node: ast.AST) -> set[ast.AST]:
        fn = node if isinstance(node, _FuncNode) \
            else self.ctx.enclosing_function(node)
        return self.reach.get(fn, set()) if fn is not None else set()

    def root_labels(self, roots) -> list[str]:
        return sorted(self.thread_roots.get(r, MAIN) if r is not MAIN
                      else MAIN for r in roots)

    def _build_effective_locks(self) -> None:
        """Fixpoint: a function's effective locks are the intersection,
        over every call site, of the locks held there plus the caller's
        own effective locks. Entry points (thread roots, functions with
        no in-module callers) start with none held."""
        fns = [n for n in ast.walk(self.ctx.tree)
               if isinstance(n, _FuncNode)]
        eff: dict[ast.AST, frozenset | None] = {}
        for fn in fns:
            if fn in self.thread_roots or not self.call_sites.get(fn):
                eff[fn] = frozenset()
            else:
                eff[fn] = None   # unknown yet (TOP)
        for _ in range(len(fns) + 1):
            changed = False
            for fn in fns:
                if fn in self.thread_roots:
                    continue
                sites = self.call_sites.get(fn)
                if not sites:
                    continue
                acc: frozenset | None = None
                for caller, call in sites:
                    caller_eff = (eff.get(caller) if caller is not None
                                  else frozenset())
                    if caller_eff is None:
                        continue      # cycle member: no constraint yet
                    here = self.held_at(call) | caller_eff
                    acc = here if acc is None else (acc & here)
                if acc is not None and acc != eff[fn]:
                    eff[fn] = acc
                    changed = True
            if not changed:
                break
        self.effective_locks: dict[ast.AST, frozenset] = {
            fn: (v if v is not None else frozenset())
            for fn, v in eff.items()}


def model_for(ctx: ModuleContext) -> ConcurrencyModel:
    """The module's (memoized) concurrency model — every rule in one
    analyze_file pass shares a single build."""
    model = getattr(ctx, "_jsan_concurrency", None)
    if model is None:
        model = ConcurrencyModel(ctx)
        ctx._jsan_concurrency = model
    return model
