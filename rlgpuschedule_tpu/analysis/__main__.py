"""jsan CLI: ``python -m rlgpuschedule_tpu.analysis [paths...]``.

Exit codes are a contract CI scripts rely on: **0** clean (after
suppressions + baseline), **1** findings (or stale baseline entries
under ``--fail-stale``), **2** anything that prevented a verdict — bad
invocation, unreadable/unparsable input, a broken baseline file, a git
failure under ``--diff``, or an internal analyzer error (traceback on
stderr). "No verdict" is never conflated with "findings": a wrapper
that treats 1 as "block the merge" must not block on an analyzer crash
it should instead report.

The default baseline is ``jsan_baseline.json`` in the current directory
when it exists (the committed grandfather list — see README "Static
analysis"); ``--no-baseline`` shows everything, ``--write-baseline``
regenerates the file, ``--prune-baseline`` drops entries whose finding
no longer exists, ``--fail-stale`` turns such stale entries into a
failure (ci.sh runs with it so the baseline can only shrink).

``--format sarif`` emits SARIF 2.1.0 for code-scanning upload (regions
carry start/end columns so editors can underline);
``--diff BASE`` restricts analysis to files changed since a git rev;
``--explain RULE`` prints a rule's full rationale (its module
docstring); ``--cache DIR`` replays per-file results keyed on
(file sha1, analyzer-source sha1) so warm runs skip unchanged files —
cross-file rules (refusal-drift, contract-drift) always re-run because
their verdicts depend on files outside the one being analyzed.

Every text-mode finding carries a stable ID ``<rule>@<path>@<hash>``
(hash of the offending source line, so it survives line drift) — the
same identity the baseline uses.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import traceback

from .engine import (analyze_paths, apply_baseline, iter_py_files,
                     load_baseline, make_baseline)
from .rules import all_rules, rule_names

DEFAULT_BASELINE = "jsan_baseline.json"

_SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                 "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")


def _explain(rule_name: str) -> int:
    for rule in all_rules():
        if rule.name == rule_name:
            doc = sys.modules[rule.check.__module__].__doc__ or rule.summary
            print(f"{rule.name}: {rule.summary}\n")
            print(doc.strip())
            return 0
    print(f"jsan: unknown rule {rule_name!r} (see --list-rules)",
          file=sys.stderr)
    return 2


def _diff_paths(base: str, paths: list[str]) -> list[str]:
    """The requested files changed since ``base`` (git's repo-relative
    names intersected with the expansion of ``paths``)."""
    proc = subprocess.run(["git", "diff", "--name-only", base, "--"],
                          capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"git diff --name-only {base} failed: "
                           f"{proc.stderr.strip() or proc.returncode}")
    changed = {os.path.normpath(line.strip())
               for line in proc.stdout.splitlines()
               if line.strip().endswith(".py")}
    return [p for p in iter_py_files(paths)
            if os.path.normpath(p) in changed]


def _sarif(findings) -> dict:
    return {
        "$schema": _SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "jsan",
                "informationUri":
                    "https://github.com/rlgpuschedule/rlgpuschedule-tpu",
                "rules": [{"id": r.name,
                           "shortDescription": {"text": r.summary}}
                          for r in all_rules()],
            }},
            "results": [{
                "ruleId": f.rule,
                "level": "error",
                "message": {"text": f.message},
                "partialFingerprints": {"jsanFindingId/v1": f.finding_id},
                "locations": [{"physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    # SARIF columns are 1-based and endColumn is
                    # exclusive; Finding.end_col is 0-based exclusive,
                    # so both convert with +1 (engine guarantees
                    # end_col > col, so endColumn > startColumn)
                    "region": {"startLine": f.line,
                               "startColumn": f.col + 1,
                               "endLine": f.end_line or f.line,
                               "endColumn": (f.end_col or f.col + 1) + 1},
                }}],
            } for f in findings],
        }],
    }


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m rlgpuschedule_tpu.analysis",
        description="jsan: JAX-pitfall + concurrency static analyzer "
                    "(see README 'Static analysis' for rules and "
                    "workflow)")
    p.add_argument("paths", nargs="*", default=["rlgpuschedule_tpu"],
                   help="files or directories to analyze (default: "
                        "rlgpuschedule_tpu)")
    p.add_argument("--format", choices=("text", "json", "sarif"),
                   default="text")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help=f"baseline JSON of grandfathered findings "
                        f"(default: {DEFAULT_BASELINE}; silently empty "
                        f"when the file does not exist)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline file")
    p.add_argument("--write-baseline", metavar="PATH", default=None,
                   help="write the current findings as a baseline to "
                        "PATH and exit 0")
    p.add_argument("--prune-baseline", action="store_true",
                   help="rewrite the baseline file keeping only entries "
                        "that still match a finding, then exit 0")
    p.add_argument("--fail-stale", action="store_true",
                   help="fail (exit 1) when the baseline contains "
                        "entries no current finding matches")
    p.add_argument("--diff", metavar="BASE", default=None,
                   help="only analyze files changed since the git rev "
                        "BASE (intersected with the requested paths)")
    p.add_argument("--cache", metavar="DIR", default=None,
                   help="cache per-file findings in DIR, keyed on the "
                        "file's content hash and the analyzer's own "
                        "source hash (any rule edit invalidates "
                        "everything); cross-file rules always re-run")
    p.add_argument("--explain", metavar="RULE", default=None,
                   help="print a rule's full rationale and exit")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.name}: {rule.summary}")
        return 0
    if args.explain:
        return _explain(args.explain)

    try:
        if args.diff is not None:
            paths = _diff_paths(args.diff, args.paths)
            if not paths:
                print(f"jsan: no analyzable files changed since "
                      f"{args.diff}")
                return 0
        else:
            paths = args.paths
        findings = analyze_paths(paths, cache_dir=args.cache)
    except FileNotFoundError as e:
        print(f"jsan: no such path: {e}", file=sys.stderr)
        return 2
    except SyntaxError as e:
        print(f"jsan: cannot parse {e.filename}:{e.lineno}: {e.msg}",
              file=sys.stderr)
        return 2
    except RuntimeError as e:
        print(f"jsan: {e}", file=sys.stderr)
        return 2
    except Exception:
        # an analyzer bug must read as "no verdict", never as "clean"
        # or "findings" — dump the traceback and use the error exit
        print("jsan: internal error:", file=sys.stderr)
        traceback.print_exc(file=sys.stderr)
        return 2

    if args.write_baseline:
        with open(args.write_baseline, "w", encoding="utf-8") as f:
            json.dump(make_baseline(findings), f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"jsan: wrote {len(findings)} finding(s) to "
              f"{args.write_baseline}")
        return 0

    baselined = 0
    stale: list[tuple[str, str, str]] = []
    if not args.no_baseline:
        try:
            baseline = load_baseline(args.baseline)
        except FileNotFoundError:
            baseline = set()
        except (json.JSONDecodeError, KeyError, TypeError) as e:
            print(f"jsan: bad baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2
        live_keys = {f.baseline_key for f in findings}
        stale = sorted(baseline - live_keys)
        kept = apply_baseline(findings, baseline)
        baselined = len(findings) - len(kept)
        findings = kept

        if args.prune_baseline:
            pruned = make_baseline([])
            pruned["entries"] = [{"rule": r, "path": p_, "snippet": s}
                                 for r, p_, s in sorted(
                                     baseline & live_keys)]
            with open(args.baseline, "w", encoding="utf-8") as f:
                json.dump(pruned, f, indent=2, sort_keys=True)
                f.write("\n")
            print(f"jsan: pruned {len(stale)} stale entr"
                  f"{'y' if len(stale) == 1 else 'ies'} from "
                  f"{args.baseline} ({len(baseline) - len(stale)} kept)")
            return 0

    if args.format == "json":
        print(json.dumps(
            {"version": 1, "count": len(findings),
             "baselined": baselined, "rules": rule_names(),
             "findings": [f.as_dict() for f in findings]},
            indent=2, sort_keys=True))
    elif args.format == "sarif":
        print(json.dumps(_sarif(findings), indent=2, sort_keys=True))
    else:
        for f in findings:
            print(f"{f.path}:{f.line}:{f.col + 1}: [{f.rule}] {f.message}")
            if f.snippet:
                print(f"    {f.snippet}")
            print(f"    id: {f.finding_id}")
        tail = f" ({baselined} baselined)" if baselined else ""
        print(f"jsan: {len(findings)} finding(s){tail}")

    if stale and args.fail_stale:
        for r, p_, s in stale:
            print(f"jsan: stale baseline entry [{r}] {p_}: {s!r} "
                  f"(run --prune-baseline)", file=sys.stderr)
        return 1
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
