"""jsan CLI: ``python -m rlgpuschedule_tpu.analysis [paths...]``.

Exit codes: 0 clean (after suppressions + baseline), 1 findings, 2 bad
invocation. The default baseline is ``jsan_baseline.json`` in the
current directory when it exists (the committed grandfather list — see
README "Static analysis"); ``--no-baseline`` shows everything,
``--write-baseline`` regenerates the file from the current findings.
"""
from __future__ import annotations

import argparse
import json
import sys

from .engine import (analyze_paths, apply_baseline, load_baseline,
                     make_baseline)
from .rules import all_rules, rule_names

DEFAULT_BASELINE = "jsan_baseline.json"


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m rlgpuschedule_tpu.analysis",
        description="jsan: JAX-pitfall static analyzer (see README "
                    "'Static analysis' for rules and workflow)")
    p.add_argument("paths", nargs="*", default=["rlgpuschedule_tpu"],
                   help="files or directories to analyze (default: "
                        "rlgpuschedule_tpu)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help=f"baseline JSON of grandfathered findings "
                        f"(default: {DEFAULT_BASELINE}; silently empty "
                        f"when the file does not exist)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline file")
    p.add_argument("--write-baseline", metavar="PATH", default=None,
                   help="write the current findings as a baseline to "
                        "PATH and exit 0")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.name}: {rule.summary}")
        return 0

    try:
        findings = analyze_paths(args.paths)
    except FileNotFoundError as e:
        print(f"jsan: no such path: {e}", file=sys.stderr)
        return 2
    except SyntaxError as e:
        print(f"jsan: cannot parse {e.filename}:{e.lineno}: {e.msg}",
              file=sys.stderr)
        return 2

    if args.write_baseline:
        with open(args.write_baseline, "w", encoding="utf-8") as f:
            json.dump(make_baseline(findings), f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"jsan: wrote {len(findings)} finding(s) to "
              f"{args.write_baseline}")
        return 0

    baselined = 0
    if not args.no_baseline:
        try:
            baseline = load_baseline(args.baseline)
        except FileNotFoundError:
            baseline = set()
        except (json.JSONDecodeError, KeyError, TypeError) as e:
            print(f"jsan: bad baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2
        kept = apply_baseline(findings, baseline)
        baselined = len(findings) - len(kept)
        findings = kept

    if args.format == "json":
        print(json.dumps(
            {"version": 1, "count": len(findings),
             "baselined": baselined, "rules": rule_names(),
             "findings": [f.as_dict() for f in findings]},
            indent=2, sort_keys=True))
    else:
        for f in findings:
            print(f"{f.path}:{f.line}:{f.col + 1}: [{f.rule}] {f.message}")
            if f.snippet:
                print(f"    {f.snippet}")
        tail = f" ({baselined} baselined)" if baselined else ""
        print(f"jsan: {len(findings)} finding(s){tail}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
