"""Runtime performance sentinels: the dynamic half of jsan.

The static rules (:mod:`.rules`) catch what local evidence can prove;
these two sentinels catch what it can't:

- :class:`CompileCounter` — counts XLA traces and backend compiles via
  ``jax.monitoring`` event listeners. The contract it enforces
  (tests/test_sentinels.py): the fused update step compiles **exactly
  once** across geometry-stable iterations. A shape-unstable argument,
  an unhashable closure capture, or a rebuilt function object all show
  up here as steady-state compiles — the recompile-per-step failure
  mode that erases a bench win without failing a test.
- :func:`no_implicit_transfers` — ``jax.transfer_guard("disallow")``
  scoped as a context: inside it, any *implicit* host↔device transfer
  raises. Wrapped around a hot loop it proves the loop is device-
  resident (explicit ``jax.device_put``/``device_get`` remain allowed,
  so deliberate materialization at loop boundaries still works).

Both are cheap enough for the ``sanitize`` tier-1 subset — neither
re-executes programs the way ``jax_debug_nans`` does.
"""
from __future__ import annotations

import contextlib

import jax

# every XLA backend compile fires this duration event; every jaxpr trace
# fires the trace event even when the *persistent* compilation cache
# serves the executable (conftest enables that cache, so a warm CI run
# may legitimately see traces without backend compiles — steady-state
# assertions must require BOTH to be zero, which assert_no_recompiles
# does)
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"


class RecompileSentinelError(AssertionError):
    """A region that must be compile-free traced or compiled."""


class CompileCounter:
    """Context manager counting traces + backend compiles in its scope.

    Usage (the geometry-stable contract)::

        step(state, batch)                 # warmup: compiles once
        with CompileCounter() as c:
            for _ in range(n):
                state, _ = step(state, batch)
        assert c.total == 0, c.events

    Counts are global to the process (jax.monitoring has no per-program
    attribution), so keep input construction — ``jnp.ones``, key splits,
    anything that dispatches its own tiny program — outside the scope.
    """

    def __init__(self):
        self.backend_compiles = 0
        self.traces = 0
        self.events: list[str] = []
        self._listener = None

    @property
    def total(self) -> int:
        return self.backend_compiles + self.traces

    def __enter__(self) -> "CompileCounter":
        def listener(event: str, duration: float, **kwargs) -> None:
            if event == BACKEND_COMPILE_EVENT:
                self.backend_compiles += 1
                self.events.append(event)
            elif event == TRACE_EVENT:
                self.traces += 1
                self.events.append(event)

        self._listener = listener
        jax.monitoring.register_event_duration_secs_listener(listener)
        return self

    def __exit__(self, *exc) -> None:
        # unregistration is a private API; degrade to a dead listener
        # (self-deactivating closure) if it moves
        try:
            from jax._src import monitoring as _monitoring
            _monitoring._unregister_event_duration_listener_by_callback(
                self._listener)
        except (ImportError, AttributeError, ValueError):  # pragma: no cover
            self.backend_compiles = self.traces = -1
        self._listener = None


@contextlib.contextmanager
def assert_no_recompiles(what: str = "region"):
    """Assert a region neither traces nor compiles (post-warmup steady
    state). Raises :class:`RecompileSentinelError` naming the events."""
    with CompileCounter() as counter:
        yield counter
    if counter.total > 0:
        raise RecompileSentinelError(
            f"{what} expected zero compilation activity but saw "
            f"{counter.traces} trace(s) and {counter.backend_compiles} "
            f"backend compile(s): a geometry-stable hot loop is "
            f"recompiling (shape-unstable args, rebuilt function object, "
            f"or unhashable static capture)")


def no_implicit_transfers():
    """``jax.transfer_guard("disallow")`` as a readable name: inside,
    implicit host↔device transfers raise; explicit device_put/device_get
    stay legal. Wrap hot loops in perf/sanitize tests to prove device
    residency."""
    return jax.transfer_guard("disallow")
