"""jsan's value-lifetime and escape model (ISSUE 18 tentpole).

PR 17's arena data plane made buffer *lifetime* a correctness surface:
submit memcpys into a recycled slab block, scatter hands back views
into the engine's actions buffer, and the block recycles right after —
so a view that outlives its block reads torn or recycled memory with no
exception to point at the bug. The four lifetime rules share this one
per-module model, built the same way :mod:`.concurrency` builds the
thread model: once per :class:`~.engine.ModuleContext`, from local
evidence only.

**View sources** — the calls whose results alias storage someone else
may reclaim:

1. ``<ring>.take_block()`` — an arena block reservation (the block and
   everything reached through it: ``blk.obs``, ``blk.futures[:n]``);
2. ``np.frombuffer(buf, ...)`` — a zero-copy view over ``buf``;
3. ``scatter_results(actions, n)`` — per-request views into one batched
   actions buffer (``serve/batching.py``'s documented contract).

**Propagation** — a view taints what it flows into, with two strengths.
Aliases, subscripts/slices, attribute loads, view-preserving ndarray
methods (``reshape``/``ravel``/``transpose``/...), and forwarders that
are documented not to copy (``np.asarray``, ``jax.tree.map`` /
``unflatten``) stay **strong**: the result provably aliases the source.
An opaque helper call that merely *receives* a strong view (``n_live =
self._seal_block(blk)``) yields a **weak** result: it might be a view,
might be a scalar — weak values only count when later *dereferenced*
(subscripted / attribute-loaded), never on bare name uses, so a count
returned past a recycle does not fire. Copies end the chain:
``.copy()``, ``np.array``, ``np.copy``, ``np.ascontiguousarray``,
``bytes``/``float``/``int`` conversions.

**Kill points** — after which a tainted value reads reclaimed storage:

1. ``<ring>.recycle(blk)`` / ``blk.reset()`` — kills the block and every
   view derived from it;
2. ``sock.recv_into(buf)`` / ``reader.readinto(buf)`` — the next recv
   into the SAME buffer object invalidates outstanding ``frombuffer``
   views over it (rebinding ``buf = sock.recv(n)`` does NOT: the old
   ``bytes`` stays alive under the old view);
3. a dispatch of a ``jax.jit(..., donate_argnums=...)`` program (tracked
   by the concurrency model) marks the names passed at donated
   positions dead — unless the dispatch's own assignment rebinds them
   (``state = step(state)`` is the blessed idiom).

**Escapes** — where a strong view outliving the function becomes
someone else's problem: returned (allowed when the function's docstring
documents the view contract — the repo convention ``_arena_views`` and
``scatter_results`` follow, mirroring the ``make_*`` naming contract),
stored on ``self`` or into a ``self`` container, or captured by a
nested function that is itself returned, stored, or handed to a thread.

Control flow is block-structured, not linear: an ``except`` handler
that recycles and re-raises does not poison the happy path below it,
branch kills merge only from branches that fall through, and loop
bodies are analyzed once (a back-edge use-before-recycle is the
documented recall limit — the runtime ``may_share_memory`` defence in
``_scatter_arena`` backstops it).
"""
from __future__ import annotations

import ast
import dataclasses

from .concurrency import model_for as _concurrency_model
from .engine import ModuleContext

_FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

# ndarray methods whose result aliases the receiver
_VIEW_METHODS = {"reshape", "view", "ravel", "transpose", "squeeze",
                 "swapaxes", "byteswap"}
# calls that end a taint chain (their result owns fresh storage)
_FRESH_CALLS = {"numpy.array", "numpy.copy", "numpy.ascontiguousarray",
                "bytes", "bytearray", "float", "int", "bool", "str",
                "len", "repr", "tuple", "dict", "set", "sum", "min",
                "max", "abs", "range", "sorted"}
# calls documented NOT to copy: result aliases any view argument
_FORWARDERS = {"numpy.asarray", "numpy.atleast_1d", "numpy.atleast_2d",
               "numpy.reshape", "numpy.ravel", "numpy.transpose",
               "numpy.squeeze", "jax.tree.map", "jax.tree.unflatten",
               "jax.tree_util.tree_map", "jax.tree_util.tree_unflatten",
               "zip", "enumerate", "reversed", "iter", "list"}
_RECV_INTO = {"recv_into", "readinto", "readinto1", "recv_bytes_into"}
_CONTAINER_ADD = {"append", "add", "insert", "extend", "appendleft"}
_PUBLISH = {"put", "put_nowait"}


@dataclasses.dataclass
class View:
    """One tracked value: where it came from and what it aliases."""
    kind: str              # "block" | "frombuffer" | "scatter" | "derived"
    root: int              # family id — kills apply to the whole family
    origin: ast.AST        # the source call node
    label: str             # e.g. "ring.take_block()"
    strong: bool
    buffer: str | None = None   # frombuffer: backing buffer name

    def derived(self, strong: "bool | None" = None) -> "View":
        return View(kind="derived", root=self.root, origin=self.origin,
                    label=self.label,
                    strong=self.strong if strong is None else strong,
                    buffer=self.buffer)


@dataclasses.dataclass
class Escape:
    node: ast.AST          # the escaping statement/expression
    view: View
    how: str               # "returned" | "stored on self.x" | ...
    fn: ast.AST
    documented: bool       # enclosing docstring documents a view contract


@dataclasses.dataclass
class DeadUse:
    node: ast.AST          # the use
    view: View
    kill: ast.AST          # the statement that reclaimed the storage
    kill_label: str
    fn: ast.AST


@dataclasses.dataclass
class Publish:
    node: ast.AST          # the .put()/submit/Thread call
    view: View
    channel: str           # e.g. "self._q.put"
    fn: ast.AST


@dataclasses.dataclass
class DonatedUse:
    node: ast.AST          # the post-dispatch use
    name: str
    dispatch: ast.AST      # the donating call
    fn: ast.AST


class _State:
    """Per-path abstract state: live views, killed families, donated-dead
    names. Cheap to fork at branches, merged at join points."""

    __slots__ = ("live", "killed", "donated", "terminated")

    def __init__(self):
        self.live: dict[str, View] = {}
        self.killed: dict[int, tuple[ast.AST, str]] = {}
        self.donated: dict[str, ast.Call] = {}
        self.terminated = False

    def fork(self) -> "_State":
        st = _State()
        st.live = dict(self.live)
        st.killed = dict(self.killed)
        st.donated = dict(self.donated)
        return st

    def merge(self, *others: "_State") -> None:
        """Join with sibling paths: kills/donations union over every
        path that falls through; a terminated path contributes nothing
        (its recycle cannot precede the code below the join)."""
        for other in others:
            if other.terminated:
                continue
            self.live.update({k: v for k, v in other.live.items()
                              if k not in self.live})
            self.killed.update(other.killed)
            self.donated.update(other.donated)


class LifetimeModel:
    """Escapes, dead uses, publishes, and donated-alias reuses for ONE
    module (built once per :class:`ModuleContext`, shared by the four
    lifetime rules)."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.cmodel = _concurrency_model(ctx)
        self.escapes: list[Escape] = []
        self.dead_uses: list[DeadUse] = []
        self.publishes: list[Publish] = []
        self.donated_uses: list[DonatedUse] = []
        self.has_sources = False
        self._global_names = {
            t.id for n in ctx.tree.body
            if isinstance(n, (ast.Assign, ast.AnnAssign))
            for t in (n.targets if isinstance(n, ast.Assign)
                      else [n.target])
            if isinstance(t, ast.Name)}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._analyze_fn(node)

    # -- helpers ------------------------------------------------------------
    def _expr_text(self, node: ast.AST) -> str:
        try:
            return ast.unparse(node)
        except Exception:
            return "<expr>"

    def _docstring_documents_views(self, fn: ast.AST) -> bool:
        doc = ast.get_docstring(fn, clean=False) or ""
        return "view" in doc.lower()

    def _donate_positions(self, jit_site: ast.AST) -> tuple[int, ...]:
        if not isinstance(jit_site, ast.Call):
            return ()
        for kw in jit_site.keywords:
            if kw.arg == "donate_argnums":
                try:
                    val = ast.literal_eval(kw.value)
                except ValueError:
                    return ()
                if isinstance(val, int):
                    return (val,)
                if isinstance(val, (tuple, list)):
                    return tuple(v for v in val if isinstance(v, int))
        return ()

    # -- value classification ----------------------------------------------
    def _value_view(self, expr: ast.AST, st: _State) -> View | None:
        if isinstance(expr, ast.Name):
            return st.live.get(expr.id)
        if isinstance(expr, ast.Starred):
            return self._value_view(expr.value, st)
        if isinstance(expr, ast.Subscript):
            base = self._value_view(expr.value, st)
            return base.derived() if base is not None else None
        if isinstance(expr, ast.Attribute):
            base = self._value_view(expr.value, st)
            return base.derived() if base is not None else None
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            for elt in expr.elts:
                v = self._value_view(elt, st)
                if v is not None:
                    return v.derived()
            return None
        if isinstance(expr, ast.IfExp):
            return (self._value_view(expr.body, st)
                    or self._value_view(expr.orelse, st))
        if isinstance(expr, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
            # [l[:bucket] for l in blk.obs] — a container of views when
            # the iterable is tracked
            for gen in expr.generators:
                v = self._value_view(gen.iter, st)
                if v is not None:
                    return v.derived()
            return None
        if isinstance(expr, ast.Call):
            return self._call_view(expr, st)
        return None

    def _call_view(self, call: ast.Call, st: _State) -> View | None:
        func = call.func
        resolved = self.ctx.resolve(func)
        if resolved is not None and resolved in _FRESH_CALLS:
            return None
        if isinstance(func, ast.Attribute):
            if func.attr == "take_block":
                self.has_sources = True
                return View(kind="block", root=id(call), origin=call,
                            label=f"{self._expr_text(func)}()",
                            strong=True)
            recv = self._value_view(func.value, st)
            if recv is not None:
                if func.attr in ("copy", "tolist", "tobytes", "item",
                                 "sum", "mean", "get"):
                    return None
                if func.attr in _VIEW_METHODS:
                    return recv.derived()
        if resolved == "numpy.frombuffer":
            buf = (call.args[0].id if call.args
                   and isinstance(call.args[0], ast.Name) else None)
            self.has_sources = True
            return View(kind="frombuffer", root=id(call), origin=call,
                        label=f"np.frombuffer({buf or '...'})",
                        strong=True, buffer=buf)
        is_scatter = (isinstance(func, ast.Name)
                      and func.id == "scatter_results") or (
                          resolved is not None
                          and resolved.endswith(".scatter_results"))
        if is_scatter:
            base = (self._value_view(call.args[0], st)
                    if call.args else None)
            self.has_sources = True
            return View(kind="scatter",
                        root=base.root if base is not None else id(call),
                        origin=call, label="scatter_results(...)",
                        strong=True)
        # forwarders alias their view arguments; anything else that
        # receives a strong view yields only a weak "maybe a view"
        tracked = self._tracked_args(call, st)
        if not tracked:
            return None
        best = max(tracked, key=lambda v: v.strong)
        if resolved is not None and resolved in _FORWARDERS:
            return best.derived()
        if best.strong:
            return best.derived(strong=False)
        return None

    def _tracked_args(self, call: ast.Call, st: _State) -> list[View]:
        out: list[View] = []
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            for node in ast.walk(arg):
                if isinstance(node, _FuncNode):
                    break
                if isinstance(node, ast.Name) and node.id in st.live:
                    out.append(st.live[node.id])
        return out

    # -- use scanning -------------------------------------------------------
    def _iter_loads(self, expr: ast.AST):
        """Name loads in an expression, not descending into nested
        function bodies (they execute later, on their own analysis)."""
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, _FuncNode):
                continue
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                yield node
            stack.extend(ast.iter_child_nodes(node))

    def _is_deref(self, name_node: ast.AST) -> bool:
        """Is this Name dereferenced as array data (subscript, attribute,
        slice) rather than merely mentioned? Weak views only fire here."""
        parent = self.ctx.parents.get(name_node)
        return isinstance(parent, (ast.Subscript, ast.Attribute,
                                   ast.Starred))

    def _check_uses(self, expr: ast.AST | None, st: _State,
                    fn: ast.AST) -> None:
        if expr is None:
            return
        for name in self._iter_loads(expr):
            view = st.live.get(name.id)
            if view is not None and view.root in st.killed:
                if view.strong or self._is_deref(name):
                    kill, label = st.killed[view.root]
                    self.dead_uses.append(DeadUse(
                        node=name, view=view, kill=kill,
                        kill_label=label, fn=fn))
            if name.id in st.donated:
                self.donated_uses.append(DonatedUse(
                    node=name, name=name.id,
                    dispatch=st.donated[name.id], fn=fn))

    # -- donated dispatch ---------------------------------------------------
    def _donated_dispatches(self, expr: ast.AST, st: _State):
        """(call, donated arg names) for dispatches of tracked donated
        programs inside ``expr``."""
        for node in ast.walk(expr):
            if isinstance(node, _FuncNode):
                continue
            if not isinstance(node, ast.Call):
                continue
            tok = self.cmodel.value_token(node.func, node)
            if tok is None or tok not in self.cmodel.donated:
                continue
            positions = self._donate_positions(self.cmodel.donated[tok])
            if not positions:
                continue
            if any(isinstance(a, ast.Starred) for a in node.args):
                continue   # splatted args: positions unknowable
            names = [node.args[i].id for i in positions
                     if i < len(node.args)
                     and isinstance(node.args[i], ast.Name)]
            if names:
                yield node, names

    # -- escapes ------------------------------------------------------------
    def _record_escape(self, node: ast.AST, view: View, how: str,
                       fn: ast.AST) -> None:
        if not view.strong:
            return
        self.escapes.append(Escape(
            node=node, view=view, how=how, fn=fn,
            documented=self._docstring_documents_views(fn)))

    def _escape_target(self, target: ast.AST) -> str | None:
        """A store target that outlives the function: ``self.x``,
        ``self.x[k]``, or a module-level global."""
        if isinstance(target, ast.Attribute) \
                and isinstance(target.value, ast.Name) \
                and target.value.id == "self":
            return f"self.{target.attr}"
        if isinstance(target, ast.Subscript):
            return self._escape_target(target.value)
        if isinstance(target, ast.Name) \
                and target.id in self._global_names:
            return target.id
        return None

    # -- function walk ------------------------------------------------------
    def _analyze_fn(self, fn: ast.AST) -> None:
        st = _State()
        self._exec_block(fn.body, st, fn)
        self._closure_pass(fn, st)

    def _exec_block(self, stmts, st: _State, fn: ast.AST) -> None:
        for stmt in stmts:
            if st.terminated:
                break
            self._exec_stmt(stmt, st, fn)

    def _bind(self, target: ast.AST, view: View | None,
              st: _State) -> None:
        if isinstance(target, ast.Name):
            st.donated.pop(target.id, None)
            if view is not None:
                st.live[target.id] = view
            else:
                st.live.pop(target.id, None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt, view.derived() if view is not None
                           else None, st)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, view, st)

    def _exec_stmt(self, stmt: ast.AST, st: _State, fn: ast.AST) -> None:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            if value is None:
                return
            self._check_uses(value, st, fn)
            bound = self._bound_names(stmt)
            for call, names in self._donated_dispatches(value, st):
                for name in names:
                    if name not in bound:
                        st.donated[name] = call
            view = self._value_view(value, st)
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for target in targets:
                dest = self._escape_target(target)
                if dest is not None and view is not None:
                    self._record_escape(stmt, view,
                                        f"stored on {dest}", fn)
                self._bind(target, view, st)
        elif isinstance(stmt, ast.Expr):
            self._check_uses(stmt.value, st, fn)
            if isinstance(stmt.value, ast.Call):
                self._exec_call_stmt(stmt.value, st, fn)
            for call, names in self._donated_dispatches(stmt.value, st):
                for name in names:
                    st.donated[name] = call
        elif isinstance(stmt, ast.Return):
            self._check_uses(stmt.value, st, fn)
            if stmt.value is not None:
                view = self._value_view(stmt.value, st)
                if view is not None:
                    self._record_escape(stmt, view, "returned", fn)
            st.terminated = True
        elif isinstance(stmt, ast.Raise):
            self._check_uses(stmt.exc, st, fn)
            st.terminated = True
        elif isinstance(stmt, (ast.Break, ast.Continue)):
            st.terminated = True
        elif isinstance(stmt, ast.If):
            self._check_uses(stmt.test, st, fn)
            then = st.fork()
            other = st.fork()
            self._exec_block(stmt.body, then, fn)
            self._exec_block(stmt.orelse, other, fn)
            if then.terminated and other.terminated and stmt.orelse:
                st.terminated = True
            st.merge(then, other)
        elif isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            if isinstance(stmt, ast.While):
                self._check_uses(stmt.test, st, fn)
            else:
                self._check_uses(stmt.iter, st, fn)
                view = self._value_view(stmt.iter, st)
                self._bind(stmt.target, view.derived()
                           if view is not None else None, st)
            body = st.fork()
            self._exec_block(stmt.body, body, fn)
            st.merge(body)
            self._exec_block(stmt.orelse, st, fn)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._check_uses(item.context_expr, st, fn)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars,
                               self._value_view(item.context_expr, st), st)
            self._exec_block(stmt.body, st, fn)
        elif isinstance(stmt, ast.Try):
            body = st.fork()
            self._exec_block(stmt.body, body, fn)
            exits = [body]
            for handler in stmt.handlers:
                h = st.fork()
                self._exec_block(handler.body, h, fn)
                exits.append(h)
            if all(e.terminated for e in exits):
                st.terminated = True
            st.merge(*exits)
            self._exec_block(stmt.finalbody, st, fn)
            self._exec_block(stmt.orelse, st, fn)
        elif isinstance(stmt, ast.Delete):
            for target in stmt.targets:
                if isinstance(target, ast.Name):
                    st.live.pop(target.id, None)
                    st.donated.pop(target.id, None)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            pass   # nested defs: handled by _closure_pass + own analysis
        elif isinstance(stmt, (ast.Assert, ast.Global, ast.Nonlocal,
                               ast.Pass, ast.Import, ast.ImportFrom,
                               ast.ClassDef)):
            if isinstance(stmt, ast.Assert):
                self._check_uses(stmt.test, st, fn)

    def _bound_names(self, stmt: ast.AST) -> set[str]:
        targets = (stmt.targets if isinstance(stmt, ast.Assign)
                   else [stmt.target])
        out: set[str] = set()
        for t in targets:
            for node in ast.walk(t):
                if isinstance(node, ast.Name):
                    out.add(node.id)
        return out

    def _exec_call_stmt(self, call: ast.Call, st: _State,
                        fn: ast.AST) -> None:
        func = call.func
        if not isinstance(func, ast.Attribute):
            return
        attr = func.attr
        # kill: <ring>.recycle(blk) — the block family dies here
        if attr == "recycle" and call.args \
                and isinstance(call.args[0], ast.Name):
            view = st.live.get(call.args[0].id)
            if view is not None:
                st.killed[view.root] = (
                    call, self._expr_text(call))
            return
        # kill: blk.reset() on a tracked block
        if attr == "reset" and isinstance(func.value, ast.Name):
            view = st.live.get(func.value.id)
            if view is not None and view.kind == "block":
                st.killed[view.root] = (call, self._expr_text(call))
            return
        # kill: the next recv into the same buffer invalidates
        # outstanding frombuffer views over it
        if attr in _RECV_INTO and call.args \
                and isinstance(call.args[0], ast.Name):
            buf = call.args[0].id
            for view in st.live.values():
                if view.buffer == buf:
                    st.killed[view.root] = (
                        call, self._expr_text(call))
            return
        # escape: self.cache.append(view) — stored past the call frame
        if attr in _CONTAINER_ADD:
            dest = self._escape_target(func.value)
            if dest is not None:
                for arg in call.args:
                    view = self._value_view(arg, st)
                    if view is not None:
                        self._record_escape(
                            call, view, f"appended to {dest}", fn)
            return
        # publish: view handed to another thread through a queue, an
        # executor, or a Thread target closure
        if attr in _PUBLISH:
            for arg in call.args:
                view = self._value_view(arg, st)
                if view is not None and view.strong:
                    self.publishes.append(Publish(
                        node=call, view=view,
                        channel=f"{self._expr_text(func)}()", fn=fn))
            return
        if attr == "submit":
            for arg in call.args:
                if isinstance(arg, ast.Lambda):
                    for name in self._iter_loads(arg.body):
                        view = st.live.get(name.id)
                        if view is not None and view.strong:
                            self.publishes.append(Publish(
                                node=call, view=view,
                                channel=f"{self._expr_text(func)}()",
                                fn=fn))
                else:
                    view = self._value_view(arg, st)
                    if view is not None and view.strong:
                        self.publishes.append(Publish(
                            node=call, view=view,
                            channel=f"{self._expr_text(func)}()", fn=fn))

    # -- closure captures ---------------------------------------------------
    def _closure_pass(self, fn: ast.AST, st: _State) -> None:
        """A nested def that captures a strong view AND is returned,
        stored on self, or handed to a thread escapes the view with it.
        ``st`` is the fall-through exit state; captures are judged
        against every name the function ever tracked, which is
        conservative in the right direction for closures (they run
        later)."""
        nested = [n for n in fn.body
                  if isinstance(n, (ast.FunctionDef,
                                    ast.AsyncFunctionDef))]
        if not nested:
            return
        for inner in nested:
            captured: View | None = None
            params = {a.arg for a in inner.args.args
                      + inner.args.posonlyargs + inner.args.kwonlyargs}
            for name in self._iter_loads_in_fn(inner):
                if name.id in params:
                    continue
                view = st.live.get(name.id)
                if view is not None and view.strong:
                    captured = view
                    break
            if captured is None:
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Return) \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id == inner.name:
                    self._record_escape(
                        node, captured,
                        f"captured by returned closure "
                        f"{inner.name!r}", fn)
                if isinstance(node, ast.Call):
                    name = self.ctx.resolve(node.func)
                    is_thread = (name in ("threading.Thread",
                                          "threading.Timer"))
                    is_submit = (isinstance(node.func, ast.Attribute)
                                 and node.func.attr == "submit")
                    if not (is_thread or is_submit):
                        continue
                    handed = [a for a in node.args] + [
                        kw.value for kw in node.keywords
                        if kw.arg == "target"]
                    if any(isinstance(a, ast.Name) and a.id == inner.name
                           for a in handed):
                        self.publishes.append(Publish(
                            node=node, view=captured,
                            channel=f"thread closure {inner.name!r}",
                            fn=fn))

    def _iter_loads_in_fn(self, fn: ast.AST):
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Load):
                yield node


def model_for(ctx: ModuleContext) -> LifetimeModel:
    """The module's (memoized) lifetime model — the four lifetime rules
    in one analyze_file pass share a single build."""
    model = getattr(ctx, "_jsan_lifetime", None)
    if model is None:
        model = LifetimeModel(ctx)
        ctx._jsan_lifetime = model
    return model
