"""`jsan` — JAX-pitfall static analysis + runtime performance sentinels.

The north star runs as fast as the hardware allows, and in JAX that speed
is lost *silently*: a stray ``.item()`` host sync in a hot loop, a dropped
``donate_argnums`` at a state-threading jit boundary, or a recompile per
step can erase a measured bench win without failing a single test
(Podracer arXiv:2104.06272 and Jumanji arXiv:2306.09884 both attribute
their throughput to exactly this jit/device-residency discipline). The
``sanitize`` test marker catches NaNs; this package catches
performance-correctness regressions:

- **Static pass** (``python -m rlgpuschedule_tpu.analysis [paths]``):
  AST rules grounded in this codebase's real hazards — see
  :mod:`.rules` for the rule set and :mod:`.engine` for the
  traced-region model, ``# jsan: disable=<rule>`` suppressions, and the
  committed-baseline workflow for grandfathered findings.
- **Runtime sentinels** (:mod:`.sentinels`): a compile-count monitor
  built on ``jax.monitoring`` (asserts the fused update step compiles
  exactly once across geometry-stable iterations) and a
  ``jax.transfer_guard`` context for the perf/sanitize test paths.
"""
from .engine import (Finding, SourceFile, analyze_paths, apply_baseline,
                     load_baseline, make_baseline)
from .rules import all_rules

__all__ = [
    "Finding", "SourceFile", "analyze_paths", "all_rules",
    "load_baseline", "make_baseline", "apply_baseline",
    "CompileCounter", "RecompileSentinelError", "assert_no_recompiles",
    "no_implicit_transfers",
]

_SENTINEL_NAMES = ("CompileCounter", "RecompileSentinelError",
                   "assert_no_recompiles", "no_implicit_transfers")


def __getattr__(name):
    # lazy (PEP 562): the sentinels import jax; the static pass must not —
    # `python -m rlgpuschedule_tpu.analysis` is a plain-AST lint and runs
    # in CI before anything touches an accelerator runtime
    if name in _SENTINEL_NAMES:
        from . import sentinels
        return getattr(sentinels, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
