"""jsan engine: file walking, the traced-region model, suppressions,
and the committed-baseline workflow.

The rules (:mod:`.rules`) are deliberately *local*: each looks only at
one module's AST plus the shared :class:`ModuleContext` built here. The
load-bearing piece of that context is the **traced-region model** — the
set of function definitions whose bodies execute under a ``jax`` trace,
where host syncs, Python control flow on tracers, and impure calls are
hazards. A function is traced when the module itself shows the evidence:

1. it is decorated with ``jax.jit`` / ``jax.pmap`` / ``partial(jax.jit,
   ...)`` (or an equinox ``filter_jit``);
2. its name is passed as a function argument to a tracing entry point
   (``jax.jit``, ``jax.vmap``, ``jax.lax.scan``, ``jax.lax.cond``,
   ``jax.grad``, ``shard_map``, ...);
3. it is defined *inside* a traced function (closures trace with their
   parent);
4. it is defined inside a ``make_*`` factory — this repo's convention
   (``make_train_step``, ``make_ppo_grad_step``, ``make_update_step``)
   builds step functions that are jitted by a *different* module, so the
   local evidence of (2) never appears; the naming convention is the
   contract (README "Static analysis").

Cross-module call graphs are out of scope: a helper that is only ever
called from jitted code in another file is invisible to rules 1–3. That
trades recall for precision — every finding points at local evidence —
and the runtime sentinels (:mod:`.sentinels`) backstop the recall gap.

Suppressions: ``# jsan: disable=<rule>[,<rule>...]  -- reason`` on the
flagged line, or on a comment-only line directly above it (use the
``--`` reason; an unexplained suppression is a review smell). Baseline:
findings identified by ``(rule, path, snippet)`` — the *stripped source
line*, not the line number, so the baseline survives unrelated edits
above the finding.
"""
from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
from typing import Iterable, Iterator

# directories never descended into during a tree walk (explicit file
# arguments are always analyzed — the analyzer's own test fixtures live
# under tests/fixtures/ and are scanned on purpose, one file at a time)
SKIP_DIRS = {"__pycache__", "fixtures", ".git", ".venv", "node_modules",
             "build", "dist"}

_SUPPRESS_RE = re.compile(r"#\s*jsan:\s*disable=([A-Za-z0-9_\-,]+)")


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One rule violation. Baseline identity is ``(rule, path, snippet)``
    (line numbers drift; the offending source line rarely does)."""
    path: str       # as given on the command line, posix separators
    line: int       # 1-based
    col: int        # 0-based
    rule: str
    message: str
    snippet: str    # stripped source line at ``line``
    end_line: int = 0   # 1-based; 0 when unknown (defaults to line)
    end_col: int = 0    # 0-based exclusive; 0 when unknown

    def as_dict(self) -> dict:
        return {"path": self.path, "line": self.line, "col": self.col,
                "rule": self.rule, "message": self.message,
                "snippet": self.snippet, "end_line": self.end_line,
                "end_col": self.end_col}

    @classmethod
    def from_dict(cls, d: dict) -> "Finding":
        return cls(path=d["path"], line=d["line"], col=d["col"],
                   rule=d["rule"], message=d["message"],
                   snippet=d["snippet"],
                   end_line=d.get("end_line", 0),
                   end_col=d.get("end_col", 0))

    @property
    def baseline_key(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.snippet)

    @property
    def finding_id(self) -> str:
        """Stable ID ``<rule>@<path>@<hash>``: the snippet hash makes it
        line-drift-proof (same identity the baseline uses), short enough
        to paste into a bug report or a CI annotation."""
        digest = hashlib.sha1(self.snippet.encode("utf-8")).hexdigest()[:8]
        return f"{self.rule}@{self.path}@{digest}"


class SourceFile:
    """Parsed module + per-line suppression table."""

    def __init__(self, path: str, text: str):
        self.path = path
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self.suppressions = self._parse_suppressions()

    def _parse_suppressions(self) -> dict[int, set[str]]:
        out: dict[int, set[str]] = {}
        for i, raw in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(raw)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            # a comment-only line suppresses the next line; an inline
            # trailer suppresses its own line
            target = i + 1 if raw.lstrip().startswith("#") else i
            out.setdefault(target, set()).update(rules)
        return out

    def suppressed(self, line: int, rule: str) -> bool:
        active = self.suppressions.get(line, ())
        return rule in active or "all" in active

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, node: ast.AST, rule: str, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        end_line = getattr(node, "end_lineno", None) or line
        end_col = getattr(node, "end_col_offset", None)
        if end_col is None or (end_line == line and end_col <= col):
            end_col = col + 1
        return Finding(path=self.path, line=line, col=col, rule=rule,
                       message=message, snippet=self.snippet(line),
                       end_line=end_line, end_col=end_col)


# ---------------------------------------------------------------------------
# module context: import aliasing, parent links, traced regions

# tracing entry points: a function passed (positionally) to any of these
# executes under a trace
_TRACING_ENTRY = {
    "jax.jit", "jax.pmap", "jax.vmap", "jax.grad", "jax.value_and_grad",
    "jax.checkpoint", "jax.remat", "jax.linearize", "jax.custom_jvp",
    "jax.custom_vjp", "jax.lax.scan", "jax.lax.map", "jax.lax.cond",
    "jax.lax.while_loop", "jax.lax.fori_loop", "jax.lax.switch",
    "jax.lax.associative_scan", "jax.experimental.shard_map.shard_map",
    "shard_map", "equinox.filter_jit",
}

_JIT_DECORATORS = {"jax.jit", "jax.pmap", "equinox.filter_jit"}

_FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


class ModuleContext:
    """Shared per-module analysis state handed to every rule."""

    def __init__(self, src: SourceFile):
        self.src = src
        self.tree = src.tree
        self.parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.aliases = self._import_aliases()
        self.functions_by_name: dict[str, list[ast.FunctionDef]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions_by_name.setdefault(node.name, []).append(node)
        self.traced = self._traced_functions()

    # -- imports ------------------------------------------------------------
    def _import_aliases(self) -> dict[str, str]:
        aliases: dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return aliases

    def resolve(self, node: ast.AST) -> str | None:
        """Canonical dotted name of a Name/Attribute chain, with import
        aliases expanded (``jnp.mean`` -> ``jax.numpy.mean``, ``np.array``
        -> ``numpy.array``). None for anything else (calls on calls,
        subscripts, ...)."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(self.aliases.get(node.id, node.id))
        return ".".join(reversed(parts))

    def resolve_call(self, call: ast.Call) -> str | None:
        return self.resolve(call.func)

    # -- tree helpers -------------------------------------------------------
    def enclosing_function(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None and not isinstance(cur, _FuncNode):
            cur = self.parents.get(cur)
        return cur

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    # -- traced-region model ------------------------------------------------
    def _decorator_name(self, dec: ast.AST) -> str | None:
        # @jax.jit / @partial(jax.jit, ...) / @functools.partial(jax.jit,..)
        if isinstance(dec, ast.Call):
            name = self.resolve(dec.func)
            if name in ("functools.partial", "partial") and dec.args:
                return self.resolve(dec.args[0])
            return name
        return self.resolve(dec)

    def _traced_functions(self) -> set[ast.AST]:
        roots: set[ast.AST] = set()
        # (1) decorated tracing entry points
        for fns in self.functions_by_name.values():
            for fn in fns:
                for dec in fn.decorator_list:
                    name = self._decorator_name(dec)
                    if name in _JIT_DECORATORS or name in _TRACING_ENTRY:
                        roots.add(fn)
        # (2) names passed to tracing entry points; lambdas likewise
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            name = self.resolve_call(node)
            if name not in _TRACING_ENTRY:
                continue
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    roots.update(self.functions_by_name.get(arg.id, ()))
                elif isinstance(arg, ast.Lambda):
                    roots.add(arg)
        # (4) defs inside a make_* factory (repo convention: factories
        # return step functions jitted elsewhere — module docstring)
        for fns in self.functions_by_name.values():
            for fn in fns:
                if fn.name.startswith("make_"):
                    for child in ast.walk(fn):
                        if child is not fn and isinstance(child, _FuncNode):
                            roots.add(child)
        # (3) closure propagation: defs nested inside traced functions
        traced = set(roots)
        for node in ast.walk(self.tree):
            if isinstance(node, _FuncNode) and node not in traced:
                if any(a in traced for a in self.ancestors(node)
                       if isinstance(a, _FuncNode)):
                    traced.add(node)
        # fixpoint for deeper nesting (ast.walk order is not outer-first)
        changed = True
        while changed:
            changed = False
            for node in ast.walk(self.tree):
                if isinstance(node, _FuncNode) and node not in traced:
                    if any(a in traced for a in self.ancestors(node)
                           if isinstance(a, _FuncNode)):
                        traced.add(node)
                        changed = True
        return traced

    def in_traced_region(self, node: ast.AST) -> bool:
        fn = self.enclosing_function(node)
        while fn is not None:
            if fn in self.traced:
                return True
            fn = self.enclosing_function(fn)
        return False


# ---------------------------------------------------------------------------
# incremental cache (ISSUE 18): per-file findings keyed on the file's
# content hash AND a rule-set hash (the sha1 of every analysis-package
# source), so editing any rule/model/engine file invalidates everything.
# Only LOCAL rules are cached — cross-file rules (refusal-drift,
# contract-drift) read sibling files whose edits a per-file key cannot
# see, so they re-run every time.

def ruleset_hash() -> str:
    h = hashlib.sha1()
    pkg = os.path.dirname(os.path.abspath(__file__))
    for root, dirs, files in os.walk(pkg):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for f in sorted(files):
            if f.endswith(".py"):
                with open(os.path.join(root, f), "rb") as fh:
                    h.update(f.encode())
                    h.update(fh.read())
    return h.hexdigest()


class FindingCache:
    """Findings from local rules, one JSON file per (path, content sha,
    rule-set sha). Corrupt or unreadable entries read as misses."""

    def __init__(self, directory: str):
        self.directory = directory
        self.rules_sha = ruleset_hash()
        os.makedirs(directory, exist_ok=True)
        self.hits = 0
        self.misses = 0

    def _entry(self, path: str, text_sha: str) -> str:
        key = hashlib.sha1(
            f"{os.path.abspath(path)}\0{text_sha}\0{self.rules_sha}"
            .encode()).hexdigest()
        return os.path.join(self.directory, f"{key}.json")

    def get(self, path: str, text_sha: str) -> "list[Finding] | None":
        try:
            with open(self._entry(path, text_sha),
                      encoding="utf-8") as f:
                data = json.load(f)
            findings = [Finding.from_dict(d) for d in data["findings"]]
        except (OSError, ValueError, KeyError, TypeError):
            return None
        self.hits += 1
        return findings

    def put(self, path: str, text_sha: str,
            findings: "list[Finding]") -> None:
        self.misses += 1
        tmp = self._entry(path, text_sha)
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"version": 1,
                           "findings": [x.as_dict() for x in findings]},
                          f, sort_keys=True)
        except OSError:
            pass   # a read-only cache dir degrades to always-miss


# ---------------------------------------------------------------------------
# driver

def iter_py_files(paths: Iterable[str]) -> Iterator[str]:
    for path in paths:
        if os.path.isfile(path):
            yield path
        elif os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(d for d in dirs
                                 if d not in SKIP_DIRS
                                 and not d.startswith("."))
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)
        else:
            raise FileNotFoundError(path)


def analyze_file(path: str, rules=None,
                 cache: "FindingCache | None" = None) -> list[Finding]:
    from .rules import all_rules
    rules = all_rules() if rules is None else rules
    with open(path, encoding="utf-8") as f:
        text = f.read()
    src = SourceFile(path.replace(os.sep, "/"), text)
    ctx = ModuleContext(src)
    local = [r for r in rules if not r.cross_file]
    cross = [r for r in rules if r.cross_file]

    def run(subset) -> list[Finding]:
        out: list[Finding] = []
        for rule in subset:
            for finding in rule.check(src, ctx):
                if not src.suppressed(finding.line, finding.rule):
                    out.append(finding)
        return out

    if cache is not None and local:
        text_sha = hashlib.sha1(text.encode("utf-8")).hexdigest()
        findings = cache.get(src.path, text_sha)
        if findings is None:
            findings = run(local)
            cache.put(src.path, text_sha, findings)
        else:
            findings = list(findings)
    else:
        findings = run(local)
    findings.extend(run(cross))
    return findings


def analyze_paths(paths: Iterable[str], rules=None,
                  cache_dir: "str | None" = None) -> list[Finding]:
    cache = FindingCache(cache_dir) if cache_dir else None
    findings: list[Finding] = []
    for path in iter_py_files(paths):
        findings.extend(analyze_file(path, rules, cache=cache))
    return sorted(findings)


# ---------------------------------------------------------------------------
# baseline

def make_baseline(findings: Iterable[Finding]) -> dict:
    entries = sorted({f.baseline_key for f in findings})
    return {"version": 1,
            "entries": [{"rule": r, "path": p, "snippet": s}
                        for r, p, s in entries]}


def load_baseline(path: str) -> set[tuple[str, str, str]]:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return {(e["rule"], e["path"], e["snippet"])
            for e in data.get("entries", ())}


def apply_baseline(findings: Iterable[Finding],
                   baseline: set[tuple[str, str, str]]) -> list[Finding]:
    return [f for f in findings if f.baseline_key not in baseline]
