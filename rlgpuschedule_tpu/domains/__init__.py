"""L6 domain-randomization layer: scenario distributions as data.

One compiled step serves the whole domain distribution — cluster
geometry, hardware speed, arrival process, and job mix are all seeded
per-env data (``DomainSchedule`` rides the existing ``faults`` slot;
trace windows come from ``traces.fit``). See README "Domain
randomization"."""
from .schedule import (DOMAIN_REGIMES, DomainDraw, DomainSchedule,
                       DomainSpec, domain_schedule, domain_stats,
                       resolve_domain, sample_domain, sample_env_domains,
                       stack_domain_schedules, validate_domain_schedule)

__all__ = [
    "DOMAIN_REGIMES", "DomainDraw", "DomainSchedule", "DomainSpec",
    "domain_schedule", "domain_stats", "resolve_domain", "sample_domain",
    "sample_env_domains", "stack_domain_schedules",
    "validate_domain_schedule",
]
