"""Domain-randomization engine (L6) — the whole scenario space as data.

``sim.faults`` proved the recipe for ONE axis of variation: schedules are
trace-like pytree DATA, so a single compiled step serves an entire fault
distribution (the Jumanji scalable-env pattern). This module extends the
same contract to every axis a production cluster varies on:

- **geometry** — per-node GPU capacity (shrunken nodes, nodes absent
  outright) carried by a new ``capacity`` array;
- **hardware speed** — heterogeneous GPU generations as per-node speed
  factors riding the EXISTING straggler ``slowdown`` array (a V100 next
  to an H100 is a permanent 2-4x straggler, so the sim/oracle stretch
  machinery applies unchanged);
- **arrival process + job mix** — offered load, diurnal cycles,
  flash-crowd bursts, and duration scaling, realized as seeded trace
  windows by ``traces.fit.gen_domain_window`` (distributions fit from
  the Philly/PAI loaders).

The carrier is :class:`DomainSchedule`: a strict superset of
:class:`~..sim.faults.FaultSchedule` (same three fault fields + per-node
``capacity``). Every fault consumer (``node_up``, ``job_stretch``,
``effective_free``, ``core.rl_step``, the oracle) reads fields by name,
so a DomainSchedule flows through the existing ``faults`` argument of
the env/rollout/experiment stack with ZERO new threading — and because
the domains path always passes a DomainSchedule (even for the identity
draw), all domain regimes share one pytree structure and therefore ONE
compiled step (CompileCounter-gated in tests/test_domains.py).

Host-side, :data:`DOMAIN_REGIMES` names the scenario distributions
(clean control, geometry shrink, hardware heterogeneity, sustained
overload, flash crowds, everything-at-once) and :func:`sample_domain`
draws seeded per-env :class:`DomainDraw`s from them — ``train
--domains`` and the ``evaluate --matrix`` generalization cross-table
both consume exactly these draws, so a matrix cell is reproducible from
``(seed, regime, n_nodes, gpus_per_node)`` alone.
"""
from __future__ import annotations

import dataclasses
import zlib
from typing import NamedTuple, Sequence

import jax
import numpy as np

from ..sim.faults import (FaultSchedule, no_faults, stack_fault_schedules,
                          validate_fault_schedule)


class DomainSchedule(NamedTuple):
    """Per-env domain data (fixed shapes): the :class:`FaultSchedule`
    triple plus per-node GPU capacity. Field ORDER keeps the fault prefix
    so duck-typed fault consumers are oblivious; a 4-leaf pytree is a
    different treedef from the 3-leaf FaultSchedule, which is exactly
    what keeps the clean-faults program and the domains program from
    silently sharing (and invalidating) each other's caches."""
    down_start: jax.Array  # f32[N, W] drain instants (+inf = unused slot)
    down_end: jax.Array    # f32[N, W] return instants (+inf = never)
    slowdown: jax.Array    # f32[N]    speed factor (faults x hardware)
    capacity: jax.Array    # i32[N]    usable GPUs per node (0 = absent)

    @property
    def n_nodes(self) -> int:
        return int(self.down_start.shape[-2])


# ---- named domain regimes ---------------------------------------------------

@dataclasses.dataclass(frozen=True)
class DomainSpec:
    """A named scenario DISTRIBUTION (static + hashable — it lives inside
    ``EnvParams.domain_process``); :func:`sample_domain` draws concrete
    seeded :class:`DomainDraw` data from it. Geometry/speed knobs shape
    the cluster; load/burst/diurnal/duration knobs shape the arrival
    process realized by ``traces.fit.gen_domain_window``."""
    name: str
    # geometry: per-node capacity ~ round(U[capacity_min_frac, 1] * G),
    # then each node absent outright with p_node_off (capacity 0)
    capacity_min_frac: float = 1.0
    p_node_off: float = 0.0
    # hardware heterogeneity: per-node chance of a permanent speed factor
    # in [slowdown_min, slowdown_max] (rides the straggler machinery)
    p_hetero: float = 0.0
    slowdown_min: float = 1.5
    slowdown_max: float = 4.0
    # arrival process: offered load ~ U[load_min, load_max]; diurnal
    # modulation; a flash crowd collapsing this fraction of the window's
    # jobs onto one burst instant
    load_min: float = 1.1
    load_max: float = 1.1
    diurnal: bool = False
    burst_frac: float = 0.0
    # job mix: duration median multiplier ~ U[min, max]
    duration_scale_min: float = 1.0
    duration_scale_max: float = 1.0

    def __post_init__(self):
        if not 0.0 < self.capacity_min_frac <= 1.0:
            raise ValueError(
                f"capacity_min_frac must be in (0, 1], got "
                f"{self.capacity_min_frac}")
        for p_name in ("p_node_off", "p_hetero", "burst_frac"):
            p = getattr(self, p_name)
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"{p_name} must be in [0, 1], got {p}")
        if self.p_node_off >= 1.0 and self.name != "_impossible":
            raise ValueError("p_node_off=1 would draw empty clusters")
        if not 1.0 <= self.slowdown_min <= self.slowdown_max:
            raise ValueError(
                f"want 1 <= slowdown_min <= slowdown_max, got "
                f"[{self.slowdown_min}, {self.slowdown_max}]")
        if not 0.0 < self.load_min <= self.load_max:
            raise ValueError(f"want 0 < load_min <= load_max, got "
                             f"[{self.load_min}, {self.load_max}]")
        if not 0.0 < self.duration_scale_min <= self.duration_scale_max:
            raise ValueError(
                f"want 0 < duration_scale_min <= duration_scale_max, got "
                f"[{self.duration_scale_min}, {self.duration_scale_max}]")


# The generalization matrix's canonical regimes: a clean control (the
# degradation denominator — load pinned at the configs' default 1.1),
# the broad training distribution, and one regime per axis so a matrix
# row localizes WHICH kind of shift breaks a policy. "overload" pins the
# BASELINE.md weakness (policy trails oracle SJF/Tiresias by ~2.3% at
# 1.6x sustained overload) as a tracked column.
DOMAIN_REGIMES: dict[str, DomainSpec] = {
    "none": DomainSpec("none"),
    "baseline": DomainSpec("baseline", load_min=0.8, load_max=1.2,
                           duration_scale_min=0.75,
                           duration_scale_max=1.5),
    "geom": DomainSpec("geom", capacity_min_frac=0.5, p_node_off=0.1,
                       load_min=0.9, load_max=1.1),
    "hetero": DomainSpec("hetero", p_hetero=0.4, load_min=0.9,
                         load_max=1.1),
    "overload": DomainSpec("overload", load_min=1.6, load_max=1.6),
    "flash": DomainSpec("flash", burst_frac=0.5, load_min=1.0,
                        load_max=1.2),
    "mixed": DomainSpec("mixed", capacity_min_frac=0.5, p_node_off=0.1,
                        p_hetero=0.4, load_min=0.8, load_max=1.4,
                        diurnal=True, burst_frac=0.25,
                        duration_scale_min=0.75, duration_scale_max=1.5),
}


def resolve_domain(spec: "DomainSpec | str") -> DomainSpec:
    if isinstance(spec, DomainSpec):
        return spec
    if spec not in DOMAIN_REGIMES:
        raise ValueError(f"unknown domain regime {spec!r}; known: "
                         f"{sorted(DOMAIN_REGIMES)}")
    return DOMAIN_REGIMES[spec]


# ---- seeded draws -----------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class DomainDraw:
    """One concrete host-side draw from a :class:`DomainSpec`: the
    cluster half (capacity/slowdown, packed into a :class:`DomainSchedule`
    by :func:`domain_schedule`) plus the arrival half (load/burst/...,
    consumed by ``experiment.make_domain_windows`` when it generates this
    env's trace windows)."""
    spec_name: str
    capacity: np.ndarray    # i32[N] usable GPUs per node
    slowdown: np.ndarray    # f32[N] hardware speed factor (>= 1)
    load: float
    duration_scale: float
    burst_frac: float
    diurnal: bool

    @property
    def total_gpus(self) -> int:
        return int(self.capacity.sum())


def sample_domain(spec: "DomainSpec | str", n_nodes: int,
                  gpus_per_node: int, seed) -> DomainDraw:
    """One seeded host-side draw. ``seed`` may be an int or a tuple of
    ints (e.g. ``(base_seed, env)``); the spec name is folded in, so one
    base seed yields independent draws per regime — the matrix's repro
    tuple is exactly ``(seed, regime, n_nodes, gpus_per_node)``."""
    spec = resolve_domain(spec)
    if n_nodes <= 0 or gpus_per_node <= 0:
        raise ValueError(f"want positive n_nodes/gpus_per_node, got "
                         f"{n_nodes}/{gpus_per_node}")
    entropy = list(seed) if isinstance(seed, (tuple, list)) else [int(seed)]
    rng = np.random.default_rng(
        [zlib.crc32(("domain:" + spec.name).encode()),
         *[int(s) & 0xFFFFFFFF for s in entropy]])
    frac = rng.uniform(spec.capacity_min_frac, 1.0, size=n_nodes)
    cap = np.maximum(np.rint(frac * gpus_per_node), 1).astype(np.int32)
    cap = np.where(rng.random(n_nodes) < spec.p_node_off, 0, cap)
    if cap.sum() == 0:
        # a zero-GPU cluster can schedule nothing; keep the draw valid by
        # forcing one full node (p_node_off < 1 makes this vanishingly
        # rare at realistic n_nodes, but tiny test clusters hit it)
        cap[0] = gpus_per_node
    hetero = rng.random(n_nodes) < spec.p_hetero
    slow = np.where(hetero, rng.uniform(spec.slowdown_min,
                                        spec.slowdown_max, size=n_nodes),
                    1.0).astype(np.float32)
    return DomainDraw(
        spec_name=spec.name, capacity=cap, slowdown=slow,
        load=float(rng.uniform(spec.load_min, spec.load_max)),
        duration_scale=float(rng.uniform(spec.duration_scale_min,
                                         spec.duration_scale_max)),
        burst_frac=spec.burst_frac, diurnal=spec.diurnal)


def sample_env_domains(spec: "DomainSpec | str", n_nodes: int,
                       gpus_per_node: int, seed: int, n_envs: int,
                       ) -> list[DomainDraw]:
    """Per-env draws for the vec-env batch: env ``e`` draws from
    ``(seed, e)``, so the batch covers the regime's distribution rather
    than replaying one cluster E times."""
    return [sample_domain(spec, n_nodes, gpus_per_node, (seed, e))
            for e in range(n_envs)]


# ---- schedules --------------------------------------------------------------

def domain_schedule(draw: DomainDraw,
                    faults: FaultSchedule | None = None) -> DomainSchedule:
    """Pack a draw's cluster half into the :class:`DomainSchedule` the
    jitted step consumes, composing with an optional per-env
    :class:`FaultSchedule` (``--domains`` and ``--faults`` stack): drain
    windows come from the fault draw, and the speed factor is the
    elementwise MAX of hardware heterogeneity and transient straggling —
    a slow GPU that also straggles runs at its worst factor, not the
    product (both model the same remaining-work stretch)."""
    n = len(draw.capacity)
    base = no_faults(n) if faults is None else faults
    if getattr(base, "n_nodes", n) != n:
        raise ValueError(f"fault schedule is shaped for {base.n_nodes} "
                         f"node(s); the domain draw has {n}")
    slow = np.maximum(np.asarray(base.slowdown, np.float32),
                      draw.slowdown).astype(np.float32)
    return DomainSchedule(
        down_start=np.asarray(base.down_start, np.float32),
        down_end=np.asarray(base.down_end, np.float32),
        slowdown=slow,
        capacity=np.asarray(draw.capacity, np.int32))


def validate_domain_schedule(n_nodes: int, gpus_per_node: int,
                             schedule: DomainSchedule) -> DomainSchedule:
    """Host-side fail-fast guard mirroring ``validate_fault_schedule``
    (which checks the fault triple) plus the capacity contract: shape
    [N], integral, within [0, gpus_per_node], and a non-empty cluster.
    Returns host numpy arrays."""
    fs = validate_fault_schedule(n_nodes, schedule)
    cap = np.asarray(schedule.capacity)
    if cap.shape != (n_nodes,):
        raise ValueError(f"domain capacity must have shape ({n_nodes},); "
                         f"got {cap.shape}")
    if not np.issubdtype(cap.dtype, np.integer):
        raise ValueError(f"domain capacity must be integral GPUs, got "
                         f"dtype {cap.dtype}")
    if (cap < 0).any() or (cap > gpus_per_node).any():
        raise ValueError(
            f"per-node capacity must lie in [0, {gpus_per_node}] (the "
            f"static gpus_per_node bound the obs/action layout is built "
            f"for); got [{int(cap.min())}, {int(cap.max())}]")
    if cap.sum() <= 0:
        raise ValueError("domain capacity sums to zero GPUs — an empty "
                         "cluster can schedule nothing")
    return DomainSchedule(fs.down_start, fs.down_end, fs.slowdown,
                          cap.astype(np.int32))


def stack_domain_schedules(schedules: Sequence[DomainSchedule],
                           ) -> DomainSchedule:
    """Stack per-env schedules into a batched device DomainSchedule
    (leading axis E) — same generic tree-stack as the fault twin."""
    return stack_fault_schedules(schedules)


def domain_stats(draw: DomainDraw) -> dict:
    """Host summary of one draw — what the matrix's ``domain_cell``
    events carry so ``obs.report`` can tell the story without re-deriving
    it from arrays."""
    cap = np.asarray(draw.capacity, np.int64)
    slow = np.asarray(draw.slowdown, np.float64)
    return {
        "spec": draw.spec_name,
        "total_gpus": int(cap.sum()),
        "n_nodes_off": int((cap == 0).sum()),
        "n_hetero": int((slow > 1.0).sum()),
        "max_slowdown": float(slow.max()) if slow.size else 1.0,
        "load": float(draw.load),
        "duration_scale": float(draw.duration_scale),
        "burst_frac": float(draw.burst_frac),
        "diurnal": bool(draw.diurnal),
    }
