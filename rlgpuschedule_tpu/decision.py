"""Shared greedy/masked policy-decision logic (eval ↔ serve).

The deterministic decision rule — greedy argmax over masked logits, with
the zero-dt stall gate that masks preempt actions past the legitimate
same-instant activity bound — used to live inline in ``eval.replay``.
The serving path (``serve/``) dispatches the SAME rule per request
batch, so the logic is extracted here and consumed by both: a change to
how actions are selected lands in evaluation and serving together, and
the two cannot drift (tests/test_serve.py pins bit-identity).

Everything here is jit-pure and shape-polymorphic over the leading
batch axis: ``mask``/``obs`` may be a single request (``[A]``) or a
batch (``[E, A]``), and pytree observation/logit structures (the
hierarchical env) pass through ``jax.tree.map`` untouched.
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np


def preempt_slice(env_params) -> jax.Array | None:
    """bool[n_actions] marking the preempt actions, or None if the flat
    action space has none (the stall gate is then a no-op)."""
    from .env.hier import HierParams
    if isinstance(env_params, HierParams) or not env_params.sim.preempt_len:
        return None
    sim = env_params.sim
    kp = sim.queue_len * sim.n_placements
    pre = np.zeros(sim.n_actions, bool)
    pre[kp:kp + sim.preempt_len] = True
    return jnp.asarray(pre)


def stall_threshold(env_params) -> int:
    """Upper bound on LEGITIMATE consecutive zero-dt decision steps.

    At one sim instant a policy can place at most ``queue_len`` distinct
    pending jobs (a placed job leaves the queue) and rearrange at most
    ``preempt_len`` running ones; anything beyond that bound within a
    single clock instant is revisiting — i.e. a place↔preempt cycle. The
    +4 keeps the bound safely above any interleaving slack."""
    sim = env_params.sim
    return sim.queue_len + sim.preempt_len + 4


def gate_stalled(mask: jax.Array, stall: jax.Array, thresh: int,
                 pre: jax.Array) -> jax.Array:
    """Mask the preempt actions of every request at/past the stall
    threshold (the eval-time place↔preempt cycle breaker — see
    ``eval.replay``'s docstring for the measured deadlock it fixes).

    ``stall`` is ``i32[]`` or ``i32[E]`` consecutive-zero-dt counts;
    ``mask`` is ``bool[A]`` or ``bool[E, A]`` — the explicit broadcast
    handles both the batched and the single-request form identically
    (and stays legal under ``jax_numpy_rank_promotion="raise"``)."""
    blocked = (jnp.expand_dims(stall >= thresh, -1)
               & jnp.broadcast_to(pre, mask.shape))
    return mask & ~blocked


def greedy_actions(logits: Any) -> Any:
    """Argmax per action head (pytree logits for the hierarchical env)."""
    return jax.tree.map(lambda lg: jnp.argmax(lg, axis=-1), logits)


def policy_decision(apply_fn: Callable, net_params: Any, obs: Any,
                    mask: Any) -> Any:
    """THE deterministic decision: masked logits -> greedy actions.

    One call site shape shared by ``eval.replay`` (per scan step) and
    ``serve.InferenceEngine`` (per request-batch dispatch)."""
    logits, _ = apply_fn(net_params, obs, mask)
    return greedy_actions(logits)


def policy_decision_full(apply_fn: Callable, net_params: Any, obs: Any,
                         mask: Any) -> tuple[Any, jax.Array, jax.Array]:
    """:func:`policy_decision` plus the behavior record the data
    flywheel logs: ``(actions, log_prob, value)``.

    The actions are computed by the IDENTICAL masked-logits -> argmax
    ops as :func:`policy_decision` (same apply, same argmax — the
    eval↔serve bit-identity contract extends to the logged path);
    ``log_prob`` is the joint greedy-action log-probability under the
    behavior params (the denominator of every later V-trace importance
    ratio — ``algos.vtrace.importance_ratios``), and ``value`` is the
    behavior critic's estimate, which continual training bootstraps the
    V-trace scan with (the same stored-behavior-value convention the
    rollout buffer uses). Used by the serving engine's capture mode and
    by the canary replay, so a served decision, its logged record, and
    a candidate's replay all go through this one rule."""
    from .algos import action_dist
    logits, value = apply_fn(net_params, obs, mask)
    actions = greedy_actions(logits)
    return actions, action_dist.log_prob(logits, actions), value
