"""The stateless jit'd inference engine behind the policy server.

Podracer's serving recipe (PAPERS.md: arXiv 2104.06272) in one class:
dedicate the device to ONE program — ``policy_step(params, obs_batch,
mask_batch) -> actions`` — compiled once per power-of-two batch bucket
with the request buffers donated at the dispatch boundary, and police
the steady state with the same sentinels that gate training
(:mod:`..analysis.sentinels`): any post-warmup trace/compile is a
``recompile`` alarm, and every post-warmup dispatch runs under
``jax.transfer_guard("disallow")`` so an implicit host sync in the hot
path fails loudly instead of silently serializing the pipeline.

The decision rule itself is :func:`..decision.policy_decision` — the
SAME function ``eval.replay`` scans over, so a served action is
bit-identical to what evaluation would replay for that observation
(tests/test_serve.py pins it). Params come from wherever the caller
restored them — the CLI resolves checkpoints through the existing
``Checkpointer`` (integrity fallback included) exactly like
``evaluate`` does, and ``select_checkpoint`` picks the step.
"""
from __future__ import annotations

from typing import Any

import jax
import numpy as np

from ..analysis.sentinels import (CompileCounter, RecompileSentinelError,
                                  no_implicit_transfers)
from ..decision import (gate_stalled, policy_decision,
                        policy_decision_full, preempt_slice,
                        stall_threshold)
from ..obs.trace import NULL_TRACER
from .batching import next_bucket, pad_batch


class InferenceEngine:
    """Bucketed, donated, sentinel-policed greedy policy inference.

    ``decide(obs, mask, stall)`` takes HOST pytrees with a leading
    request axis ``[n, ...]``, pads to the next power-of-two bucket,
    uploads explicitly (``jax.device_put`` — the transfer the guard
    allows), dispatches the jitted decision, and returns the first
    ``n`` actions as host arrays plus the bucket used.

    Compile accounting is per-bucket: the FIRST dispatch of each bucket
    size is its warmup (the compile is blessed, recorded as a
    ``compile`` event when a bus is attached — or pre-paid via
    :meth:`warmup`); any compile activity on a warmed bucket is a
    **recompile alarm**: the ``serve_recompile_alarms_total`` counter
    bumps, a ``recompile`` event is emitted, and with ``strict=True``
    the dispatch raises :class:`RecompileSentinelError`. A bench run
    asserts the counter stays at zero (ISSUE 7 acceptance).
    """

    def __init__(self, apply_fn, net_params: Any, env_params: Any = None,
                 max_bucket: int = 256, registry=None, bus=None,
                 strict: bool = False, stall_gate: bool = True,
                 tracer=None, device=None, engine_id: "int | None" = None,
                 capture: bool = False):
        from ..obs import Registry
        if max_bucket <= 0 or (max_bucket & (max_bucket - 1)):
            raise ValueError(f"max_bucket must be a positive power of "
                             f"two, got {max_bucket}")
        self.max_bucket = max_bucket
        self.strict = strict
        self.registry = registry if registry is not None else Registry()
        self._bus = bus
        self.tracer = tracer if tracer is not None else NULL_TRACER
        # placement resolved from the shared unified mesh (same device
        # walk as train/async) instead of jax's implicit default device:
        # a lone engine serves from the mesh's first device; the router
        # (serve.router, PR 13) passes one data-axis device per engine
        # (parallel.mesh.serve_devices), so a deployment that pins the
        # unified mesh to a chip subset moves the whole fleet with it.
        from ..parallel.mesh import unified_mesh
        if device is None:
            device = unified_mesh().devices.flatten()[0]
        self.device = device
        self.engine_id = engine_id
        self._serve_sharding = jax.sharding.SingleDeviceSharding(device)
        self._params = jax.device_put(net_params, self._serve_sharding)
        pre = (preempt_slice(env_params)
               if stall_gate and env_params is not None else None)
        thresh = stall_threshold(env_params) if pre is not None else 0
        self._has_stall_gate = pre is not None
        self._warmed: set[int] = set()
        self._example: "tuple[Any, Any] | None" = None
        # engine_id labels the sentinel series so N routed engines keep
        # N separate counters in ONE registry (the per-engine
        # zero-recompile contract is per engine, not fleet-aggregate)
        labels = ({"engine": str(engine_id)}
                  if engine_id is not None else None)
        self._recompiles = self.registry.counter(
            "serve_recompile_alarms_total",
            "post-warmup dispatches that traced or compiled",
            labels=labels)
        self._compiles = self.registry.counter(
            "serve_bucket_compiles_total",
            "blessed per-bucket warmup compiles", labels=labels)
        # capture mode (the data-flywheel tap): the SAME single compiled
        # program additionally returns the behavior log-prob and value
        # per row (decision.policy_decision_full) — part of the program
        # from the start, so the zero-recompile contract is untouched,
        # and the actions come from the identical masked-argmax ops, so
        # served actions stay bit-identical to the uncaptured engine
        self.capture = bool(capture)
        rule = policy_decision_full if capture else policy_decision
        # ONE jit per engine, built here and reused every dispatch (the
        # jsan recompile-hazard discipline); request buffers are donated
        # — they are per-dispatch transients, and donation lets XLA
        # reuse their pages for the outputs (the Podracer trick)
        if self._has_stall_gate:
            # stall (i32[bucket]) is deliberately NOT donated: it is the
            # one input whose shape/dtype matches the actions output, so
            # XLA aliases the two — and on the multi-device CPU backend
            # a cache-loaded aliased executable corrupts the result (the
            # same donation hazard checkpoint._fresh_copy documents).
            # The donation win lives in the big obs/mask request
            # buffers anyway.
            def _decide(params, obs, mask, stall):
                return rule(apply_fn, params, obs,
                            gate_stalled(mask, stall, thresh, pre))
            self._step = jax.jit(_decide, donate_argnums=(1, 2))
        else:
            def _decide(params, obs, mask):
                return rule(apply_fn, params, obs, mask)
            self._step = jax.jit(_decide, donate_argnums=(1, 2))

    @property
    def post_warmup_recompiles(self) -> int:
        return int(self._recompiles.value)

    @property
    def warmed_buckets(self) -> "tuple[int, ...]":
        return tuple(sorted(self._warmed))

    def bucket_for(self, n: int) -> int:
        return next_bucket(n, self.max_bucket)

    def set_params(self, net_params: Any) -> None:
        """Swap the served weights in place (the promotion pipeline's
        live-swap primitive). The new params must share the incumbent's
        pytree structure/shapes/dtypes — then the compiled per-bucket
        programs are reused as-is (params are a traced argument, never
        baked into the executable), so a swap costs one host->device
        upload and ZERO recompiles. Shape-changing "swaps" are a
        redeploy, not a swap: refuse loudly."""
        old = jax.tree.structure(self._params)
        new = jax.tree.structure(net_params)
        if old != new:
            raise ValueError(
                f"param swap changed the pytree structure ({new} vs "
                f"incumbent {old}); a structural change cannot reuse the "
                f"compiled serving programs — redeploy instead")
        for a, b in zip(jax.tree.leaves(self._params),
                        jax.tree.leaves(net_params)):
            a, b = np.asarray(a), np.asarray(b)
            if a.shape != b.shape or a.dtype != b.dtype:
                raise ValueError(
                    f"param swap changed a leaf from {a.shape}/{a.dtype} "
                    f"to {b.shape}/{b.dtype}; shape or dtype drift would "
                    f"retrace every warmed bucket — redeploy instead")
        self._params = jax.device_put(net_params, self._serve_sharding)

    def rewarm(self) -> "tuple[int, ...]":
        """Blessed re-warm after a :meth:`set_params` swap: re-dispatch
        one neutral batch through EVERY warmed bucket before the engine
        takes traffic, so any compile the swap could conceivably trigger
        fires here rather than on a live request. With the shape-stable
        swap contract this is a pure pipe-cleaning pass — zero compiles
        expected, and a compile here hits a WARMED bucket, so it counts
        as a recompile alarm (raising under ``strict``), which is
        exactly the promotion pipeline's zero-recompile proof. Returns
        the buckets re-driven. Requires a prior :meth:`warmup` (the
        stored example shapes the neutral batches)."""
        if self._example is None:
            raise RuntimeError(
                "rewarm() needs the example request stored by warmup(); "
                "warm the engine before swapping params")
        example_obs, example_mask = self._example
        driven = []
        for b in self.warmed_buckets:
            obs = jax.tree.map(
                lambda x: np.zeros((b,) + np.asarray(x).shape,
                                   np.asarray(x).dtype), example_obs)
            mask = jax.tree.map(
                lambda x: np.ones((b,) + np.asarray(x).shape,
                                  np.asarray(x).dtype), example_mask)
            self.decide(obs, mask, np.zeros(b, np.int32))
            driven.append(b)
        return tuple(driven)

    def _emit(self, kind: str, **fields) -> None:
        if self._bus is not None:
            self._bus.emit(kind, **fields)

    def _dispatch(self, obs_d, mask_d, stall_d, bucket: int):
        """One guarded dispatch at ``bucket`` (device inputs)."""
        warm = bucket not in self._warmed
        args = ((self._params, obs_d, mask_d, stall_d)
                if self._has_stall_gate
                else (self._params, obs_d, mask_d))
        with CompileCounter() as c:
            if warm:
                import warnings
                with warnings.catch_warnings():
                    # the actions output is smaller than the donated
                    # request buffers, so backends that can't repurpose
                    # the pages (CPU) warn per compile — donation is
                    # still correct (a no-op at worst), the warning is
                    # compile-time-only noise
                    warnings.filterwarnings(
                        "ignore",
                        message="Some donated buffers were not usable")
                    out = self._step(*args)
            else:
                # steady state: no implicit host<->device traffic either
                # direction — the dispatch must be pure device work
                with no_implicit_transfers():
                    out = self._step(*args)
        if c.total:
            if warm:
                self._compiles.inc()
                self._emit("compile", scope="serve", bucket=bucket,
                           traces=c.traces,
                           backend_compiles=c.backend_compiles)
            else:
                self._recompiles.inc()
                self._emit("recompile", scope="serve", bucket=bucket,
                           traces=c.traces,
                           backend_compiles=c.backend_compiles)
                if self.strict:
                    raise RecompileSentinelError(
                        f"serving dispatch at warmed bucket {bucket} "
                        f"traced/compiled ({c.traces} traces, "
                        f"{c.backend_compiles} backend compiles): a "
                        f"steady-state policy server must never "
                        f"recompile")
        self._warmed.add(bucket)
        return out

    def decide(self, obs: Any, mask: Any,
               stall: "np.ndarray | None" = None) -> "tuple[Any, int]":
        """Decide one coalesced request batch. ``obs``/``mask`` are host
        pytrees ``[n, ...]``; ``stall`` is ``i32[n]`` (ignored unless the
        action space has preempt actions). Returns ``(actions[:n] on
        host, bucket)``."""
        n = int(jax.tree.leaves(obs)[0].shape[0])
        bucket = self.bucket_for(n)
        with self.tracer.span("pad", n=n, bucket=bucket):
            obs_p = pad_batch(obs, bucket)
            mask_p = pad_batch(mask, bucket, fill_mask_true=True)
            if stall is None:
                stall = np.zeros(n, np.int32)
            stall_p = pad_batch(np.asarray(stall, np.int32), bucket)
            # explicit upload: the one host->device transfer serving
            # performs, outside the transfer-guarded dispatch by design
            obs_d = jax.device_put(obs_p, self._serve_sharding)
            mask_d = jax.device_put(mask_p, self._serve_sharding)
            stall_d = (jax.device_put(stall_p, self._serve_sharding)
                       if self._has_stall_gate else None)
        with self.tracer.span("dispatch", bucket=bucket):
            out = self._dispatch(obs_d, mask_d, stall_d, bucket)
            actions = jax.device_get(out)   # explicit download, ditto
        return jax.tree.map(lambda a: a[:n], actions), bucket

    def warmup(self, example_obs: Any, example_mask: Any,
               buckets: "tuple[int, ...]" = ()) -> "tuple[int, ...]":
        """Pre-pay the per-bucket compiles with neutral batches shaped
        from one example request (host pytrees, no leading axis). With
        no explicit ``buckets``, warms every power of two up to
        ``max_bucket`` — after this, NO live dispatch should ever
        compile. Returns the buckets warmed by this call."""
        self._example = (example_obs, example_mask)
        if not buckets:
            buckets = tuple(1 << i
                            for i in range(self.max_bucket.bit_length()))
        done = []
        for b in sorted(set(buckets)):
            if b != next_bucket(b, self.max_bucket):
                raise ValueError(f"bucket {b} is not a power of two "
                                 f"<= max_bucket={self.max_bucket}")
            if b in self._warmed:
                continue
            obs = jax.tree.map(
                lambda x: np.zeros((b,) + np.asarray(x).shape,
                                   np.asarray(x).dtype), example_obs)
            mask = jax.tree.map(
                lambda x: np.ones((b,) + np.asarray(x).shape,
                                  np.asarray(x).dtype), example_mask)
            self.decide(obs, mask, np.zeros(b, np.int32))
            done.append(b)
        return tuple(done)
