"""Multi-engine serving scale-out: the mesh-resolved engine router.

Podracer's pod-carving recipe (PAPERS.md: arXiv 2104.06272) applied to
the serving tier: "millions of users" cannot funnel through one chip,
so the unified ``Mesh(pop × data × model)`` is carved into one
:class:`~.engine.InferenceEngine` per DATA-axis device
(:func:`..parallel.mesh.serve_devices` — the axis that carries
request-batch parallelism), each with its own donated request buffers,
its own blessed per-bucket warmup, and its own CompileCounter/
transfer-guard sentinels (per-engine labeled series in ONE registry:
``serve_recompile_alarms_total{engine="i"}``). The router dispatches
each coalesced batch to the **least-loaded** active engine, so
decisions/s scales with engines instead of saturating one device.

Correctness contract (tests/test_router.py): every engine is the SAME
single-device program — identical params, identical jit, identical
decision function — so a routed fleet of N engines is **bit-identical**
to a single engine fed the same request stream, regardless of which
engine served which batch (batch-composition invariance of the policy
is pinned separately in tests/test_serve.py). That is what makes the
scale-out testable on the forced-virtual-CPU rig.

Thread safety: the router is the layer that owns device-level dispatch
concurrency. On the CPU backend all N "devices" share one XLA backend
whose compile cache and donation paths are NOT safe under concurrent
execute threads (the async_engine PR-8 finding), so CPU routing
serializes device work behind one dispatch lock — routing still
balances rows across engines (the accounting, warmup isolation, and
per-engine sentinels are all real), but wall-clock decisions/s does
not scale on CPU. On real accelerator backends the lock degrades to a
no-op and engines dispatch concurrently. Bench output carries this
caveat honestly (``serialized_dispatch_cpu``).

The autoscale loop closes here too: :class:`AutoscaleAdvisor` turns
the SLO surface the server already exports (p99, queue depth,
occupancy, shed rate) into a desired-engine-count signal with
hysteresis, and :meth:`EngineRouter.set_active` applies it live —
spin-up re-warms a drained engine with blessed compiles before it
takes traffic, drain simply stops routing to it (inflight work
completes; buckets stay warm for the next spin-up).
"""
from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from typing import Any

import jax

from ..obs.trace import NULL_TRACER
from ..parallel.mesh import serve_devices
from .batching import stack_requests
from .engine import InferenceEngine

SERVE_FAULT_KINDS = ("engine-raise", "engine-hang", "engine-slow")


class InjectedEngineFault(RuntimeError):
    """The exception an injected serving fault surfaces as — typed so
    tests and the retry hedge can tell an injected crash from a real
    one, and so the hedge provably absorbs exactly the injected set."""


@dataclasses.dataclass
class ServeFaultSpec:
    """One armed serving fault: fires on the first not-yet-fired router
    dispatch with sequence number >= ``at`` that lands on ``engine``.

    ``>=`` rather than ``==`` on purpose: unlike training iterations
    (``resilience.FaultSpec``), which engine serves dispatch N is a race
    between pump threads — an exact-match spec could miss its engine
    forever. Each spec still fires exactly once."""
    kind: str        # one of SERVE_FAULT_KINDS
    at: int          # router-global dispatch sequence number (>= fires)
    engine: int = 0  # target engine id
    fired: bool = False


def parse_serve_fault(spec: str) -> ServeFaultSpec:
    """Parse ``kind@N[:engine=E]`` (e.g. ``engine-raise@40``,
    ``engine-hang@10:engine=1``) — the serving twin of
    :func:`~..resilience.faults.parse_fault`. Raises ValueError with the
    offending spec."""
    body = spec.strip()
    engine = 0
    if ":" in body:
        body, _, opt = body.partition(":")
        key, _, val = opt.partition("=")
        if key.strip() != "engine" or not val.strip().lstrip("-").isdigit():
            raise ValueError(f"bad serve-fault option {opt!r} in {spec!r} "
                             f"(expected engine=E)")
        engine = int(val)
    kind, sep, at = body.partition("@")
    kind = kind.strip()
    if kind not in SERVE_FAULT_KINDS or not sep or not at.strip().isdigit():
        raise ValueError(
            f"bad serve-fault spec {spec!r}; expected kind@N[:engine=E] "
            f"with kind in {SERVE_FAULT_KINDS}")
    return ServeFaultSpec(kind=kind, at=int(at), engine=engine)


class ServeFaultInjector:
    """Deterministic engine-fault injection for the serving tier,
    mirroring :class:`~..resilience.faults.FaultInjector`: holds parsed
    specs, every hook is a no-op unless an armed spec matches, each spec
    fires exactly once, firings land on the event bus before the fault
    takes effect. Three kinds, one per failure shape:

    - ``engine-raise`` — the dispatch raises immediately (XLA error /
      device loss surfacing synchronously);
    - ``engine-hang`` — the dispatch stalls ``hang_s`` then raises, as a
      hang reaped by a dispatch timeout would (bounded, so tier-1 tests
      never actually hang);
    - ``engine-slow`` — the dispatch stalls ``slow_s`` then SUCCEEDS
      (brownout: the engine is slow, not wrong — health tracking must
      not eject it for latency alone).
    """

    def __init__(self, specs: "list[ServeFaultSpec]", bus=None,
                 hang_s: float = 0.2, slow_s: float = 0.05):
        self.specs = list(specs)
        self._bus = bus   # obs.EventBus (or None): fault firings
        self.hang_s = float(hang_s)
        self.slow_s = float(slow_s)
        self._lock = threading.Lock()

    def _take(self, engine: int, seq: int) -> "ServeFaultSpec | None":
        with self._lock:   # pump threads race the same spec list
            for s in self.specs:
                if s.engine == engine and seq >= s.at and not s.fired:
                    s.fired = True
                    return s
        return None

    def _emit(self, spec: ServeFaultSpec, **fields: Any) -> None:
        if self._bus is not None:
            self._bus.emit("serve_fault", fault=spec.kind, at=spec.at,
                           engine=spec.engine, **fields)

    def on_dispatch(self, engine: int, seq: int) -> None:
        """Hook the router calls right before device work for dispatch
        ``seq`` on ``engine`` (probes included — a persistent fault
        keeps failing the re-probe and the engine stays ejected)."""
        spec = self._take(engine, seq)
        if spec is None:
            return
        self._emit(spec, dispatch=seq)
        if spec.kind == "engine-slow":
            time.sleep(self.slow_s)
            return
        if spec.kind == "engine-hang":
            time.sleep(self.hang_s)
            raise InjectedEngineFault(
                f"engine {engine} hung on dispatch {seq} (injected "
                f"{spec.kind}@{spec.at}, reaped after {self.hang_s}s)")
        raise InjectedEngineFault(
            f"engine {engine} raised on dispatch {seq} (injected "
            f"{spec.kind}@{spec.at})")


@dataclasses.dataclass
class EngineStats:
    """Point-in-time per-engine routing state (:meth:`EngineRouter.stats`)."""
    engine_id: int
    device: str            # str(device) — placement, for humans/logs
    active: bool
    inflight: int          # dispatches currently on the device
    dispatches: int        # completed dispatches routed here, lifetime
    rows: int              # real request rows served, lifetime
    slots: int             # bucket rows dispatched (rows + padding)
    recompiles: int        # post-warmup recompile alarms (must stay 0)
    ejected: bool = False  # health-ejected (distinct from !active)
    consecutive_failures: int = 0

    @property
    def occupancy(self) -> "float | None":
        """Lifetime mean occupancy: real rows / bucket slots."""
        return self.rows / self.slots if self.slots else None


class EngineRouter:
    """N per-device inference engines behind one ``decide()``.

    Drop-in for a single :class:`~.engine.InferenceEngine` everywhere
    the :class:`~.batching.PolicyServer` touches one (``decide``,
    ``max_bucket``, ``bucket_for``, ``warmup``,
    ``post_warmup_recompiles``, ``warmed_buckets``), so the batching
    front end needs no interface change — point the server at a router
    and ``start(dispatchers=N)`` to keep N dispatches in flight.

    Dispatch policy: **least-loaded** — the active engine with the
    fewest inflight dispatches, ties broken by fewest lifetime rows
    served, then lowest id (deterministic; fairness is property-tested).
    Engine selection and load accounting sit behind the router's own
    lock; device work sits behind the CPU-only dispatch lock (module
    docstring).
    """

    def __init__(self, apply_fn, net_params: Any, env_params: Any = None,
                 max_bucket: int = 256, registry=None, bus=None,
                 strict: bool = False, stall_gate: bool = True,
                 tracer=None, n_engines: "int | None" = None, mesh=None,
                 fault_injector: "ServeFaultInjector | None" = None,
                 eject_after: int = 2, probe_backoff_s: float = 0.25,
                 probe_backoff_max_s: float = 8.0, clock=time.monotonic,
                 capture: bool = False):
        from ..obs import Registry
        self.registry = registry if registry is not None else Registry()
        self.tracer = tracer if tracer is not None else NULL_TRACER
        if eject_after < 1:
            raise ValueError(f"eject_after must be >= 1, got {eject_after}")
        if probe_backoff_s <= 0 or probe_backoff_max_s < probe_backoff_s:
            raise ValueError(
                f"need 0 < probe_backoff_s <= probe_backoff_max_s, got "
                f"{probe_backoff_s} / {probe_backoff_max_s}")
        devices = serve_devices(mesh)
        if n_engines is None:
            n_engines = len(devices)
        if not 1 <= n_engines <= len(devices):
            raise ValueError(
                f"n_engines={n_engines} must be in [1, {len(devices)}] "
                f"(one engine per data-axis device of the unified mesh)")
        # one engine per data-axis device, each on its own trace lane so
        # pad/dispatch spans land on per-engine tracks in the timeline
        self.capture = bool(capture)
        self.engines = [
            InferenceEngine(
                apply_fn, net_params, env_params, max_bucket=max_bucket,
                registry=self.registry, bus=bus, strict=strict,
                stall_gate=stall_gate,
                tracer=self.tracer.lane(f"engine-{i}"),
                device=devices[i], engine_id=i, capture=capture)
            for i in range(n_engines)
        ]
        self.max_bucket = max_bucket
        # PR-8 finding: XLA:CPU's backend is shared by all virtual CPU
        # devices and is unsafe under concurrent execute threads with
        # donation — serialize device work on CPU, free elsewhere
        self._on_cpu = devices[0].platform == "cpu"
        self._device_lock = (threading.Lock() if self._on_cpu
                             else contextlib.nullcontext())
        self._lock = threading.Lock()
        self._active = [True] * n_engines
        self._inflight = [0] * n_engines
        self._rows = [0] * n_engines
        self._slots = [0] * n_engines
        self._dispatch_counts = [0] * n_engines
        self._example: "tuple[Any, Any] | None" = None
        # ---- health tracking (ejection / backoff re-probe) ----------
        self._bus = bus
        self._injector = fault_injector
        self.eject_after = int(eject_after)
        self.probe_backoff_s = float(probe_backoff_s)
        self.probe_backoff_max_s = float(probe_backoff_max_s)
        self._clock = clock
        self._dispatch_seq = 0          # router-global, probes included
        self._consec_fail = [0] * n_engines
        self._ejected = [False] * n_engines
        self._eject_until = [0.0] * n_engines
        self._backoff = [float(probe_backoff_s)] * n_engines
        self._probing = [False] * n_engines
        self._eng_dispatches = [
            self.registry.counter(
                "serve_engine_dispatches_total",
                "batch dispatches routed to this engine",
                labels={"engine": str(i)})
            for i in range(n_engines)]
        self._eng_rows = [
            self.registry.counter(
                "serve_engine_rows_total",
                "real request rows served by this engine",
                labels={"engine": str(i)})
            for i in range(n_engines)]
        self._eng_occupancy = [
            self.registry.gauge(
                "serve_engine_occupancy",
                "real rows / bucket rows of this engine's last dispatch",
                labels={"engine": str(i)})
            for i in range(n_engines)]
        self._eng_failures = [
            self.registry.counter(
                "serve_engine_failures_total",
                "dispatches on this engine that raised (probe failures "
                "included)",
                labels={"engine": str(i)})
            for i in range(n_engines)]
        self._eng_ejections = [
            self.registry.counter(
                "serve_engine_ejections_total",
                "times this engine was health-ejected from routing after "
                "consecutive dispatch failures",
                labels={"engine": str(i)})
            for i in range(n_engines)]
        self._eng_readmissions = [
            self.registry.counter(
                "serve_engine_readmissions_total",
                "times this engine passed its re-probe and rejoined "
                "routing",
                labels={"engine": str(i)})
            for i in range(n_engines)]
        self._retries = self.registry.counter(
            "serve_retry_hedges_total",
            "batches retried once on a healthy engine after their first "
            "engine's dispatch failed")
        self._g_ejected = self.registry.gauge(
            "serve_engines_ejected", "engines currently health-ejected")
        self._g_total = self.registry.gauge(
            "serve_engines_total", "engines resolved from the mesh")
        self._g_active = self.registry.gauge(
            "serve_engines_active", "engines currently taking traffic")
        self._g_total.set(n_engines)
        self._g_active.set(n_engines)
        # fired after a set_active that re-warmed or resized the fleet;
        # the PolicyServer resets its service-time Ewma here so a
        # pre-swap estimate can never leak into Retry-After hints
        self._rewarm_listeners: "list[Any]" = []

    def add_rewarm_listener(self, cb) -> None:
        """Register ``cb()`` to run after :meth:`set_active` changes the
        fleet (spin-up warm or active-count change). Callbacks must be
        cheap and non-raising; they run outside the router locks."""
        self._rewarm_listeners.append(cb)

    # ---- engine-interface parity -------------------------------------

    @property
    def n_engines(self) -> int:
        return len(self.engines)

    @property
    def n_active(self) -> int:
        with self._lock:
            return sum(self._active)

    @property
    def post_warmup_recompiles(self) -> int:
        """Fleet-aggregate recompile alarms; :meth:`per_engine_recompiles`
        carries the per-engine contract (each must be 0 on its own)."""
        return sum(e.post_warmup_recompiles for e in self.engines)

    def per_engine_recompiles(self) -> "list[int]":
        return [e.post_warmup_recompiles for e in self.engines]

    @property
    def warmed_buckets(self) -> "tuple[int, ...]":
        return self.engines[0].warmed_buckets

    def bucket_for(self, n: int) -> int:
        return self.engines[0].bucket_for(n)

    def serialized_dispatch(self) -> bool:
        """True when device work is serialized behind the CPU dispatch
        lock — the honesty bit the bench carries next to its
        decisions/s-vs-engines numbers."""
        return self._on_cpu

    # ---- dispatch ----------------------------------------------------

    def _acquire(self, exclude: "int | None" = None) -> int:
        """Pick the least-loaded active, healthy engine and book an
        inflight slot (fewest inflight, then fewest lifetime rows, then
        lowest id). ``exclude`` bars the engine a retry hedge just
        failed on."""
        with self._lock:
            candidates = [i for i in range(len(self.engines))
                          if self._active[i] and not self._ejected[i]
                          and i != exclude]
            if not candidates:
                raise RuntimeError("no active healthy engines")
            eid = min(candidates,
                      key=lambda i: (self._inflight[i], self._rows[i], i))
            self._inflight[eid] += 1
            return eid

    def _release(self, eid: int, rows: int, bucket: "int | None") -> None:
        with self._lock:
            self._inflight[eid] -= 1
            if bucket is not None:        # dispatch actually completed
                self._rows[eid] += rows
                self._slots[eid] += bucket
                self._dispatch_counts[eid] += 1
                self._eng_dispatches[eid].inc()
                self._eng_rows[eid].inc(rows)
                self._eng_occupancy[eid].set(rows / bucket)

    def _dispatch_on(self, eid: int, obs: Any, mask: Any, stall,
                     n: int) -> "tuple[Any, int]":
        """One booked dispatch on engine ``eid`` (inflight slot already
        acquired; always released). The fault injector is consulted with
        a fresh router-global sequence number right before device work."""
        with self._lock:
            seq = self._dispatch_seq
            self._dispatch_seq += 1
        bucket = None
        try:
            with self._device_lock:
                if self._injector is not None:
                    self._injector.on_dispatch(eid, seq)
                actions, bucket = self.engines[eid].decide(obs, mask, stall)
        finally:
            self._release(eid, n, bucket)
        return actions, bucket

    def _note_success(self, eid: int) -> None:
        with self._lock:
            self._consec_fail[eid] = 0

    def _note_failure(self, eid: int, exc: BaseException) -> None:
        """Record one dispatch failure; eject the engine once it hits
        ``eject_after`` CONSECUTIVE failures (one transient error never
        drains capacity). Ejection arms the exponential-backoff re-probe
        and is loud: bus event, per-engine counter, lane instant."""
        fields = None
        with self._lock:
            self._eng_failures[eid].inc()
            self._consec_fail[eid] += 1
            if (not self._ejected[eid]
                    and self._consec_fail[eid] >= self.eject_after):
                self._ejected[eid] = True
                backoff = self._backoff[eid]
                self._eject_until[eid] = self._clock() + backoff
                self._backoff[eid] = min(backoff * 2,
                                         self.probe_backoff_max_s)
                self._eng_ejections[eid].inc()
                self._g_ejected.set(sum(self._ejected))
                fields = dict(engine=eid,
                              consecutive_failures=self._consec_fail[eid],
                              backoff_s=backoff,
                              error=type(exc).__name__)
        if fields is not None:
            if self._bus is not None:
                self._bus.emit("engine_eject", **fields)
            self.engines[eid].tracer.instant("eject", **fields)

    def _probe(self, eid: int) -> bool:
        """Re-probe an ejected engine: blessed re-warm (idempotent — a
        warm engine's buckets are remembered) then ONE real 1-row
        dispatch through the fault injector, straight on the engine so
        probe rows never pollute the routing row accounting. True =
        healthy, readmit."""
        if self._example is None:
            return True        # nothing to probe with; trust the retry
        obs = stack_requests([self._example[0]])
        mask = stack_requests([self._example[1]])
        try:
            with self.engines[eid].tracer.span("rewarm_probe"):
                with self._lock:
                    seq = self._dispatch_seq
                    self._dispatch_seq += 1
                with self._device_lock:
                    if self._injector is not None:
                        self._injector.on_dispatch(eid, seq)
                    self.engines[eid].warmup(*self._example)
                    self.engines[eid].decide(obs, mask, None)
            return True
        except Exception:
            with self._lock:
                self._eng_failures[eid].inc()
            return False

    def _maybe_readmit(self) -> None:
        """Give every ejected engine whose backoff has elapsed one
        re-probe; readmit on success (reset failure streak + backoff),
        push the next probe out exponentially on failure. Called at
        decide time — probes ride the request stream, no extra thread."""
        with self._lock:
            if not any(self._ejected):
                return
            now = self._clock()
            due = [i for i in range(len(self.engines))
                   if self._ejected[i] and not self._probing[i]
                   and now >= self._eject_until[i]]
            for i in due:
                self._probing[i] = True
        for i in due:
            ok = self._probe(i)
            with self._lock:
                self._probing[i] = False
                if ok:
                    self._ejected[i] = False
                    self._consec_fail[i] = 0
                    self._backoff[i] = self.probe_backoff_s
                    self._eng_readmissions[i].inc()
                    self._g_ejected.set(sum(self._ejected))
                else:
                    self._eject_until[i] = (self._clock()
                                            + self._backoff[i])
                    self._backoff[i] = min(self._backoff[i] * 2,
                                           self.probe_backoff_max_s)
            if ok:
                if self._bus is not None:
                    self._bus.emit("engine_readmit", engine=i)
                self.engines[i].tracer.instant("readmit")

    def decide(self, obs: Any, mask: Any, stall=None) -> "tuple[Any, int]":
        """One routed batch decision — same signature and result as
        :meth:`.engine.InferenceEngine.decide` (bit-identical, per the
        module-docstring contract).

        Failure path (the PR-13 no-silent-drop invariant through engine
        loss): a failed dispatch is retried ONCE on a different healthy
        engine (bounded hedge, counted in ``serve_retry_hedges_total``);
        if the retry fails too — or no healthy engine remains — the
        exception propagates, and the batching layer resolves every
        affected future with it. Nothing is ever dropped silently."""
        n = int(jax.tree.leaves(obs)[0].shape[0])
        self._maybe_readmit()
        eid = self._acquire()
        try:
            out = self._dispatch_on(eid, obs, mask, stall, n)
        except Exception as first:
            self._note_failure(eid, first)
            try:
                retry_eid = self._acquire(exclude=eid)
            except RuntimeError:
                raise first
            self._retries.inc()
            if self._bus is not None:
                self._bus.emit("serve_retry", from_engine=eid,
                               to_engine=retry_eid,
                               error=type(first).__name__)
            try:
                with self.engines[retry_eid].tracer.span(
                        "retry_hedge", from_engine=eid):
                    out = self._dispatch_on(retry_eid, obs, mask, stall, n)
            except Exception as second:
                self._note_failure(retry_eid, second)
                raise
            self._note_success(retry_eid)
            return out
        self._note_success(eid)
        return out

    # ---- warmup / live resize ----------------------------------------

    def warmup(self, example_obs: Any, example_mask: Any,
               buckets: "tuple[int, ...]" = ()) -> "tuple[int, ...]":
        """Warm every ACTIVE engine's buckets (blessed compiles), and
        remember the example so :meth:`set_active` can warm engines it
        spins up later. Returns the buckets the first engine warmed."""
        self._example = (example_obs, example_mask)
        done: "tuple[int, ...]" = ()
        for i, e in enumerate(self.engines):
            with self._lock:
                active = self._active[i]
            if not active:
                continue
            with self._device_lock:
                out = e.warmup(example_obs, example_mask, buckets)
            if i == 0:
                done = out
        return done

    def set_active(self, k: int) -> int:
        """Resize the serving fleet to the first ``k`` engines (clamped
        to ``[1, n_engines]``). Spin-up warms a cold engine FIRST (its
        compiles stay blessed — it takes no traffic until warm); drain
        just stops routing (inflight dispatches finish; the engine's
        warmed buckets are kept, so re-activation is free). Returns the
        applied count."""
        k = max(1, min(int(k), len(self.engines)))
        with self._lock:
            need_warm = [i for i in range(k)
                         if not self._active[i]
                         and self.engines[i].warmed_buckets == ()]
        if self._example is not None:
            for i in need_warm:
                with self._device_lock:
                    self.engines[i].warmup(*self._example)
        with self._lock:
            changed = bool(need_warm) or sum(self._active) != k
            for i in range(len(self.engines)):
                self._active[i] = i < k
            self._g_active.set(k)
        if changed:
            # the service-time distribution just changed (different
            # parallelism and/or freshly warmed engines) — listeners
            # drop stale learned estimates
            for cb in list(self._rewarm_listeners):
                cb()
        return k

    def swap_params(self, net_params: Any) -> "tuple[int, ...]":
        """Live fleet-wide weight swap (the promotion pipeline's apply
        step). EVERY engine — active or drained — gets the new params
        (a drained engine must never rejoin with stale weights), each
        swap under the device lock so it serializes with in-flight
        dispatches on CPU, then every WARMED engine runs a blessed
        :meth:`~.engine.InferenceEngine.rewarm` pass before traffic
        resumes: with the shape-stable swap contract that pass compiles
        nothing, and if it ever did, the compile lands on a warmed
        bucket and counts as a recompile alarm — the promotion
        pipeline's zero-recompile proof, not a hidden warmup. Fires the
        rewarm listeners last (the server's learned service-time
        estimate described the old weights' dispatch cost). Returns the
        buckets re-driven on engine 0."""
        driven: "tuple[int, ...]" = ()
        for i, e in enumerate(self.engines):
            with self._device_lock:
                e.set_params(net_params)
                if e.warmed_buckets:
                    out = e.rewarm()
                    if i == 0:
                        driven = out
        for cb in list(self._rewarm_listeners):
            cb()
        return driven

    def apply_autoscale(self, advisor: "AutoscaleAdvisor") -> int:
        """One autoscale tick: let ``advisor`` vote on the SLO surface,
        apply the (hysteresis-filtered) desired engine count live.
        Returns the active count after application."""
        return self.set_active(advisor.observe())

    # ---- introspection -----------------------------------------------

    def stats(self) -> "list[EngineStats]":
        with self._lock:
            return [EngineStats(
                engine_id=i,
                device=str(self.engines[i].device),
                active=self._active[i],
                inflight=self._inflight[i],
                dispatches=self._dispatch_counts[i],
                rows=self._rows[i],
                slots=self._slots[i],
                recompiles=self.engines[i].post_warmup_recompiles,
                ejected=self._ejected[i],
                consecutive_failures=self._consec_fail[i])
                for i in range(len(self.engines))]

    def fault_stats(self) -> dict:
        """Fleet-aggregate health numbers for bench/soak reports."""
        with self._lock:
            return {
                "failures": int(sum(c.value for c in self._eng_failures)),
                "ejections": int(sum(c.value
                                     for c in self._eng_ejections)),
                "readmissions": int(sum(c.value
                                        for c in self._eng_readmissions)),
                "retry_hedges": int(self._retries.value),
                "engines_ejected": int(sum(self._ejected)),
            }


class AutoscaleAdvisor:
    """SLO gauges -> desired engine count, with hysteresis.

    Reads the registry surface the serving stack already exports —
    ``serve_decision_latency_p99_ms``, ``serve_queue_depth``,
    ``serve_batch_occupancy``, ``serve_shed_total`` — and votes each
    :meth:`observe` tick:

    - **up** when the tail is blowing the target (p99 over
      ``p99_target_ms``), the queue is backing up past ``queue_high``,
      or ANY request was shed since the last tick (shedding is the
      loudest under-capacity signal there is);
    - **down** when capacity is clearly idle: occupancy under
      ``occupancy_low`` with an empty queue, no shedding, and p99 under
      half the target;
    - **hold** otherwise.

    A vote only moves the desired count after ``hysteresis`` CONSECUTIVE
    same-direction votes (mixed or hold votes reset the streak), so a
    steady load cannot flap the fleet — pinned by the hysteresis
    property test. The desired count is exported as the
    ``serve_autoscale_desired_engines`` gauge; resize decisions count in
    ``serve_autoscale_resizes_total``.
    """

    def __init__(self, registry, n_max: int, n_min: int = 1,
                 initial: "int | None" = None,
                 p99_target_ms: float = 50.0, queue_high: int = 64,
                 occupancy_low: float = 0.25, hysteresis: int = 3):
        if n_min < 1 or n_max < n_min:
            raise ValueError(f"need 1 <= n_min <= n_max, got "
                             f"n_min={n_min}, n_max={n_max}")
        if hysteresis < 1:
            raise ValueError(f"hysteresis must be >= 1, got {hysteresis}")
        self.registry = registry
        self.n_min = int(n_min)
        self.n_max = int(n_max)
        self.p99_target_ms = float(p99_target_ms)
        self.queue_high = int(queue_high)
        self.occupancy_low = float(occupancy_low)
        self.hysteresis = int(hysteresis)
        self.desired = (int(initial) if initial is not None else n_max)
        self.desired = max(self.n_min, min(self.desired, self.n_max))
        self._streak = 0          # signed: +k = k up votes in a row
        self._shed_seen = 0.0
        self._g_desired = registry.gauge(
            "serve_autoscale_desired_engines",
            "engine count the autoscale advisor currently wants")
        self._resizes = registry.counter(
            "serve_autoscale_resizes_total",
            "times the advisor changed its desired engine count")
        self._g_desired.set(self.desired)

    def _vote(self) -> int:
        # reading via registry.gauge() re-registers and returns the
        # shared series object — unset gauges read 0, which only ever
        # suppresses a vote, never invents pressure
        p99 = self.registry.gauge("serve_decision_latency_p99_ms").value
        depth = self.registry.gauge("serve_queue_depth").value
        occ = self.registry.gauge("serve_batch_occupancy").value
        shed = self.registry.counter("serve_shed_total").value
        shed_delta = shed - self._shed_seen
        self._shed_seen = shed
        if (shed_delta > 0 or depth > self.queue_high
                or (p99 > 0 and p99 > self.p99_target_ms)):
            return 1
        if (depth == 0 and shed_delta == 0 and occ < self.occupancy_low
                and p99 < self.p99_target_ms / 2):
            return -1
        return 0

    def observe(self) -> int:
        """One advisory tick: fold the current SLO surface into the
        hysteresis streak; return the (possibly updated) desired engine
        count. Runs the registry's pre-scrape collectors first, so the
        gauges it votes on (and the SLO burn windows) are fresh at the
        tick — callers no longer refresh by hand (ISSUE 20)."""
        self.registry.collect()
        v = self._vote()
        if v == 0:
            self._streak = 0
        elif v * self._streak >= 0:
            self._streak += v
        else:
            self._streak = v
        if abs(self._streak) >= self.hysteresis:
            new = max(self.n_min, min(self.desired + v, self.n_max))
            if new != self.desired:
                self.desired = new
                self._resizes.inc()
                self._g_desired.set(new)
            self._streak = 0
        return self.desired
