"""`serve --bench`: the latency half of the serving SLO story.

Drives a deterministic synthetic request stream through the full
engine + continuous-batching stack and reports decision-latency
percentiles, decisions/s(/chip), occupancy, and — the steady-state
contract — the post-warmup recompile count, which must be ZERO across
distinct request batch sizes inside one bucket (ISSUE 7 acceptance;
ci.sh asserts it).

Requests are real observations: a pool is built by resetting the
config's env windows and stepping them a few decisions under the same
greedy policy being served, so the benched batches look like live
cluster snapshots, not zeros.

PR 13 adds the scale-out half: :func:`run_scaleout` measures
decisions/s + shed rate vs engine count (1 vs N routed engines, each
arm an isolated router + registry), and :func:`run_soak` drives a
sustained paced request stream through a live dispatcher fleet — the
p99-drift / zero-torn-span / zero-recompile surface the ci.sh
soak-lite stage asserts on. Both carry the
``serialized_dispatch_cpu`` honesty bit: on the CPU backend the router
serializes device work (XLA:CPU thread-safety), so decisions/s does
NOT scale with engines there — the numbers prove the routing and
accounting, not CPU wall-clock scaling.
"""
from __future__ import annotations

import time
from typing import Any

import jax
import numpy as np

from ..decision import policy_decision
from ..env import env as env_lib


def default_request_sizes(bucket: int) -> "tuple[int, ...]":
    """Three distinct request counts that all coalesce to ``bucket``
    (i.e. in ``(bucket/2, bucket]``) — the acceptance shape: one
    compiled program must serve all of them without retracing. Needs
    ``bucket >= 8`` for three distinct sizes to exist comfortably."""
    if bucket < 8:
        raise ValueError(f"default request sizes need bucket >= 8 for "
                         f"three distinct sizes in (bucket/2, bucket]; "
                         f"got {bucket} — pass explicit sizes")
    return (bucket // 2 + 1, (3 * bucket) // 4, bucket)


def build_request_pool(apply_fn, net_params: Any, env_params: Any,
                       traces: Any, steps: int = 4,
                       faults: Any = None) -> "list[tuple[Any, Any]]":
    """Materialize a pool of (obs, mask) request rows by stepping the
    env batch ``steps`` decisions under the greedy policy — every pool
    entry is a cluster state the policy actually reaches. Host pytrees,
    no leading axis; pool order is (step, env) row-major."""
    state, ts = env_lib.vec_reset(env_params, traces, faults)
    obs, mask = ts.obs, ts.action_mask
    pool: list[tuple[Any, Any]] = []

    def rows(o, m):
        o, m = jax.device_get((o, m))
        n = jax.tree.leaves(o)[0].shape[0]
        for i in range(n):
            pool.append((jax.tree.map(lambda x: np.asarray(x)[i], o),
                         jax.tree.map(lambda x: np.asarray(x)[i], m)))

    rows(obs, mask)
    for _ in range(max(steps, 0)):
        actions = policy_decision(apply_fn, net_params, obs, mask)
        state, ts = env_lib.vec_step(env_params, state, traces, actions,
                                     faults=faults)
        obs, mask = ts.obs, ts.action_mask
        rows(obs, mask)
    return pool


def run_bench(engine, server, pool: "list[tuple[Any, Any]]",
              rounds: int = 24,
              request_sizes: "tuple[int, ...] | None" = None) -> dict:
    """Serve ``rounds`` coalesced dispatches, cycling the request sizes
    and the pool deterministically, inline-pumped so every dispatch's
    composition is exactly the round's request size. Returns the SLO
    report (and leaves the same numbers in the server's registry for
    the scrape endpoint / .prom snapshot)."""
    if rounds <= 0:
        raise ValueError(f"rounds must be positive, got {rounds}")
    if not pool:
        raise ValueError("empty request pool")
    if request_sizes is None:
        request_sizes = default_request_sizes(engine.max_bucket)
    request_sizes = tuple(int(s) for s in request_sizes)
    if any(s <= 0 for s in request_sizes):
        raise ValueError(f"request sizes must be positive: "
                         f"{request_sizes}")
    buckets = sorted({engine.bucket_for(s) for s in request_sizes})

    # pre-pay the per-bucket compiles so the measured rounds are pure
    # steady state — after this, ANY compile is an alarm
    obs0, mask0 = pool[0]
    engine.warmup(obs0, mask0, buckets=tuple(buckets))
    warm_recompiles = engine.post_warmup_recompiles

    cursor = 0
    futures = []
    for r in range(rounds):
        k = request_sizes[r % len(request_sizes)]
        for _ in range(k):
            obs, mask = pool[cursor % len(pool)]
            futures.append(server.submit(obs, mask))
            cursor += 1
        server.pump()
    results = [f.result(timeout=60) for f in futures]

    snap = server.slo_snapshot()
    return {
        "rounds": rounds,
        "request_sizes": list(request_sizes),
        "buckets": [int(b) for b in buckets],
        "pool_size": len(pool),
        "post_warmup_recompiles":
            engine.post_warmup_recompiles - warm_recompiles,
        "warmed_buckets": [int(b) for b in engine.warmed_buckets],
        **snap,
        "requests": len(results),
    }


def run_scaleout(apply_fn, net_params: Any, env_params: Any,
                 pool: "list[tuple[Any, Any]]", *, max_bucket: int,
                 rounds: int = 24,
                 request_sizes: "tuple[int, ...] | None" = None,
                 engine_counts: "tuple[int, ...]" = (1, 2),
                 deadline_s: "float | None" = None) -> dict:
    """Decisions/s + shed rate vs engine count: one isolated arm per
    count in ``engine_counts`` (fresh router + registry + server, so
    arms share nothing), each serving the SAME deterministic request
    stream through ``engines`` live dispatcher threads. Per-arm output
    carries per-engine row shares and recompile counts; the top level
    carries the CPU-serialization caveat (module docstring)."""
    from ..obs import Registry
    from .batching import DeadlineSheddedError, PolicyServer
    from .router import EngineRouter

    if request_sizes is None:
        request_sizes = default_request_sizes(max_bucket)
    request_sizes = tuple(int(s) for s in request_sizes)
    obs0, mask0 = pool[0]
    arms = []
    serialized = None
    for k in engine_counts:
        reg = Registry()
        router = EngineRouter(apply_fn, net_params, env_params,
                              max_bucket=max_bucket, registry=reg,
                              n_engines=int(k))
        serialized = router.serialized_dispatch()
        buckets = tuple(sorted({router.bucket_for(s)
                                for s in request_sizes}))
        router.warmup(obs0, mask0, buckets=buckets)
        server = PolicyServer(router, registry=reg)
        server.start(dispatchers=int(k))
        futures, shed, cursor = [], 0, 0
        t0 = time.perf_counter()
        for r in range(rounds):
            for _ in range(request_sizes[r % len(request_sizes)]):
                obs, mask = pool[cursor % len(pool)]
                futures.append(server.submit(obs, mask,
                                             deadline_s=deadline_s))
                cursor += 1
        for f in futures:
            try:
                f.result(timeout=120)
            except DeadlineSheddedError:
                shed += 1
        wall = time.perf_counter() - t0
        server.stop()
        total_rows = sum(s.rows for s in router.stats()) or 1
        arms.append({
            "engines": int(k),
            "requests": len(futures),
            "served": len(futures) - shed,
            "shed": shed,
            "shed_rate": shed / len(futures),
            "decisions_per_s": (len(futures) - shed) / wall,
            "wall_s": wall,
            "per_engine_rows": [s.rows for s in router.stats()],
            "per_engine_row_share": [s.rows / total_rows
                                     for s in router.stats()],
            "per_engine_dispatches": [s.dispatches
                                      for s in router.stats()],
            "per_engine_occupancy": [s.occupancy
                                     for s in router.stats()],
            "per_engine_recompiles": router.per_engine_recompiles(),
        })
    return {
        "engine_counts": [int(k) for k in engine_counts],
        "rounds": rounds,
        "request_sizes": list(request_sizes),
        "deadline_s": deadline_s,
        "serialized_dispatch_cpu": bool(serialized),
        "caveat": ("CPU backend serializes device dispatch behind one "
                   "lock (XLA:CPU thread-safety) — decisions/s does not "
                   "scale with engines here; routing/occupancy/shed "
                   "accounting is what this measures"
                   if serialized else None),
        "arms": arms,
    }


def run_soak(server, pool: "list[tuple[Any, Any]]", *,
             duration_s: float = 6.0, rate_hz: float = 200.0,
             deadline_s: "float | None" = None, router=None,
             advisor=None, advisor_every_s: float = 0.5) -> dict:
    """Sustained-load soak through a RUNNING server (caller started the
    dispatchers): pace submissions at ``rate_hz`` for ``duration_s``,
    optionally attaching a per-request ``deadline_s`` (shedding active)
    and an autoscale loop (every ``advisor_every_s``: refresh the SLO
    gauges, let ``advisor`` vote, apply to ``router``). Reports
    first-half vs second-half p99 — the drift surface the soak-lite CI
    stage bounds (an unbounded queue or a leak shows up as second-half
    p99 runaway)."""
    from .batching import DeadlineSheddedError

    if advisor is not None and router is None:
        raise ValueError("autoscale soak needs the router to apply "
                         "advisor votes to")
    interval = 1.0 / float(rate_hz)
    futures = []
    cursor = 0
    resizes = 0
    t_start = time.perf_counter()
    next_t = t_start
    next_tick = t_start + advisor_every_s
    while time.perf_counter() - t_start < duration_s:
        obs, mask = pool[cursor % len(pool)]
        futures.append(server.submit(obs, mask, deadline_s=deadline_s))
        cursor += 1
        now = time.perf_counter()
        if advisor is not None and now >= next_tick:
            server.slo_snapshot()       # refresh the gauges it reads
            before = advisor.desired
            router.apply_autoscale(advisor)
            resizes += int(advisor.desired != before)
            next_tick += advisor_every_s
        next_t += interval
        sleep = next_t - time.perf_counter()
        if sleep > 0:
            time.sleep(sleep)
    lat_s: "list[float | None]" = []
    shed = 0
    for f in futures:
        try:
            lat_s.append(f.result(timeout=120).latency_s)
        except DeadlineSheddedError:
            shed += 1
            lat_s.append(None)
    wall = time.perf_counter() - t_start

    def p99_ms(xs):
        xs = [x for x in xs if x is not None]
        return (float(np.percentile(np.asarray(xs), 99) * 1e3)
                if xs else None)

    half = len(lat_s) // 2
    p99_a, p99_b = p99_ms(lat_s[:half]), p99_ms(lat_s[half:])
    out = {
        "requests": len(futures),
        "served": len(futures) - shed,
        "shed": shed,
        "shed_rate": shed / max(len(futures), 1),
        "duration_s": wall,
        "rate_hz": rate_hz,
        "deadline_s": deadline_s,
        "p99_first_half_ms": p99_a,
        "p99_second_half_ms": p99_b,
        "p99_drift": (p99_b / p99_a
                      if p99_a and p99_b and p99_a > 0 else None),
        "autoscale_resizes": resizes if advisor is not None else None,
    }
    if router is not None:
        out["per_engine_rows"] = [s.rows for s in router.stats()]
        out["per_engine_occupancy"] = [s.occupancy
                                       for s in router.stats()]
        out["per_engine_recompiles"] = router.per_engine_recompiles()
        out["engines_active"] = router.n_active
        out["serialized_dispatch_cpu"] = router.serialized_dispatch()
    return out


def fit_paced_gaps(fit, n: int, seed, rate_hz: float) -> np.ndarray:
    """Inter-arrival gaps carrying a fitted workload's arrival SHAPE at
    a chosen offered rate: realize one seeded window from ``fit``
    (:func:`~..traces.fit.gen_domain_window` — the same arrival process
    the simulator replays), take its inter-arrival gaps, and rescale
    them so the mean gap is exactly ``1/rate_hz``. The soak then pounds
    the server with the trace's burstiness, not a metronome — idle
    stretches and pile-ups included — while the offered load stays the
    configured number. Deterministic per (fit, seed)."""
    from ..traces.fit import gen_domain_window

    if n < 1:
        raise ValueError(f"need at least one gap, got n={n}")
    if rate_hz <= 0:
        raise ValueError(f"rate_hz must be positive, got {rate_hz}")
    win = gen_domain_window(fit, n_jobs=n + 1, seed=seed, n_gpus=8,
                            load=1.0)
    gaps = np.maximum(np.diff(win.submit.astype(np.float64)), 0.0)
    mean = float(gaps.mean())
    if mean <= 0:       # degenerate window (all-burst); fall back flat
        return np.full(n, 1.0 / rate_hz)
    return gaps * ((1.0 / rate_hz) / mean)


def run_chaos_soak(server, pool: "list[tuple[Any, Any]]", *, fit,
                   duration_s: float = 6.0, rate_hz: float = 150.0,
                   deadline_s: "float | None" = None, router=None,
                   seed: int = 0) -> dict:
    """:func:`run_soak` graduated to chaos: replay-paced load
    (:func:`fit_paced_gaps` — the fitted trace's arrival process, not a
    fixed interval) through a RUNNING dispatcher fleet while a
    :class:`~.router.ServeFaultInjector` (attached to the router by the
    caller) fails engines mid-run. Every future is awaited with a bound
    and bucketed into exactly one of served / shed / failed, so the
    report carries the conservation invariant directly::

        submitted == served + shed + failed      (failed must be 0:
        the retry hedge absorbs injected engine faults)

    plus the exactly-once counter cross-check (``registry_shed_total``
    must equal the shed futures actually observed) and the router's
    ejection/readmission/hedge story (:meth:`~.router.EngineRouter.
    fault_stats`)."""
    from .batching import DeadlineSheddedError

    n_gaps = max(int(duration_s * rate_hz * 2) + 16, 1)
    gaps = fit_paced_gaps(fit, n_gaps, seed=(seed, 0xC7A05),
                          rate_hz=rate_hz)
    futures = []
    cursor = 0
    t_start = time.perf_counter()
    next_t = t_start
    while time.perf_counter() - t_start < duration_s:
        obs, mask = pool[cursor % len(pool)]
        futures.append(server.submit(obs, mask, deadline_s=deadline_s))
        next_t += gaps[cursor % len(gaps)]
        cursor += 1
        sleep = next_t - time.perf_counter()
        if sleep > 0:
            time.sleep(sleep)
    lat_s: "list[float | None]" = []
    shed = 0
    failed = 0
    failure_kinds: dict[str, int] = {}
    for f in futures:
        try:
            lat_s.append(f.result(timeout=30).latency_s)
        except DeadlineSheddedError:
            shed += 1
            lat_s.append(None)
        except Exception as e:   # incl. a hung future's TimeoutError
            failed += 1
            kind = type(e).__name__
            failure_kinds[kind] = failure_kinds.get(kind, 0) + 1
            lat_s.append(None)
    wall = time.perf_counter() - t_start
    served = len(futures) - shed - failed

    def p99_ms(xs):
        xs = [x for x in xs if x is not None]
        return (float(np.percentile(np.asarray(xs), 99) * 1e3)
                if xs else None)

    half = len(lat_s) // 2
    p99_a, p99_b = p99_ms(lat_s[:half]), p99_ms(lat_s[half:])
    reg = server.registry
    out = {
        "requests": len(futures),
        "served": served,
        "shed": shed,
        "failed": failed,
        "failure_kinds": failure_kinds,
        "conservation_ok": len(futures) == served + shed + failed,
        "registry_requests_total": int(
            reg.counter("serve_requests_total").value),
        "registry_shed_total": int(reg.counter("serve_shed_total").value),
        "shed_rate": shed / max(len(futures), 1),
        "duration_s": wall,
        "rate_hz": rate_hz,
        "arrival_fit": fit.name,
        "deadline_s": deadline_s,
        "p99_first_half_ms": p99_a,
        "p99_second_half_ms": p99_b,
        "p99_drift": (p99_b / p99_a
                      if p99_a and p99_b and p99_a > 0 else None),
    }
    if router is not None:
        out["fault_stats"] = router.fault_stats()
        out["per_engine_rows"] = [s.rows for s in router.stats()]
        out["per_engine_recompiles"] = router.per_engine_recompiles()
        out["engines_active"] = router.n_active
        out["serialized_dispatch_cpu"] = router.serialized_dispatch()
    return out
