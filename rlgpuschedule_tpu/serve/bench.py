"""`serve --bench`: the latency half of the serving SLO story.

Drives a deterministic synthetic request stream through the full
engine + continuous-batching stack and reports decision-latency
percentiles, decisions/s(/chip), occupancy, and — the steady-state
contract — the post-warmup recompile count, which must be ZERO across
distinct request batch sizes inside one bucket (ISSUE 7 acceptance;
ci.sh asserts it).

Requests are real observations: a pool is built by resetting the
config's env windows and stepping them a few decisions under the same
greedy policy being served, so the benched batches look like live
cluster snapshots, not zeros.
"""
from __future__ import annotations

from typing import Any

import jax
import numpy as np

from ..decision import policy_decision
from ..env import env as env_lib


def default_request_sizes(bucket: int) -> "tuple[int, ...]":
    """Three distinct request counts that all coalesce to ``bucket``
    (i.e. in ``(bucket/2, bucket]``) — the acceptance shape: one
    compiled program must serve all of them without retracing. Needs
    ``bucket >= 8`` for three distinct sizes to exist comfortably."""
    if bucket < 8:
        raise ValueError(f"default request sizes need bucket >= 8 for "
                         f"three distinct sizes in (bucket/2, bucket]; "
                         f"got {bucket} — pass explicit sizes")
    return (bucket // 2 + 1, (3 * bucket) // 4, bucket)


def build_request_pool(apply_fn, net_params: Any, env_params: Any,
                       traces: Any, steps: int = 4,
                       faults: Any = None) -> "list[tuple[Any, Any]]":
    """Materialize a pool of (obs, mask) request rows by stepping the
    env batch ``steps`` decisions under the greedy policy — every pool
    entry is a cluster state the policy actually reaches. Host pytrees,
    no leading axis; pool order is (step, env) row-major."""
    state, ts = env_lib.vec_reset(env_params, traces, faults)
    obs, mask = ts.obs, ts.action_mask
    pool: list[tuple[Any, Any]] = []

    def rows(o, m):
        o, m = jax.device_get((o, m))
        n = jax.tree.leaves(o)[0].shape[0]
        for i in range(n):
            pool.append((jax.tree.map(lambda x: np.asarray(x)[i], o),
                         jax.tree.map(lambda x: np.asarray(x)[i], m)))

    rows(obs, mask)
    for _ in range(max(steps, 0)):
        actions = policy_decision(apply_fn, net_params, obs, mask)
        state, ts = env_lib.vec_step(env_params, state, traces, actions,
                                     faults=faults)
        obs, mask = ts.obs, ts.action_mask
        rows(obs, mask)
    return pool


def run_bench(engine, server, pool: "list[tuple[Any, Any]]",
              rounds: int = 24,
              request_sizes: "tuple[int, ...] | None" = None) -> dict:
    """Serve ``rounds`` coalesced dispatches, cycling the request sizes
    and the pool deterministically, inline-pumped so every dispatch's
    composition is exactly the round's request size. Returns the SLO
    report (and leaves the same numbers in the server's registry for
    the scrape endpoint / .prom snapshot)."""
    if rounds <= 0:
        raise ValueError(f"rounds must be positive, got {rounds}")
    if not pool:
        raise ValueError("empty request pool")
    if request_sizes is None:
        request_sizes = default_request_sizes(engine.max_bucket)
    request_sizes = tuple(int(s) for s in request_sizes)
    if any(s <= 0 for s in request_sizes):
        raise ValueError(f"request sizes must be positive: "
                         f"{request_sizes}")
    buckets = sorted({engine.bucket_for(s) for s in request_sizes})

    # pre-pay the per-bucket compiles so the measured rounds are pure
    # steady state — after this, ANY compile is an alarm
    obs0, mask0 = pool[0]
    engine.warmup(obs0, mask0, buckets=tuple(buckets))
    warm_recompiles = engine.post_warmup_recompiles

    cursor = 0
    futures = []
    for r in range(rounds):
        k = request_sizes[r % len(request_sizes)]
        for _ in range(k):
            obs, mask = pool[cursor % len(pool)]
            futures.append(server.submit(obs, mask))
            cursor += 1
        server.pump()
    results = [f.result(timeout=60) for f in futures]

    snap = server.slo_snapshot()
    return {
        "rounds": rounds,
        "request_sizes": list(request_sizes),
        "buckets": [int(b) for b in buckets],
        "pool_size": len(pool),
        "post_warmup_recompiles":
            engine.post_warmup_recompiles - warm_recompiles,
        "warmed_buckets": [int(b) for b in engine.warmed_buckets],
        **snap,
        "requests": len(results),
    }
