"""`serve --bench`: the latency half of the serving SLO story.

Drives a deterministic synthetic request stream through the full
engine + continuous-batching stack and reports decision-latency
percentiles, decisions/s(/chip), occupancy, and — the steady-state
contract — the post-warmup recompile count, which must be ZERO across
distinct request batch sizes inside one bucket (ISSUE 7 acceptance;
ci.sh asserts it).

Requests are real observations: a pool is built by resetting the
config's env windows and stepping them a few decisions under the same
greedy policy being served, so the benched batches look like live
cluster snapshots, not zeros.

PR 13 adds the scale-out half: :func:`run_scaleout` measures
decisions/s + shed rate vs engine count (1 vs N routed engines, each
arm an isolated router + registry), and :func:`run_soak` drives a
sustained paced request stream through a live dispatcher fleet — the
p99-drift / zero-torn-span / zero-recompile surface the ci.sh
soak-lite stage asserts on. Both carry the
``serialized_dispatch_cpu`` honesty bit: on the CPU backend the router
serializes device work (XLA:CPU thread-safety), so decisions/s does
NOT scale with engines there — the numbers prove the routing and
accounting, not CPU wall-clock scaling.
"""
from __future__ import annotations

import os
import time
from typing import Any

import jax
import numpy as np

from ..decision import policy_decision
from ..env import env as env_lib


def default_request_sizes(bucket: int) -> "tuple[int, ...]":
    """Three distinct request counts that all coalesce to ``bucket``
    (i.e. in ``(bucket/2, bucket]``) — the acceptance shape: one
    compiled program must serve all of them without retracing. Needs
    ``bucket >= 8`` for three distinct sizes to exist comfortably."""
    if bucket < 8:
        raise ValueError(f"default request sizes need bucket >= 8 for "
                         f"three distinct sizes in (bucket/2, bucket]; "
                         f"got {bucket} — pass explicit sizes")
    return (bucket // 2 + 1, (3 * bucket) // 4, bucket)


def build_request_pool(apply_fn, net_params: Any, env_params: Any,
                       traces: Any, steps: int = 4,
                       faults: Any = None) -> "list[tuple[Any, Any]]":
    """Materialize a pool of (obs, mask) request rows by stepping the
    env batch ``steps`` decisions under the greedy policy — every pool
    entry is a cluster state the policy actually reaches. Host pytrees,
    no leading axis; pool order is (step, env) row-major."""
    state, ts = env_lib.vec_reset(env_params, traces, faults)
    obs, mask = ts.obs, ts.action_mask
    pool: list[tuple[Any, Any]] = []

    def rows(o, m):
        o, m = jax.device_get((o, m))
        n = jax.tree.leaves(o)[0].shape[0]
        for i in range(n):
            pool.append((jax.tree.map(lambda x: np.asarray(x)[i], o),
                         jax.tree.map(lambda x: np.asarray(x)[i], m)))

    rows(obs, mask)
    for _ in range(max(steps, 0)):
        actions = policy_decision(apply_fn, net_params, obs, mask)
        state, ts = env_lib.vec_step(env_params, state, traces, actions,
                                     faults=faults)
        obs, mask = ts.obs, ts.action_mask
        rows(obs, mask)
    return pool


def run_bench(engine, server, pool: "list[tuple[Any, Any]]",
              rounds: int = 24,
              request_sizes: "tuple[int, ...] | None" = None) -> dict:
    """Serve ``rounds`` coalesced dispatches, cycling the request sizes
    and the pool deterministically, inline-pumped so every dispatch's
    composition is exactly the round's request size. Returns the SLO
    report (and leaves the same numbers in the server's registry for
    the scrape endpoint / .prom snapshot)."""
    if rounds <= 0:
        raise ValueError(f"rounds must be positive, got {rounds}")
    if not pool:
        raise ValueError("empty request pool")
    if request_sizes is None:
        request_sizes = default_request_sizes(engine.max_bucket)
    request_sizes = tuple(int(s) for s in request_sizes)
    if any(s <= 0 for s in request_sizes):
        raise ValueError(f"request sizes must be positive: "
                         f"{request_sizes}")
    buckets = sorted({engine.bucket_for(s) for s in request_sizes})

    # pre-pay the per-bucket compiles so the measured rounds are pure
    # steady state — after this, ANY compile is an alarm
    obs0, mask0 = pool[0]
    engine.warmup(obs0, mask0, buckets=tuple(buckets))
    warm_recompiles = engine.post_warmup_recompiles

    cursor = 0
    futures = []
    for r in range(rounds):
        k = request_sizes[r % len(request_sizes)]
        for _ in range(k):
            obs, mask = pool[cursor % len(pool)]
            futures.append(server.submit(obs, mask))
            cursor += 1
        server.pump()
    results = [f.result(timeout=60) for f in futures]

    # a DATA site, not a gauge refresh: the snapshot dict is the bench
    # report (gauge freshness is the registry collector hook's job now)
    snap = server.slo_snapshot()
    return {
        "rounds": rounds,
        "request_sizes": list(request_sizes),
        "buckets": [int(b) for b in buckets],
        "pool_size": len(pool),
        "post_warmup_recompiles":
            engine.post_warmup_recompiles - warm_recompiles,
        "warmed_buckets": [int(b) for b in engine.warmed_buckets],
        **snap,
        "requests": len(results),
    }


def run_scaleout(apply_fn, net_params: Any, env_params: Any,
                 pool: "list[tuple[Any, Any]]", *, max_bucket: int,
                 rounds: int = 24,
                 request_sizes: "tuple[int, ...] | None" = None,
                 engine_counts: "tuple[int, ...]" = (1, 2),
                 deadline_s: "float | None" = None) -> dict:
    """Decisions/s + shed rate vs engine count: one isolated arm per
    count in ``engine_counts`` (fresh router + registry + server, so
    arms share nothing), each serving the SAME deterministic request
    stream through ``engines`` live dispatcher threads. Per-arm output
    carries per-engine row shares and recompile counts; the top level
    carries the CPU-serialization caveat (module docstring)."""
    from ..obs import Registry
    from .batching import DeadlineSheddedError, PolicyServer
    from .router import EngineRouter

    if request_sizes is None:
        request_sizes = default_request_sizes(max_bucket)
    request_sizes = tuple(int(s) for s in request_sizes)
    obs0, mask0 = pool[0]
    arms = []
    serialized = None
    for k in engine_counts:
        reg = Registry()
        router = EngineRouter(apply_fn, net_params, env_params,
                              max_bucket=max_bucket, registry=reg,
                              n_engines=int(k))
        serialized = router.serialized_dispatch()
        buckets = tuple(sorted({router.bucket_for(s)
                                for s in request_sizes}))
        router.warmup(obs0, mask0, buckets=buckets)
        server = PolicyServer(router, registry=reg)
        server.start(dispatchers=int(k))
        futures, shed, cursor = [], 0, 0
        t0 = time.perf_counter()
        for r in range(rounds):
            for _ in range(request_sizes[r % len(request_sizes)]):
                obs, mask = pool[cursor % len(pool)]
                futures.append(server.submit(obs, mask,
                                             deadline_s=deadline_s))
                cursor += 1
        for f in futures:
            try:
                f.result(timeout=120)
            except DeadlineSheddedError:
                shed += 1
        wall = time.perf_counter() - t0
        server.stop()
        total_rows = sum(s.rows for s in router.stats()) or 1
        arms.append({
            "engines": int(k),
            "requests": len(futures),
            "served": len(futures) - shed,
            "shed": shed,
            "shed_rate": shed / len(futures),
            "decisions_per_s": (len(futures) - shed) / wall,
            "wall_s": wall,
            "per_engine_rows": [s.rows for s in router.stats()],
            "per_engine_row_share": [s.rows / total_rows
                                     for s in router.stats()],
            "per_engine_dispatches": [s.dispatches
                                      for s in router.stats()],
            "per_engine_occupancy": [s.occupancy
                                     for s in router.stats()],
            "per_engine_recompiles": router.per_engine_recompiles(),
        })
    return {
        "engine_counts": [int(k) for k in engine_counts],
        "rounds": rounds,
        "request_sizes": list(request_sizes),
        "deadline_s": deadline_s,
        "serialized_dispatch_cpu": bool(serialized),
        "caveat": ("CPU backend serializes device dispatch behind one "
                   "lock (XLA:CPU thread-safety) — decisions/s does not "
                   "scale with engines here; routing/occupancy/shed "
                   "accounting is what this measures"
                   if serialized else None),
        "arms": arms,
    }


def run_soak(server, pool: "list[tuple[Any, Any]]", *,
             duration_s: float = 6.0, rate_hz: float = 200.0,
             deadline_s: "float | None" = None, router=None,
             advisor=None, advisor_every_s: float = 0.5) -> dict:
    """Sustained-load soak through a RUNNING server (caller started the
    dispatchers): pace submissions at ``rate_hz`` for ``duration_s``,
    optionally attaching a per-request ``deadline_s`` (shedding active)
    and an autoscale loop (every ``advisor_every_s``: let ``advisor``
    vote — its tick refreshes the SLO gauges through the registry
    collector hook — and apply to ``router``). Reports
    first-half vs second-half p99 — the drift surface the soak-lite CI
    stage bounds (an unbounded queue or a leak shows up as second-half
    p99 runaway)."""
    from .batching import DeadlineSheddedError

    if advisor is not None and router is None:
        raise ValueError("autoscale soak needs the router to apply "
                         "advisor votes to")
    interval = 1.0 / float(rate_hz)
    futures = []
    cursor = 0
    resizes = 0
    t_start = time.perf_counter()
    next_t = t_start
    next_tick = t_start + advisor_every_s
    while time.perf_counter() - t_start < duration_s:
        obs, mask = pool[cursor % len(pool)]
        futures.append(server.submit(obs, mask, deadline_s=deadline_s))
        cursor += 1
        now = time.perf_counter()
        if advisor is not None and now >= next_tick:
            before = advisor.desired
            router.apply_autoscale(advisor)
            resizes += int(advisor.desired != before)
            next_tick += advisor_every_s
        next_t += interval
        sleep = next_t - time.perf_counter()
        if sleep > 0:
            time.sleep(sleep)
    lat_s: "list[float | None]" = []
    shed = 0
    for f in futures:
        try:
            lat_s.append(f.result(timeout=120).latency_s)
        except DeadlineSheddedError:
            shed += 1
            lat_s.append(None)
    wall = time.perf_counter() - t_start

    def p99_ms(xs):
        xs = [x for x in xs if x is not None]
        return (float(np.percentile(np.asarray(xs), 99) * 1e3)
                if xs else None)

    half = len(lat_s) // 2
    p99_a, p99_b = p99_ms(lat_s[:half]), p99_ms(lat_s[half:])
    out = {
        "requests": len(futures),
        "served": len(futures) - shed,
        "shed": shed,
        "shed_rate": shed / max(len(futures), 1),
        "duration_s": wall,
        "rate_hz": rate_hz,
        "deadline_s": deadline_s,
        "p99_first_half_ms": p99_a,
        "p99_second_half_ms": p99_b,
        "p99_drift": (p99_b / p99_a
                      if p99_a and p99_b and p99_a > 0 else None),
        "autoscale_resizes": resizes if advisor is not None else None,
    }
    if router is not None:
        out["per_engine_rows"] = [s.rows for s in router.stats()]
        out["per_engine_occupancy"] = [s.occupancy
                                       for s in router.stats()]
        out["per_engine_recompiles"] = router.per_engine_recompiles()
        out["engines_active"] = router.n_active
        out["serialized_dispatch_cpu"] = router.serialized_dispatch()
    return out


class StubEngine:
    """Zero-device-work engine for the host-path bench: ``decide``
    returns a view of ONE preallocated action buffer (never a fresh
    ndarray, never an alias of the caller's obs/mask — so the arena's
    zero-copy scatter needs no defensive copy and the steady-state
    allocation gate measures the data plane alone). With device work
    gone, decisions/s isolates exactly the host path this rig can
    honestly measure: submit → coalesce → pad/seal → scatter."""

    def __init__(self, max_bucket: int = 8):
        self.max_bucket = int(max_bucket)
        self.dispatches = 0
        self.rows = 0
        self.post_warmup_recompiles = 0     # nothing compiles, ever
        self.warmed_buckets: "tuple[int, ...]" = ()

        self._actions = np.zeros(self.max_bucket, dtype=np.int32)

    def bucket_for(self, n: int) -> int:
        from .batching import next_bucket
        return next_bucket(n, self.max_bucket)

    def warmup(self, example_obs: Any, example_mask: Any,
               buckets: "tuple[int, ...]" = ()) -> "tuple[int, ...]":
        self.warmed_buckets = tuple(buckets)
        return self.warmed_buckets

    def decide(self, obs: Any, mask: Any, stall=None):
        n = int(np.asarray(jax.tree.leaves(obs)[0]).shape[0])
        self.dispatches += 1
        self.rows += n
        return self._actions[:n], self.bucket_for(n)


class _AllocCounter:
    """Context manager counting calls to the numpy batch constructors
    the hot path must not touch in steady state (the same four the jsan
    ``alloc-in-hot-loop`` rule polices). Wraps the module-level
    functions, so every caller in-process is counted — including the
    legacy plane's ``stack_requests``/``pad_batch``."""

    TRACKED = ("zeros", "empty", "concatenate", "stack")

    def __init__(self):
        self.calls = 0
        self._orig: dict = {}

    def __enter__(self):
        def counted(fn):
            def inner(*a, **k):
                self.calls += 1
                return fn(*a, **k)
            return inner
        for name in self.TRACKED:
            self._orig[name] = getattr(np, name)
            setattr(np, name, counted(self._orig[name]))
        return self

    def __exit__(self, *exc):
        for name, fn in self._orig.items():
            setattr(np, name, fn)
        self._orig.clear()
        return False


def _run_wire_arm(pool: "list[tuple[Any, Any]]", *, bucket: int,
                  framed: bool, n_requests: int, clients: int = 8,
                  warmup: int = 64) -> dict:
    """One transport arm over a LIVE stack (dispatcher thread + asyncio
    frontend + real sockets): the pre-PR shape is one HTTP connection
    per request over the legacy plane; the post-PR shape is one framed
    keep-alive connection per client over the arena. ``clients``
    concurrent client threads keep the batcher fed so dispatches
    coalesce. Client and server share one interpreter, so the number is
    the whole host path — wire parse included, the part the framed mode
    exists to amortize."""
    import socket
    import threading

    from ..obs import Registry
    from . import wire
    from .batching import PolicyServer
    from .frontend import start_frontend

    plane = "arena" if framed else "legacy"
    obs0, mask0 = pool[0]
    reg = Registry()
    engine = StubEngine(bucket)
    server = PolicyServer(engine, registry=reg, data_plane=plane,
                          example_obs=obs0, example_mask=mask0)
    server.start(dispatchers=1)
    handle = start_frontend(server, obs0, mask0, registry=reg)
    addr = ("127.0.0.1", handle.port)
    per_client = max(n_requests // clients, 1)
    warm_per_client = max(warmup // clients, 1)
    ok = [0] * clients
    barrier = threading.Barrier(clients + 1)

    def http_request(obs, mask):
        body = (np.ascontiguousarray(obs).tobytes()
                + np.ascontiguousarray(mask).tobytes())
        return (f"POST /v1/decide HTTP/1.1\r\nHost: bench\r\n"
                f"Content-Length: {len(body)}\r\n"
                f"Connection: close\r\n\r\n").encode() + body

    def run_http(k: int) -> None:
        obs, mask = pool[k % len(pool)]
        req = http_request(obs, mask)
        for phase, n in (("warm", warm_per_client),
                         ("measure", per_client)):
            if phase == "measure":
                barrier.wait()
            for _ in range(n):
                with socket.create_connection(addr) as s:
                    s.sendall(req)
                    buf = b""
                    while True:         # Connection: close -> read to EOF
                        c = s.recv(65536)
                        if not c:
                            break
                        buf += c
                if phase == "measure" and buf.startswith(b"HTTP/1.1 200"):
                    ok[k] += 1

    def run_framed(k: int) -> None:
        obs, mask = pool[k % len(pool)]
        frame = wire.pack_request(obs, mask)
        with socket.create_connection(addr) as s:
            for phase, n in (("warm", warm_per_client),
                             ("measure", per_client)):
                if phase == "measure":
                    barrier.wait()
                for _ in range(n):
                    s.sendall(frame)
                    kind, _, _, _, _, _ = wire.recv_frame(s)
                    if phase == "measure" and kind == wire.KIND_RESP:
                        ok[k] += 1

    target = run_framed if framed else run_http
    threads = [threading.Thread(target=target, args=(k,), daemon=True)
               for k in range(clients)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    occupancy = reg.gauge("serve_batch_occupancy").value
    handle.close()
    served = sum(ok)
    return {
        "transport": ("framed keep-alive" if framed
                      else "http connection-per-request"),
        "data_plane": plane,
        "clients": clients,
        "requests": per_client * clients,
        "served": served,
        "conservation_ok": served == per_client * clients,
        "decisions_per_s": served / wall,
        "wall_s": wall,
        "last_batch_occupancy": float(occupancy),
        "post_warmup_recompiles": engine.post_warmup_recompiles,
    }


def run_host_path(pool: "list[tuple[Any, Any]]", *, max_bucket: int = 8,
                  rounds: int = 300, warmup_rounds: int = 12,
                  fit=None, seed: int = 0,
                  rate_hz: "float | None" = None,
                  wire_requests: int = 0, clients: int = 8,
                  planes: "tuple[str, ...]" = ("legacy", "arena")) -> dict:
    """Host-path decisions/s, pre-PR vs post-PR data plane (BENCH_r09).

    Two in-process arms isolate the BATCHING layer: one arm per plane
    (fresh registry + :class:`StubEngine` + server, inline-pumped so
    every dispatch is exactly ``max_bucket`` rows), same request
    stream. When ``fit`` and ``rate_hz`` are given, submissions are
    replay-paced with :func:`fit_paced_gaps` — pass a rate above
    saturation so the trace contributes burstiness, not a rate ceiling.
    The measured window wraps the four numpy batch constructors
    (:class:`_AllocCounter`): the legacy arm's count is the churn being
    deleted, the arena arm's must be ZERO — and the arena's
    slab-allocation counter must stay flat (both CI-gated).

    When ``wire_requests > 0`` two further arms measure the WHOLE data
    plane through real sockets (:func:`_run_wire_arm`): the pre-PR
    shape (one HTTP connection per request, legacy batching) vs the
    post-PR shape (framed keep-alive, arena batching). The headline
    ``speedup`` is the wire-arm ratio when present — that is the plane
    this PR replaced end to end — with the batching-only ratio kept as
    ``speedup_inproc``."""
    from ..obs import Registry
    from .batching import PolicyServer

    if rounds <= 0 or warmup_rounds < 1:
        raise ValueError(f"need rounds > 0 and warmup_rounds >= 1, got "
                         f"{rounds} / {warmup_rounds}")
    if not pool:
        raise ValueError("empty request pool")
    bucket = int(max_bucket)
    obs0, mask0 = pool[0]
    n_requests = rounds * bucket
    gaps = None
    if fit is not None:
        if rate_hz is None or rate_hz <= 0:
            raise ValueError("replay pacing needs rate_hz > 0")
        gaps = fit_paced_gaps(fit, n_requests, seed=(seed, 0x405B),
                              rate_hz=rate_hz)

    arms: dict[str, dict] = {}
    for plane in planes:
        reg = Registry()
        engine = StubEngine(bucket)
        server = PolicyServer(engine, registry=reg, data_plane=plane,
                              example_obs=obs0, example_mask=mask0)
        slab_allocs = reg.counter("serve_arena_allocs_total")

        cursor = 0

        # inline pump resolves every future before submit of the next
        # round, so the bench counts served rows off pump()'s return and
        # drops the futures immediately — accumulating 10k+ live futures
        # would measure the GC scanning the bench's own garbage, not the
        # data plane (both arms flatline identically under that load)
        def one_round() -> int:
            nonlocal cursor
            for _ in range(bucket):
                obs, mask = pool[cursor % len(pool)]
                server.submit(obs, mask)
                cursor += 1
            return server.pump()

        # warmup: slab ring growth, pad-fill cache, estimator warm —
        # after this ANY allocation in the arena arm is a regression
        for _ in range(warmup_rounds):
            one_round()
        allocs_before = int(slab_allocs.value)
        requests_before = int(reg.counter("serve_requests_total").value)

        served = 0
        counter = _AllocCounter()
        t0 = time.perf_counter()
        next_t = t0
        with counter:
            if gaps is None:
                for r in range(rounds):
                    served += one_round()
            else:
                for r in range(rounds):
                    for _ in range(bucket):
                        obs, mask = pool[cursor % len(pool)]
                        server.submit(obs, mask)
                        next_t += gaps[cursor % len(gaps)]
                        sleep = next_t - time.perf_counter()
                        if sleep > 0:
                            time.sleep(sleep)
                        cursor += 1
                    served += server.pump()
        wall = time.perf_counter() - t0
        submitted = (int(reg.counter("serve_requests_total").value)
                     - requests_before)
        shed = int(reg.counter("serve_shed_total").value)
        server.close()
        arms[plane] = {
            "data_plane": plane,
            "requests": submitted,
            "served": served,
            "shed": shed,
            "conservation_ok": submitted == served + shed,
            "decisions_per_s": served / wall,
            "wall_s": wall,
            "dispatches": engine.dispatches,
            "alloc_calls": counter.calls,
            "allocs_per_batch": counter.calls / rounds,
            "steady_state_slab_allocs":
                int(slab_allocs.value) - allocs_before,
            "post_warmup_recompiles": engine.post_warmup_recompiles,
            "arena": server.arena_stats() if plane == "arena" else None,
        }

    out = {
        "bucket": bucket,
        "rounds": rounds,
        "warmup_rounds": warmup_rounds,
        "requests_per_arm": n_requests,
        "paced": gaps is not None,
        "arrival_fit": getattr(fit, "name", None) if fit is not None
        else None,
        "rate_hz": rate_hz,
        "caveat": ("stub engine, zero device work: decisions/s is the "
                   "HOST path only (submit/coalesce/seal/scatter) — the "
                   "number this serialized-dispatch CPU rig can honestly "
                   "measure; device-inclusive numbers await the real-pod "
                   "item"),
        "arms": [arms[p] for p in planes],
    }
    if "legacy" in arms and "arena" in arms:
        base = arms["legacy"]["decisions_per_s"]
        out["speedup_inproc"] = (arms["arena"]["decisions_per_s"] / base
                                 if base > 0 else None)
        out["speedup"] = out["speedup_inproc"]
    if wire_requests > 0:
        before = _run_wire_arm(pool, bucket=bucket, framed=False,
                               n_requests=wire_requests, clients=clients)
        after = _run_wire_arm(pool, bucket=bucket, framed=True,
                              n_requests=wire_requests, clients=clients)
        out["wire_arms"] = [before, after]
        base = before["decisions_per_s"]
        out["speedup"] = (after["decisions_per_s"] / base
                          if base > 0 else None)
    return out


def fit_paced_gaps(fit, n: int, seed, rate_hz: float) -> np.ndarray:
    """Inter-arrival gaps carrying a fitted workload's arrival SHAPE at
    a chosen offered rate: realize one seeded window from ``fit``
    (:func:`~..traces.fit.gen_domain_window` — the same arrival process
    the simulator replays), take its inter-arrival gaps, and rescale
    them so the mean gap is exactly ``1/rate_hz``. The soak then pounds
    the server with the trace's burstiness, not a metronome — idle
    stretches and pile-ups included — while the offered load stays the
    configured number. Deterministic per (fit, seed)."""
    from ..traces.fit import gen_domain_window

    if n < 1:
        raise ValueError(f"need at least one gap, got n={n}")
    if rate_hz <= 0:
        raise ValueError(f"rate_hz must be positive, got {rate_hz}")
    win = gen_domain_window(fit, n_jobs=n + 1, seed=seed, n_gpus=8,
                            load=1.0)
    gaps = np.maximum(np.diff(win.submit.astype(np.float64)), 0.0)
    mean = float(gaps.mean())
    if mean <= 0:       # degenerate window (all-burst); fall back flat
        return np.full(n, 1.0 / rate_hz)
    return gaps * ((1.0 / rate_hz) / mean)


def _rss_bytes() -> "int | None":
    """Resident-set size from ``/proc/self/statm`` (no psutil dep);
    None where procfs is absent (non-Linux). Used by the chaos soak's
    heap-drift gate: a steady-state serving plane recycling arena slabs
    must not grow its RSS materially under sustained load + faults."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return None


def run_chaos_soak(server, pool: "list[tuple[Any, Any]]", *, fit,
                   duration_s: float = 6.0, rate_hz: float = 150.0,
                   deadline_s: "float | None" = None, router=None,
                   seed: int = 0) -> dict:
    """:func:`run_soak` graduated to chaos: replay-paced load
    (:func:`fit_paced_gaps` — the fitted trace's arrival process, not a
    fixed interval) through a RUNNING dispatcher fleet while a
    :class:`~.router.ServeFaultInjector` (attached to the router by the
    caller) fails engines mid-run. Every future is awaited with a bound
    and bucketed into exactly one of served / shed / failed, so the
    report carries the conservation invariant directly::

        submitted == served + shed + failed      (failed must be 0:
        the retry hedge absorbs injected engine faults)

    plus the exactly-once counter cross-check (``registry_shed_total``
    must equal the shed futures actually observed) and the router's
    ejection/readmission/hedge story (:meth:`~.router.EngineRouter.
    fault_stats`).

    The pacing loop calls ``registry.collect()`` twice a second, so the
    SLO engine's burn windows advance DURING the fault window (a burn
    alert must fire while the bleeding happens, not at the post-mortem
    scrape), and after the last future resolves the soak keeps
    collecting until every SLO stops alerting (bounded) — the report's
    ``slo`` section shows the recovered budget."""
    from .batching import DeadlineSheddedError

    n_gaps = max(int(duration_s * rate_hz * 2) + 16, 1)
    gaps = fit_paced_gaps(fit, n_gaps, seed=(seed, 0xC7A05),
                          rate_hz=rate_hz)
    reg = server.registry
    rss_start = _rss_bytes()
    futures = []
    cursor = 0
    t_start = time.perf_counter()
    next_t = t_start
    # pre-incident baseline sample: burn is measured between samples,
    # so a fault that fires before the FIRST collect would be invisible
    # (baked into the initial cumulative reading) without this
    reg.collect()
    next_collect = t_start + 0.5
    while time.perf_counter() - t_start < duration_s:
        obs, mask = pool[cursor % len(pool)]
        futures.append(server.submit(obs, mask, deadline_s=deadline_s))
        next_t += gaps[cursor % len(gaps)]
        cursor += 1
        if time.perf_counter() >= next_collect:
            reg.collect()
            next_collect += 0.5
        sleep = next_t - time.perf_counter()
        if sleep > 0:
            time.sleep(sleep)
    lat_s: "list[float | None]" = []
    shed = 0
    failed = 0
    failure_kinds: dict[str, int] = {}
    for f in futures:
        try:
            lat_s.append(f.result(timeout=30).latency_s)
        except DeadlineSheddedError:
            shed += 1
            lat_s.append(None)
        except Exception as e:   # incl. a hung future's TimeoutError
            failed += 1
            kind = type(e).__name__
            failure_kinds[kind] = failure_kinds.get(kind, 0) + 1
            lat_s.append(None)
    wall = time.perf_counter() - t_start
    served = len(futures) - shed - failed

    # settle: keep the burn windows sliding until every SLO clears (the
    # 1s engine-health window un-trips ~1s after the last hedge, the 3s
    # budget window recovers shortly after), bounded so a genuinely
    # still-burning SLO reports alerting=True instead of hanging
    slo_status: dict = {}
    if getattr(server, "slo", None) is not None:
        settle_by = time.perf_counter() + 4.0
        while True:
            reg.collect()
            slo_status = server.slo.status()
            settled = not any(s["alerting"] for s in slo_status.values())
            # ...and let SHORT budget windows slide fully past the
            # incident, so the report shows the recovered budget rather
            # than the mid-bleed snapshot (long windows would outlast
            # the settle bound — leave those to the dashboards)
            settled = settled and all(
                s["budget_remaining"] >= 1.0
                for s in slo_status.values()
                if s["alerts_total"] and s["budget_window_s"] <= 3.0)
            if settled or time.perf_counter() >= settle_by:
                break
            time.sleep(0.2)

    def p99_ms(xs):
        xs = [x for x in xs if x is not None]
        return (float(np.percentile(np.asarray(xs), 99) * 1e3)
                if xs else None)

    half = len(lat_s) // 2
    p99_a, p99_b = p99_ms(lat_s[:half]), p99_ms(lat_s[half:])
    out = {
        "requests": len(futures),
        "served": served,
        "shed": shed,
        "failed": failed,
        "failure_kinds": failure_kinds,
        "conservation_ok": len(futures) == served + shed + failed,
        "registry_requests_total": int(
            reg.counter("serve_requests_total").value),
        "registry_shed_total": int(reg.counter("serve_shed_total").value),
        "shed_rate": shed / max(len(futures), 1),
        "duration_s": wall,
        "rate_hz": rate_hz,
        "arrival_fit": fit.name,
        "deadline_s": deadline_s,
        "p99_first_half_ms": p99_a,
        "p99_second_half_ms": p99_b,
        "p99_drift": (p99_b / p99_a
                      if p99_a and p99_b and p99_a > 0 else None),
        "slo": slo_status,
    }
    # heap-drift gate inputs: RSS before the first submit vs after the
    # last future resolved (all recycled slabs back in the ring)
    rss_end = _rss_bytes()
    out["rss_start_bytes"] = rss_start
    out["rss_end_bytes"] = rss_end
    out["rss_growth_bytes"] = (rss_end - rss_start
                               if rss_start is not None
                               and rss_end is not None else None)
    out["rss_growth_frac"] = ((rss_end - rss_start) / rss_start
                              if rss_start else None)
    if router is not None:
        out["fault_stats"] = router.fault_stats()
        out["per_engine_rows"] = [s.rows for s in router.stats()]
        out["per_engine_recompiles"] = router.per_engine_recompiles()
        out["engines_active"] = router.n_active
        out["serialized_dispatch_cpu"] = router.serialized_dispatch()
    return out
