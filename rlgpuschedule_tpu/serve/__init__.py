"""Fleet-scale serving (L6): continuous batching + vmapped fleet replay.

The ROADMAP's "millions of users" entry point (PR 7): the trained
scheduler policy served as a batched inference system instead of a
one-at-a-time evaluation.

- :mod:`.engine` — :class:`InferenceEngine`: the stateless jit'd
  ``policy_step(obs_batch) -> actions``, compiled once per power-of-two
  batch bucket with donated request buffers, sharing the greedy/masked
  decision rule with ``eval.replay`` (:mod:`..decision`) and policed by
  the jsan runtime sentinels — post-warmup recompiles and implicit
  host syncs are production alarms, not silent slowdowns.
- :mod:`.batching` — the continuous-batching front end:
  :class:`PolicyServer` request queue (coalesce to the next bucket,
  pad, dispatch, scatter in FIFO order), deadline-aware adaptive
  batching + load shedding (typed :class:`DeadlineSheddedError`
  rejections, ``serve_shed_total``), and the SLO metric surface
  (p50/p99 decision latency, decisions/s/chip, queue depth, batch
  occupancy) through the ``obs`` registry.
- :mod:`.router` — multi-engine scale-out (PR 13):
  :class:`EngineRouter` resolves one engine per data-axis device of
  the unified mesh and dispatches least-loaded;
  :class:`AutoscaleAdvisor` turns the SLO gauges into a desired-engine
  count the router applies live.
- :mod:`.fleet` — vmapped fleet replay: one checkpoint vs N seeded
  simulated clusters (optionally under ``sim.faults`` regimes) in a
  single fused-scan dispatch, bit-identical to N sequential
  ``eval.replay`` runs.
- :mod:`.bench` — the ``serve --bench`` driver: deterministic request
  streams, zero-recompile steady-state assertion; ``run_chaos_soak``
  paces the fitted trace arrival process through a fleet under
  injected engine faults and reports the conservation invariant.
- :mod:`.frontend` — the network front door (PR 16, rebuilt PR 17):
  :class:`ServeFrontend`, an asyncio listener speaking keep-alive
  HTTP/1.1 *and* the length-prefixed binary frame dialect
  (:mod:`.wire`) on one port, with zero-copy request decoding, wire
  deadline propagation (503 + learned clamped ``Retry-After`` on
  shed), queue-depth connection backpressure, and graceful SIGTERM
  drain (typed :class:`ServerClosedError` for late submits — never a
  hung future).
- :mod:`.wire` — the framed transport: 24-byte prefix (magic,
  version, kind, lengths, metadata) + dtype/shape descriptor header +
  raw row bytes; ``np.frombuffer`` is the only decode.
- ``python -m rlgpuschedule_tpu.serve`` — the CLI (``--bench``,
  ``--fleet N``, ``--metrics-port`` live Prometheus scrape endpoint,
  ``--chaos-faults`` engine-fault chaos soak, ``--frontend-port``).
"""
from . import wire
from .batching import (DeadlineSheddedError, Ewma, PolicyServer, Reservoir,
                       ServeResult, ServerClosedError, next_bucket,
                       pad_batch, scatter_results, stack_requests)
from .bench import StubEngine, run_host_path
from .engine import InferenceEngine
from .fleet import fleet_replay, fleet_windows, sample_fleet_faults
from .frontend import FrontendHandle, ServeFrontend, start_frontend
from .router import (SERVE_FAULT_KINDS, AutoscaleAdvisor, EngineRouter,
                     EngineStats, InjectedEngineFault, ServeFaultInjector,
                     ServeFaultSpec, parse_serve_fault)

__all__ = [
    "InferenceEngine", "PolicyServer", "Reservoir", "ServeResult",
    "DeadlineSheddedError", "ServerClosedError", "Ewma",
    "EngineRouter", "AutoscaleAdvisor", "EngineStats",
    "SERVE_FAULT_KINDS", "ServeFaultSpec", "ServeFaultInjector",
    "InjectedEngineFault", "parse_serve_fault",
    "ServeFrontend", "FrontendHandle", "start_frontend", "wire",
    "StubEngine", "run_host_path",
    "next_bucket", "pad_batch", "scatter_results", "stack_requests",
    "fleet_replay", "fleet_windows", "sample_fleet_faults",
]
