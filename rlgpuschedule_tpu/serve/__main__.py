"""Serving CLI: ``python -m rlgpuschedule_tpu.serve``.

Five modes, composable in one invocation:

- ``--bench``: drive a deterministic synthetic request stream through
  the continuous-batching policy server and report the SLO table —
  p50/p99 decision latency, decisions/s(/chip), batch occupancy, and
  the steady-state contract (zero post-warmup recompiles across
  distinct request sizes within one bucket, CompileCounter-verified).
- ``--soak SECONDS``: sustained paced load through live dispatcher
  threads (``--rate``, ``--deadline-ms`` shedding, ``--adaptive-wait``
  learned batching, ``--autoscale`` advisor loop) reporting p99 drift
  + shed rate — the ci.sh soak-lite surface.
- ``--scaleout``: decisions/s + shed rate, 1 engine vs ``--engines``
  routed engines on the same stream (honest CPU caveat included).
- ``--fleet N``: vmapped fleet replay — the checkpoint vs N seeded
  simulated clusters in one dispatch (optionally under a
  ``sim.faults`` regime), reporting fleet mean JCT / completion /
  decisions/s.
- ``--host-path``: the data-plane bench (BENCH_r09) — a zero-device
  stub engine isolates the host path (submit/coalesce/seal/scatter),
  comparing the legacy copy-per-batch plane against the arena plane,
  with the numpy batch-constructor count gated to ZERO in the arena
  arm; ``--wire-requests N`` adds the socket arms (HTTP
  connection-per-request vs framed keep-alive).
- ``--flight-log DIR`` / ``--promote``: the data flywheel (ISSUE 19) —
  record served decisions into crc-sidecar'd shards during ``--soak``
  (exactly-once: ``rows_logged == served``), then canary-gate a
  candidate checkpoint against the logged window and promote it live
  (``swap_params`` + blessed re-warm) under an SLO watchdog that rolls
  back automatically; the whole lineage lands in the promotion ledger.

``--engines N`` serves every mode through the mesh-resolved
:class:`~.router.EngineRouter` (one engine per data-axis device,
least-loaded dispatch, per-engine labeled sentinel series).

``--metrics-port`` exposes the live Prometheus scrape endpoint
(``obs.serve_http``); ``--obs-dir`` writes the event stream (blessed
``compile`` / alarm ``recompile`` events) + a ``metrics.prom``
snapshot. The JSON on stdout carries the same reproducibility tuple
``evaluate`` emits (``configs.repro_tuple``: config/seed/.../ckpt_dir/
RESOLVED ckpt_step), so serving numbers are regenerable exactly.

Examples::

    python -m rlgpuschedule_tpu.serve --config ppo-mlp-synth64 \
        --ckpt-dir out/ckpt --bench --bucket 16
    python -m rlgpuschedule_tpu.serve --config ppo-mlp-synth64 \
        --fleet 512 --fleet-regime storm --metrics-port 9090
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="rlgpuschedule_tpu.serve",
        description="Fleet-scale policy serving: continuous-batching "
                    "bench + vmapped fleet replay.")
    p.add_argument("--config", default="ppo-mlp-synth64")
    p.add_argument("--ckpt-dir", default=None,
                   help="restore the served policy from this checkpoint "
                        "dir (omit = untrained init weights; pick the "
                        "step with select_checkpoint)")
    p.add_argument("--ckpt-step", type=int, default=None)
    # cluster-shape overrides — MUST match the training run when
    # restoring a checkpoint (same contract as evaluate)
    p.add_argument("--trace", default=None,
                   choices=["synthetic", "philly", "pai", "philly-proxy",
                            "pai-proxy"])
    p.add_argument("--trace-path", default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--n-envs", type=int, default=None)
    p.add_argument("--n-nodes", type=int, default=None)
    p.add_argument("--gpus-per-node", type=int, default=None)
    p.add_argument("--window-jobs", type=int, default=None)
    p.add_argument("--queue-len", type=int, default=None)
    p.add_argument("--horizon", type=int, default=None)
    p.add_argument("--obs-kind", default=None,
                   choices=["flat", "grid", "graph"])
    # bench mode
    p.add_argument("--bench", action="store_true",
                   help="latency bench: deterministic request stream "
                        "through the continuous-batching server; "
                        "asserts the zero-recompile steady state")
    p.add_argument("--bucket", type=int, default=8,
                   help="largest power-of-two batch bucket the engine "
                        "compiles (bench default request sizes live in "
                        "(bucket/2, bucket])")
    p.add_argument("--rounds", type=int, default=24,
                   help="bench: coalesced dispatches to serve")
    p.add_argument("--request-sizes", default=None, metavar="A,B,...",
                   help="bench: request counts to cycle per round "
                        "(default: three distinct sizes inside the "
                        "--bucket bucket)")
    p.add_argument("--pool-steps", type=int, default=4,
                   help="bench: env decision steps used to materialize "
                        "the request pool")
    # multi-engine scale-out (PR 13)
    p.add_argument("--engines", type=int, default=1,
                   help="serve through N routed per-device engines (one "
                        "per data-axis device of the unified mesh; "
                        "least-loaded dispatch; N=1 keeps the single "
                        "engine). Refused for hierarchical configs")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="per-request latency SLO for --soak/--scaleout "
                        "submissions; requests whose deadline cannot be "
                        "met are shed with a typed rejection "
                        "(serve_shed_total)")
    p.add_argument("--adaptive-wait", action="store_true",
                   help="learn the partial-bucket hold time from the "
                        "observed arrival rate (streaming estimator) "
                        "instead of a fixed max-wait; dispatches early "
                        "when the head-of-line deadline approaches")
    p.add_argument("--soak", type=float, default=None, metavar="SECONDS",
                   help="sustained-load soak: pace --rate requests/s "
                        "through live dispatcher threads for this long; "
                        "reports first-half vs second-half p99 drift, "
                        "shed rate, per-engine rows/recompiles")
    p.add_argument("--rate", type=float, default=None, metavar="HZ",
                   help="soak arrival rate (default 200/s)")
    p.add_argument("--autoscale", action="store_true",
                   help="with --soak: run the AutoscaleAdvisor loop "
                        "(SLO gauges -> desired engine count, applied "
                        "live by the router with hysteresis)")
    p.add_argument("--chaos-faults", default=None,
                   metavar="SPEC[,SPEC...]",
                   help="with --soak: inject engine faults mid-run "
                        "(kind@N[:engine=E], kind in engine-raise / "
                        "engine-hang / engine-slow; N = router dispatch "
                        "sequence, fires on the target engine's first "
                        "dispatch >= N). The soak paces arrivals by the "
                        "config's fitted trace arrival process and "
                        "gates on exact request conservation; needs "
                        "--engines >= 2 so the retry hedge has a "
                        "healthy engine to land on")
    p.add_argument("--frontend-port", type=int, default=None,
                   metavar="PORT",
                   help="with --soak: run the asyncio HTTP front door "
                        "on this port (0 = ephemeral) and self-check "
                        "the wire contract after the soak (200 decide, "
                        "graceful drain, typed late-submit refusal)")
    p.add_argument("--scaleout", action="store_true",
                   help="decisions/s + shed rate vs engine count: "
                        "isolated 1-engine and --engines-engine arms "
                        "serving the same stream (CPU caveat: dispatch "
                        "is serialized there)")
    # host-path data-plane bench (PR 17)
    p.add_argument("--host-path", action="store_true",
                   help="data-plane bench: stub engine (zero device "
                        "work) isolating the host path, legacy vs "
                        "arena planes, with the steady-state "
                        "allocation gauge (arena must be 0)")
    p.add_argument("--host-rounds", type=int, default=300,
                   help="host-path: measured full-bucket rounds per "
                        "arm (plus a fixed warmup)")
    p.add_argument("--wire-requests", type=int, default=0, metavar="N",
                   help="host-path: also run the socket arms (HTTP "
                        "connection-per-request vs framed keep-alive) "
                        "with N measured requests each; the headline "
                        "speedup becomes the wire ratio")
    # fleet mode
    p.add_argument("--fleet", type=int, default=None, metavar="N",
                   help="fleet replay: evaluate the checkpoint against "
                        "N seeded simulated clusters in one dispatch")
    p.add_argument("--fleet-regime", default=None, metavar="REGIME",
                   help="with --fleet: replay every cluster under this "
                        "seeded fault regime (sim.faults.FAULT_REGIMES; "
                        "flat configs)")
    p.add_argument("--fleet-seed", type=int, default=0,
                   help="with --fleet-regime: base seed of the fault "
                        "draws (cluster e draws (seed, e))")
    p.add_argument("--max-steps", type=int, default=None,
                   help="fleet: cap decision steps per cluster "
                        "(default: the env horizon)")
    # observability
    p.add_argument("--metrics-port", type=int, default=None,
                   help="expose the live Prometheus scrape endpoint on "
                        "this port (0 = ephemeral; the bound port and a "
                        "self-scrape check land in the JSON)")
    p.add_argument("--obs-dir", default=None,
                   help="emit serve events (JSONL bus) + a metrics.prom "
                        "snapshot under this directory")
    p.add_argument("--trace-spans", action="store_true",
                   help="flight recorder: record the request lifecycle "
                        "(enqueue/bucket_wait/pad/dispatch/scatter) as "
                        "nested spans on the event bus; requires "
                        "--obs-dir (spans ride the JSONL stream). NOT "
                        "--trace, which picks the workload trace source")
    # data flywheel (ISSUE 19): flight log + canary-gated promotion
    p.add_argument("--flight-log", default=None, metavar="DIR",
                   help="with --soak: record every served decision "
                        "(obs/mask/action/behavior log-prob/value/"
                        "stall/deadline outcome) into crc-sidecar'd "
                        "shards under DIR; with --promote*: the logged "
                        "window the canary replays. Recording switches "
                        "the engine to capture mode (same compiled "
                        "program, extra outputs — zero-recompile "
                        "contract intact)")
    p.add_argument("--flight-capacity", type=int, default=512,
                   help="flight log rows per sealed shard")
    p.add_argument("--durable-log", action="store_true",
                   help="fsync flight-log shards + promotion-ledger "
                        "lines on seal (power-loss durability; default "
                        "is flush-only — see obs.events for the "
                        "overhead stance)")
    p.add_argument("--promote", default=None, metavar="CKPTDIR",
                   help="canary-gated promotion: load the candidate "
                        "policy from this checkpoint dir, replay the "
                        "--flight-log window under candidate vs "
                        "incumbent through the shared decision rule, "
                        "and only swap the serving weights if the "
                        "hysteresis gate clears; post-swap SLO "
                        "watchdog rolls back automatically")
    p.add_argument("--promote-step", type=int, default=None,
                   help="candidate checkpoint step (default: latest)")
    p.add_argument("--promote-noise", type=float, default=None,
                   metavar="SIGMA",
                   help="synthesize the candidate by perturbing the "
                        "incumbent with seeded N(0, SIGMA) noise "
                        "(alone: the candidate IS the perturbed "
                        "incumbent; with --promote: noise on top of "
                        "the loaded candidate). Large SIGMA is the "
                        "ci.sh seeded-regressed candidate the gate "
                        "must block")
    p.add_argument("--promote-fault", action="store_true",
                   help="inject a post-swap SLO regression (the "
                        "watchdog's observed p99 is inflated 10x) to "
                        "prove automatic rollback restores the "
                        "incumbent bit-identically")
    p.add_argument("--canary-slices", type=int, default=8,
                   help="held-out window slices the hysteresis gate "
                        "scores")
    p.add_argument("--canary-tol", type=float, default=0.02,
                   help="per-slice agreement regression tolerance")
    p.add_argument("--canary-hysteresis", type=int, default=2,
                   help="consecutive regressed slices that block "
                        "promotion")
    return p


def main(argv: "list[str] | None" = None) -> dict:
    args = build_parser().parse_args(argv)
    from ..configs import CONFIGS, repro_tuple
    if args.config not in CONFIGS:
        sys.exit(f"unknown config {args.config!r}")
    promote_mode = (args.promote is not None
                    or args.promote_noise is not None)
    if (not args.bench and args.fleet is None and args.soak is None
            and not args.scaleout and not args.host_path
            and not promote_mode):
        sys.exit("nothing to do: pass --bench, --soak S, --scaleout, "
                 "--host-path, --promote/--promote-noise, and/or "
                 "--fleet N")
    if args.fleet is not None and args.fleet <= 0:
        sys.exit("--fleet must be a positive cluster count")
    if args.bucket <= 0 or (args.bucket & (args.bucket - 1)):
        sys.exit("--bucket must be a positive power of two")
    if args.engines < 1:
        sys.exit("--engines must be >= 1")
    if args.scaleout and args.engines < 2:
        sys.exit("--scaleout compares 1 engine vs --engines; pass "
                 "--engines >= 2 with it")
    if args.soak is not None and args.soak <= 0:
        sys.exit("--soak must be a positive duration in seconds")
    if args.host_rounds <= 0:
        sys.exit("--host-rounds must be positive")
    if args.wire_requests < 0:
        sys.exit("--wire-requests must be >= 0")
    if args.wire_requests and not args.host_path:
        sys.exit("--wire-requests adds socket arms to --host-path; "
                 "pass --host-path with it (refusing the silent no-op)")
    if args.rate is not None and args.soak is None:
        sys.exit("--rate paces --soak submissions; pass --soak S with "
                 "it (refusing the silent no-op)")
    if args.rate is not None and args.rate <= 0:
        sys.exit("--rate must be positive requests/s")
    if args.autoscale and args.soak is None:
        sys.exit("--autoscale runs the advisor loop during --soak; "
                 "pass --soak S with it (refusing the silent no-op)")
    if args.autoscale and args.engines < 2:
        sys.exit("--autoscale resizes a multi-engine router; pass "
                 "--engines >= 2 with it (one engine cannot scale)")
    chaos_specs = None
    if args.chaos_faults is not None:
        if args.soak is None:
            sys.exit("--chaos-faults injects engine faults during "
                     "--soak; pass --soak S with it (refusing the "
                     "silent no-op)")
        if args.engines < 2:
            sys.exit("--chaos-faults needs --engines >= 2: the retry "
                     "hedge moves a failed dispatch to a DIFFERENT "
                     "healthy engine (one engine has nowhere to go)")
        if args.autoscale:
            sys.exit("--chaos-faults runs the chaos soak, which does "
                     "not drive the autoscale loop; drop --autoscale "
                     "(refusing the silent no-op)")
        from .router import parse_serve_fault
        try:
            chaos_specs = [parse_serve_fault(s)
                           for s in args.chaos_faults.split(",") if s]
        except ValueError as e:
            sys.exit(str(e))
        if not chaos_specs:
            sys.exit("--chaos-faults got no specs")
        bad_engine = [s for s in chaos_specs
                      if not 0 <= s.engine < args.engines]
        if bad_engine:
            sys.exit(f"--chaos-faults targets engine(s) "
                     f"{sorted({s.engine for s in bad_engine})} outside "
                     f"[0, {args.engines})")
    if args.frontend_port is not None and args.soak is None:
        sys.exit("--frontend-port runs the HTTP front door around "
                 "--soak; pass --soak S with it (refusing the silent "
                 "no-op)")
    if args.frontend_port is not None and args.frontend_port < 0:
        sys.exit("--frontend-port must be >= 0 (0 = ephemeral)")
    if args.deadline_ms is not None and args.deadline_ms <= 0:
        sys.exit("--deadline-ms must be positive")
    if (args.deadline_ms is not None and args.soak is None
            and not args.scaleout):
        sys.exit("--deadline-ms attaches SLOs to --soak/--scaleout "
                 "submissions; pass one of them (refusing the silent "
                 "no-op)")
    if args.fleet_regime is not None and args.fleet is None:
        sys.exit("--fleet-regime configures --fleet replay; pass "
                 "--fleet N with it (refusing the silent no-op)")
    sizes = None
    if args.request_sizes is not None:
        if not args.bench:
            sys.exit("--request-sizes configures --bench (refusing the "
                     "silent no-op)")
        try:
            sizes = tuple(int(s) for s in args.request_sizes.split(",")
                          if s)
        except ValueError:
            sys.exit(f"bad --request-sizes {args.request_sizes!r}")
        if not sizes or any(s <= 0 for s in sizes):
            sys.exit("--request-sizes must be positive integers")
        too_big = [s for s in sizes if s > args.bucket]
        if too_big:
            sys.exit(f"--request-sizes {too_big} exceed --bucket "
                     f"{args.bucket}")
    if args.trace_spans and not args.obs_dir:
        sys.exit("--trace-spans records spans on the event bus; pass "
                 "--obs-dir with it (refusing the silent no-op)")
    if args.flight_log is not None and args.soak is None \
            and not promote_mode:
        sys.exit("--flight-log records --soak traffic or feeds "
                 "--promote replay; pass one of them (refusing the "
                 "silent no-op)")
    if promote_mode and args.flight_log is None:
        sys.exit("promotion replays a logged window; pass "
                 "--flight-log DIR with --promote/--promote-noise")
    if args.flight_capacity <= 0:
        sys.exit("--flight-capacity must be a positive row count")
    if args.promote_step is not None and args.promote is None:
        sys.exit("--promote-step picks the --promote candidate step; "
                 "pass --promote CKPTDIR with it (refusing the silent "
                 "no-op)")
    if args.promote_noise is not None and args.promote_noise <= 0:
        sys.exit("--promote-noise must be a positive sigma")
    if args.promote_fault and not promote_mode:
        sys.exit("--promote-fault injects a post-swap SLO regression; "
                 "pass --promote/--promote-noise with it (refusing "
                 "the silent no-op)")
    if args.canary_slices < 1:
        sys.exit("--canary-slices must be >= 1")
    if args.canary_tol < 0:
        sys.exit("--canary-tol must be >= 0")
    if args.canary_hysteresis < 1:
        sys.exit("--canary-hysteresis must be >= 1")
    if args.durable_log and args.flight_log is None:
        sys.exit("--durable-log hardens the --flight-log shards and "
                 "ledger; pass --flight-log DIR with it (refusing the "
                 "silent no-op)")
    if args.fleet_regime is not None:
        from ..sim.faults import FAULT_REGIMES
        if args.fleet_regime not in FAULT_REGIMES:
            sys.exit(f"unknown --fleet-regime {args.fleet_regime!r}; "
                     f"known: {sorted(FAULT_REGIMES)}")

    cfg = CONFIGS[args.config]
    over = {k: v for k, v in
            {"trace": args.trace, "trace_path": args.trace_path,
             "seed": args.seed, "n_envs": args.n_envs,
             "n_nodes": args.n_nodes,
             "gpus_per_node": args.gpus_per_node,
             "window_jobs": args.window_jobs,
             "queue_len": args.queue_len, "horizon": args.horizon,
             "obs_kind": args.obs_kind}.items() if v is not None}
    cfg = dataclasses.replace(cfg, **over)
    from ..configs import ModeCombinationError, validate_mode_combination
    try:
        validate_mode_combination({"router": args.engines > 1,
                                   "hier": cfg.n_pods > 1})
    except ModeCombinationError as e:
        sys.exit(str(e))

    import os

    from ..experiment import Experiment
    from ..obs import EventBus, Registry
    from ..obs.trace import NULL_TRACER, Tracer
    from ..utils.platform import enable_compile_cache
    from .batching import PolicyServer
    from .bench import (build_request_pool, run_bench, run_host_path,
                        run_scaleout, run_soak)
    from .engine import InferenceEngine
    from .fleet import fleet_replay, fleet_windows, sample_fleet_faults
    from .router import AutoscaleAdvisor, EngineRouter

    enable_compile_cache()
    repro = repro_tuple(cfg, ckpt_dir=args.ckpt_dir)

    exp = Experiment.build(cfg)
    if args.ckpt_dir:
        from ..checkpoint import Checkpointer
        with Checkpointer(os.path.abspath(args.ckpt_dir)) as ckpt:
            exp.restore_checkpoint(ckpt, step=args.ckpt_step)
            # resolved, not requested: the integrity fallback may
            # restore an older retained step than asked for
            repro["ckpt_step"] = ckpt.last_restored_step
        print(f"policy restored from {args.ckpt_dir} "
              f"(step {repro['ckpt_step']})", file=sys.stderr)
    else:
        print("note: no --ckpt-dir; serving untrained init weights",
              file=sys.stderr)

    registry = Registry()
    bus = None
    if args.obs_dir:
        bus = EventBus(os.path.abspath(args.obs_dir), rank=0,
                       name="serve")
    tracer = (Tracer(bus, enabled=True)
              if args.trace_spans else NULL_TRACER)
    scraper = None
    report: dict = {"repro": repro}
    try:
        if args.metrics_port is not None:
            from ..obs import serve_http
            scraper = serve_http(registry, port=args.metrics_port)
            print(f"metrics scrape endpoint: {scraper.url}",
                  file=sys.stderr)
        injector = None
        if chaos_specs is not None:
            from .router import ServeFaultInjector
            injector = ServeFaultInjector(chaos_specs, bus=bus)
        # flight-log recording and canary replay both need the engine's
        # capture outputs (behavior log-prob/value from the SAME
        # compiled decision program — never a post-hoc recompute)
        capture = args.flight_log is not None
        if args.engines > 1:
            from ..parallel.mesh import serve_devices
            avail = len(serve_devices())
            if args.engines > avail:
                sys.exit(f"--engines {args.engines} exceeds the "
                         f"{avail} data-axis device(s) of the unified "
                         f"mesh (one engine per device)")
            engine = EngineRouter(exp.apply_fn, exp.train_state.params,
                                  exp.env_params, max_bucket=args.bucket,
                                  registry=registry, bus=bus,
                                  tracer=tracer, n_engines=args.engines,
                                  fault_injector=injector,
                                  capture=capture)
            print(f"engine router: {args.engines} engines on "
                  f"{[str(e.device) for e in engine.engines]}"
                  + (" (CPU: dispatch serialized)"
                     if engine.serialized_dispatch() else ""),
                  file=sys.stderr)
        else:
            engine = InferenceEngine(exp.apply_fn,
                                     exp.train_state.params,
                                     exp.env_params,
                                     max_bucket=args.bucket,
                                     registry=registry, bus=bus,
                                     tracer=tracer, capture=capture)
        pool = None
        if (args.bench or args.soak is not None or args.scaleout
                or args.host_path or promote_mode):
            pool = build_request_pool(exp.apply_fn,
                                      exp.train_state.params,
                                      exp.env_params, exp.traces,
                                      steps=args.pool_steps,
                                      faults=exp.faults)
        flight_writer = None
        if args.flight_log is not None and args.soak is not None:
            from ..flywheel import FlightLogWriter
            flight_writer = FlightLogWriter(
                os.path.abspath(args.flight_log),
                capacity=args.flight_capacity,
                policy_step=int(exp.train_state.step),
                registry=registry, bus=bus,
                durable=args.durable_log)
        deadline_s = (args.deadline_ms / 1e3
                      if args.deadline_ms is not None else None)
        if args.bench:
            server = PolicyServer(engine, registry=registry,
                                  tracer=tracer, bus=bus,
                                  adaptive_wait=args.adaptive_wait)
            report["bench"] = run_bench(engine, server, pool,
                                        rounds=args.rounds,
                                        request_sizes=sizes)
            b = report["bench"]
            print(f"bench: {b['requests']} decisions over "
                  f"{b['rounds']} dispatches (sizes "
                  f"{b['request_sizes']} -> buckets {b['buckets']}), "
                  f"p50 {b['latency_p50_ms']:.2f} ms, "
                  f"p99 {b['latency_p99_ms']:.2f} ms, "
                  f"{b['decisions_per_s']:.0f} decisions/s "
                  f"({b['decisions_per_s_per_chip']:.0f}/chip), "
                  f"post-warmup recompiles: "
                  f"{b['post_warmup_recompiles']}", file=sys.stderr)
        if args.soak is not None:
            obs0, mask0 = pool[0]
            engine.warmup(obs0, mask0)   # every bucket pre-paid
            server = PolicyServer(engine, registry=registry,
                                  tracer=tracer, bus=bus,
                                  adaptive_wait=args.adaptive_wait,
                                  flight_log=flight_writer)
            advisor = None
            if args.autoscale:
                advisor = AutoscaleAdvisor(registry,
                                           n_max=args.engines,
                                           initial=args.engines)
            router = engine if args.engines > 1 else None
            server.start(dispatchers=args.engines)
            fe_handle = None
            try:
                if args.frontend_port is not None:
                    from .frontend import start_frontend
                    fe_handle = start_frontend(server, obs0, mask0,
                                               port=args.frontend_port)
                    fe_handle.install_sigterm()
                    print(f"http front door: {fe_handle.url} "
                          f"(SIGTERM drains gracefully)",
                          file=sys.stderr)
                if injector is not None:
                    from ..traces.fit import domain_fit
                    from .bench import run_chaos_soak
                    soak = run_chaos_soak(
                        server, pool, fit=domain_fit(cfg),
                        duration_s=args.soak,
                        rate_hz=(args.rate if args.rate is not None
                                 else 150.0),
                        deadline_s=deadline_s, router=router,
                        seed=cfg.seed)
                else:
                    soak = run_soak(
                        server, pool, duration_s=args.soak,
                        rate_hz=(args.rate if args.rate is not None
                                 else 200.0),
                        deadline_s=deadline_s, router=router,
                        advisor=(advisor if router is not None
                                 else None))
                if fe_handle is not None:
                    report["frontend"] = _frontend_selfcheck(
                        fe_handle, obs0, mask0)
            finally:
                if fe_handle is not None:
                    fe_handle.close()   # drain: also closes the server
                else:
                    server.stop()
            # no manual slo_snapshot() here: the registry collector
            # hook refreshes the gauges at every collect/render — the
            # metrics.prom write below scrapes fresh values (ISSUE 20)
            soak["post_warmup_recompiles"] = \
                engine.post_warmup_recompiles
            report["soak"] = soak
            if flight_writer is not None:
                flight_writer.close()   # seal the tail shard
                # exactly-once accounting: every dispatched row was
                # logged, every shed row was not (shed requests never
                # reach the engine, so they never reach the log)
                # the frontend selfcheck (if it ran) served one more
                # request through the same server after the soak loop
                fe_rows = (1 if report.get("frontend", {})
                           .get("decide_status") == 200 else 0)
                fl = {"dir": os.path.abspath(args.flight_log),
                      "rows_logged": flight_writer.rows_logged,
                      "served": soak["served"] + fe_rows,
                      "conservation_ok":
                          flight_writer.rows_logged
                          == soak["served"] + fe_rows}
                report["flight_log"] = fl
                print(f"flight log: {fl['rows_logged']} rows sealed "
                      f"under {fl['dir']}, conservation "
                      + ("ok" if fl["conservation_ok"] else "VIOLATED"),
                      file=sys.stderr)
            drift = soak["p99_drift"]
            print(f"soak: {soak['requests']} requests over "
                  f"{soak['duration_s']:.1f}s at {soak['rate_hz']:.0f}/s"
                  f", shed {soak['shed']} "
                  f"({soak['shed_rate']:.1%}), p99 "
                  f"{soak['p99_first_half_ms']} -> "
                  f"{soak['p99_second_half_ms']} ms (drift "
                  + (f"{drift:.2f}x" if drift is not None else "n/a")
                  + f"), post-warmup recompiles: "
                  f"{soak['post_warmup_recompiles']}", file=sys.stderr)
            if injector is not None:
                fs = soak["fault_stats"]
                fired = sum(s.fired for s in chaos_specs)
                soak["chaos_faults"] = args.chaos_faults
                soak["faults_fired"] = int(fired)
                conserved = (soak["conservation_ok"]
                             and soak["failed"] == 0)
                print(f"chaos: {fired}/{len(chaos_specs)} faults fired, "
                      f"engine failures {fs['failures']}, ejections "
                      f"{fs['ejections']}, readmissions "
                      f"{fs['readmissions']}, retry hedges "
                      f"{fs['retry_hedges']}, conservation "
                      + ("ok" if conserved else "VIOLATED"),
                      file=sys.stderr)
        if promote_mode:
            report["promote"] = _run_promotion(
                args, cfg, exp, engine, pool, registry, bus,
                warmed=args.soak is not None)
        if args.scaleout:
            report["scaleout"] = run_scaleout(
                exp.apply_fn, exp.train_state.params, exp.env_params,
                pool, max_bucket=args.bucket, rounds=args.rounds,
                request_sizes=sizes,
                engine_counts=(1, args.engines),
                deadline_s=deadline_s)
            for arm in report["scaleout"]["arms"]:
                print(f"scaleout[{arm['engines']} engine(s)]: "
                      f"{arm['decisions_per_s']:.0f} decisions/s, "
                      f"shed {arm['shed_rate']:.1%}, rows/engine "
                      f"{arm['per_engine_rows']}, recompiles "
                      f"{arm['per_engine_recompiles']}",
                      file=sys.stderr)
        if args.host_path:
            hp = run_host_path(pool, max_bucket=args.bucket,
                               rounds=args.host_rounds,
                               wire_requests=args.wire_requests)
            report["host_path"] = hp
            for arm in hp["arms"]:
                print(f"host-path[{arm['data_plane']}]: "
                      f"{arm['decisions_per_s']:.0f} decisions/s, "
                      f"{arm['alloc_calls']} ndarray allocs "
                      f"({arm['allocs_per_batch']:.1f}/batch), "
                      f"conservation "
                      + ("ok" if arm["conservation_ok"] else "VIOLATED"),
                      file=sys.stderr)
            for arm in hp.get("wire_arms", ()):
                print(f"host-path[{arm['transport']}]: "
                      f"{arm['decisions_per_s']:.0f} decisions/s over "
                      f"{arm['clients']} clients, conservation "
                      + ("ok" if arm["conservation_ok"] else "VIOLATED"),
                      file=sys.stderr)
            line = f"host-path speedup: {hp['speedup']:.2f}x"
            if "wire_arms" in hp:
                line += (" (wire; in-process "
                         f"{hp['speedup_inproc']:.2f}x)")
            print(line, file=sys.stderr)
        if args.fleet is not None:
            windows, traces = fleet_windows(cfg, args.fleet,
                                            source=exp.source)
            faults = None
            if args.fleet_regime is not None:
                faults = sample_fleet_faults(
                    cfg.n_nodes, args.fleet_regime, args.fleet_seed,
                    args.fleet, windows)
            fl = fleet_replay(exp.apply_fn, exp.train_state.params,
                              exp.env_params, traces, faults=faults,
                              max_steps=args.max_steps)
            fl["regime"] = args.fleet_regime
            fl["fleet_seed"] = (args.fleet_seed
                                if args.fleet_regime else None)
            registry.gauge("serve_fleet_mean_jct",
                           "fleet replay pooled mean JCT").set(
                fl["mean_jct"])
            registry.gauge("serve_fleet_completion",
                           "fleet replay completed fraction").set(
                fl["completion"])
            registry.gauge("serve_fleet_decisions_per_s",
                           "fleet replay decision throughput").set(
                fl["decisions_per_s"])
            report["fleet"] = fl
            print(f"fleet: {fl['n_clusters']} clusters"
                  + (f" under {args.fleet_regime!r} faults"
                     if args.fleet_regime else "")
                  + f", mean JCT {fl['mean_jct']:.1f} s, completion "
                  f"{fl['completion']:.1%}, {fl['decisions']} decisions "
                  f"in {fl['wall_s']:.2f} s "
                  f"({fl['decisions_per_s']:.0f}/s)", file=sys.stderr)
        if scraper is not None:
            report["scrape"] = _self_scrape(scraper)
        if args.obs_dir:
            registry.write(os.path.join(os.path.abspath(args.obs_dir),
                                        "metrics.prom"))
    finally:
        if scraper is not None:
            scraper.close()
        if bus is not None:
            bus.close()
    print(json.dumps(report))
    return report


def _swap_weights(engine, params) -> "tuple[int, ...]":
    """Live swap + blessed re-warm through whichever serving surface is
    up: the router swaps every engine under its device lock; a single
    engine swaps in place. Both re-drive the warmed buckets so a shape/
    dtype drift surfaces HERE as a recompile alarm, not on live traffic."""
    if hasattr(engine, "swap_params"):
        return engine.swap_params(params)
    engine.set_params(params)
    return engine.rewarm()


def _run_promotion(args, cfg, exp, engine, pool, registry, bus,
                   warmed: bool) -> dict:
    """``serve --promote``: canary-gate the candidate on the logged
    window, swap only if the gate clears, then watch the post-swap SLOs
    and roll back automatically on regression.

    The candidate comes from ``--promote CKPTDIR`` (a real checkpoint,
    e.g. the continual retrain's output) and/or ``--promote-noise``
    (seeded perturbation — the ci.sh regressed-candidate arm).
    ``--promote-fault`` inflates the watchdog's observed p99 10x after
    the swap: an injected SLO regression exercising the rollback path
    end-to-end (the rollback itself is real — weights swap back and the
    probe must match the pre-promotion decisions bit-identically)."""
    import os
    import time

    import jax
    import numpy as np

    from ..flywheel import (PromotionLedger, SLOWatchdog, read_flight_log,
                            run_canary, unflatten_like)

    flight_dir = os.path.abspath(args.flight_log)
    data = read_flight_log(flight_dir)
    if not data.shards:
        sys.exit(f"--promote: no verified flight-log shards under "
                 f"{flight_dir}"
                 + (f" (torn tail: {data.torn_reason})"
                    if data.torn_tail else ""))
    window = data.concat()
    obs0, mask0 = pool[0]
    incumbent = exp.train_state.params

    candidate = incumbent
    source = "incumbent"
    if args.promote is not None:
        from ..checkpoint import Checkpointer
        with Checkpointer(os.path.abspath(args.promote)) as cckpt:
            cand_state, _, _, _ = cckpt.restore(
                exp.train_state, step=args.promote_step)
            source = (f"{os.path.abspath(args.promote)}"
                      f"@{cckpt.last_restored_step}")
        candidate = cand_state.params
    if args.promote_noise is not None:
        rng = np.random.default_rng(cfg.seed)
        candidate = jax.tree.map(
            lambda l: (np.asarray(l) + rng.normal(
                0.0, args.promote_noise, np.shape(l)
            ).astype(np.asarray(l).dtype))
            if np.issubdtype(np.asarray(l).dtype, np.floating) else l,
            candidate)
        source += f"+noise(sigma={args.promote_noise:g},seed={cfg.seed})"

    rep = run_canary(exp.apply_fn, incumbent, candidate, window,
                     obs0, mask0, env_params=exp.env_params,
                     slices=args.canary_slices, tol=args.canary_tol,
                     hysteresis=args.canary_hysteresis,
                     registry=registry, bus=bus)
    ledger = PromotionLedger(flight_dir, durable=args.durable_log)
    lineage = {"candidate": source,
               "incumbent_step": int(exp.train_state.step),
               "window_rows": window.rows,
               "verdict": rep.verdict,
               "incumbent_agreement": rep.incumbent_agreement,
               "candidate_agreement": rep.candidate_agreement}
    out = {"candidate": source, "verdict": rep.verdict,
           "canary": rep.to_json(), "promoted": False,
           "rollback": False, "ledger_entries": 1}
    if rep.verdict != "promote":
        ledger.append(dict(lineage, action="blocked",
                           regress_streak=rep.max_regress_streak))
        print(f"promotion BLOCKED: candidate agreement "
              f"{rep.candidate_agreement:.3f} vs incumbent "
              f"{rep.incumbent_agreement:.3f} on the logged window "
              f"(regressed streak {rep.max_regress_streak} >= "
              f"{args.canary_hysteresis})", file=sys.stderr)
        return out

    # gate cleared: pre-promotion probe -> swap -> watchdog
    if not warmed:
        engine.warmup(obs0, mask0)
    k = min(args.bucket, window.rows)
    probe_obs = unflatten_like(obs0, [l[:k] for l in window.obs_leaves])
    probe_mask = unflatten_like(mask0,
                                [l[:k] for l in window.mask_leaves])
    probe_stall = window.stall[:k]

    def probe() -> "tuple[list, float]":
        t0 = time.perf_counter()
        dec, _ = engine.decide(probe_obs, probe_mask, probe_stall)
        # capture triple: [0] is the action tree (promote mode always
        # serves a capture engine — --flight-log is required)
        acts = [np.asarray(a) for a in jax.tree.leaves(
            jax.device_get(dec[0]))]
        return acts, (time.perf_counter() - t0) * 1e3

    g_p99 = registry.gauge("serve_decision_latency_p99_ms")
    wd = SLOWatchdog(registry, engine=engine, breach_after=2, bus=bus)
    pre_acts: list = []
    for _ in range(4):
        pre_acts, ms = probe()
        g_p99.set(ms)
        wd.sample_baseline()
    recomp_before = int(engine.post_warmup_recompiles)
    driven = _swap_weights(engine, candidate)
    wd.arm()
    swap_recompiles = int(engine.post_warmup_recompiles) - recomp_before
    if bus is not None:
        bus.emit("promote_apply", candidate=source,
                 rewarmed_buckets=list(driven),
                 swap_recompiles=swap_recompiles)
    ledger.append(dict(lineage, action="promote",
                       rewarmed_buckets=list(driven),
                       swap_recompiles=swap_recompiles))
    out.update(promoted=True, rewarmed_buckets=list(driven),
               swap_recompiles=swap_recompiles, ledger_entries=2)
    print(f"promoted {source}: canary agreement "
          f"{rep.candidate_agreement:.3f}, re-warmed buckets "
          f"{tuple(driven)}, swap recompiles {swap_recompiles}",
          file=sys.stderr)

    ticks, breach = [], None
    for _ in range(max(3, args.canary_hysteresis + 1)):
        _, ms = probe()
        if args.promote_fault:
            ms *= 10.0        # injected post-swap SLO regression
        g_p99.set(ms)
        tick = wd.observe()
        ticks.append({k_: tick[k_] for k_ in
                      ("rollback", "reasons", "streak", "p99_ms",
                       "baseline_p99_ms")})
        if tick["rollback"]:
            breach = tick
            break
    out["watchdog_ticks"] = ticks
    if breach is not None:
        _swap_weights(engine, incumbent)
        post_acts, _ = probe()
        bit = (len(pre_acts) == len(post_acts)
               and all(np.array_equal(a, b)
                       for a, b in zip(pre_acts, post_acts)))
        ledger.append(dict(lineage, action="rollback",
                           reasons=breach["reasons"],
                           bit_identical=bool(bit)))
        out.update(rollback=True, rollback_reasons=breach["reasons"],
                   probe_bit_identical=bool(bit), ledger_entries=3)
        print(f"ROLLBACK: {breach['reasons']}; incumbent restored, "
              f"probe decisions bit-identical: {bit}", file=sys.stderr)
    out["post_warmup_recompiles"] = int(engine.post_warmup_recompiles)
    return out


def _frontend_selfcheck(handle, obs0, mask0) -> dict:
    """Prove the wire contract on the live front door: one real POST
    decide must answer 200 with an action (no deadline attached — a
    cold or loaded server still serves), then a graceful drain, after
    which a late submit gets the typed :class:`ServerClosedError` (the
    never-a-hung-future half of the drain contract) and new connections
    are refused outright."""
    import urllib.error
    import urllib.request

    import numpy as np

    from .batching import ServerClosedError

    body = (np.ascontiguousarray(obs0).tobytes()
            + np.ascontiguousarray(mask0).tobytes())
    req = urllib.request.Request(handle.url + "/v1/decide", data=body,
                                 method="POST")
    with urllib.request.urlopen(req, timeout=30) as resp:
        decide_status = resp.status
        payload = json.loads(resp.read().decode())
    handle.drain()
    try:
        handle.frontend.server.submit(obs0, mask0)
        late_submit = "accepted"          # contract violation
    except ServerClosedError:
        late_submit = "server-closed"
    try:
        urllib.request.urlopen(
            urllib.request.Request(handle.url + "/v1/decide", data=body,
                                   method="POST"), timeout=5)
        post_drain_connect = "accepted"   # contract violation
    except (urllib.error.URLError, ConnectionError):
        post_drain_connect = "refused"
    return {"url": handle.url, "port": handle.port,
            "decide_status": decide_status,
            "decide_has_action": "action" in payload,
            "drained": True, "late_submit": late_submit,
            "post_drain_connect": post_drain_connect}


def _self_scrape(scraper) -> dict:
    """GET the live endpoint once and validate the exposition is
    well-formed — the smoke proof that a fleet scraper would accept it."""
    import urllib.request
    with urllib.request.urlopen(scraper.url, timeout=10) as resp:
        body = resp.read().decode("utf-8")
        status = resp.status
        ctype = resp.headers.get("Content-Type", "")
    lines = [ln for ln in body.splitlines() if ln]
    sample_lines = [ln for ln in lines if not ln.startswith("#")]
    well_formed = (
        status == 200 and ctype.startswith("text/plain")
        and all(ln.startswith(("# HELP ", "# TYPE "))
                or len(ln.split()) == 2 for ln in lines)
        and any(ln.startswith("serve_") for ln in sample_lines))
    return {"url": scraper.url, "port": scraper.port, "status": status,
            "content_type": ctype, "metric_lines": len(sample_lines),
            "well_formed": bool(well_formed)}


if __name__ == "__main__":
    main()
