"""Vmapped fleet replay: one checkpoint vs thousands of clusters at once.

The throughput half of the serving story: instead of streaming requests
through the continuous-batching front end, evaluate the policy against
``N`` seeded simulated clusters as ONE program — the batched
``eval.replay`` scan with the cluster index as the batch axis (the
TF-Agents batched-environment pattern at fleet scale). Because it IS
``eval.replay`` — same decision rule, same env step, same pooling — a
fleet replay of N clusters matches N sequential single-cluster
evaluations bit-for-bit on CPU (the ISSUE 7 acceptance gate,
tests/test_serve.py), while dispatching once instead of N times.

Optionally each cluster replays under a seeded
:mod:`..sim.faults` regime (cluster ``e`` draws schedule ``(seed, e)``
— the chaos matrix's reproducibility contract), so a fleet run doubles
as a degraded-mode SLO probe.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any

import jax
import numpy as np

from ..env import stack_traces
from ..env.env import EnvParams
from ..eval import pooled_avg_jct, replay


def fleet_windows(cfg, n_clusters: int, source=None, start: int = 0):
    """Cut ``n_clusters`` seeded trace windows (one per simulated
    cluster) from the config's source trace — the same deterministic
    tiling training/eval use (``experiment.make_env_windows``), so fleet
    cluster ``e`` is exactly eval window ``start + e``. Returns
    ``(windows, batched_traces)``."""
    from ..experiment import (build_env_params, load_source_trace,
                              make_env_windows)
    from ..sim.core import SimParams, validate_trace
    if n_clusters <= 0:
        raise ValueError(f"n_clusters must be positive, got {n_clusters}")
    fleet_cfg = dataclasses.replace(cfg, n_envs=n_clusters)
    if cfg.n_pods > 1:
        # the hierarchical env windows against the per-pod simulator
        # shape (mirrors experiment.build_stack's pod_sim)
        sim_params = SimParams(n_nodes=cfg.n_nodes // cfg.n_pods,
                               gpus_per_node=cfg.gpus_per_node,
                               max_jobs=cfg.window_jobs,
                               queue_len=cfg.queue_len,
                               n_placements=cfg.n_placements)
    else:
        sim_params = build_env_params(cfg).sim
    if source is None:
        source = validate_trace(sim_params, load_source_trace(cfg),
                                clamp=True)
    windows = make_env_windows(fleet_cfg, source, start)
    return windows, stack_traces(windows, sim_params)


def sample_fleet_faults(n_nodes: int, regime: str, seed: int,
                        n_clusters: int, windows) -> Any:
    """Seeded per-cluster fault schedules for a fleet replay: cluster
    ``e`` draws ``(seed, e)`` over the windows' fault horizon — the same
    reproducibility tuple ``evaluate --chaos`` records."""
    from ..sim.faults import (fault_horizon, resolve_regime,
                              sample_fault_schedule,
                              stack_fault_schedules)
    r = resolve_regime(regime)
    horizon_s = fault_horizon(windows)
    return stack_fault_schedules(
        [sample_fault_schedule(n_nodes, r, (seed, e), horizon_s)
         for e in range(n_clusters)])


def fleet_replay(apply_fn, net_params: Any, env_params: Any, traces: Any,
                 faults: Any = None, max_steps: int | None = None,
                 stall_guard: bool = True) -> dict:
    """Replay one checkpoint against the whole cluster batch in a single
    fused-scan dispatch and report throughput-style SLO numbers.

    Returns the pooled fleet table: ``mean_jct`` (completion-weighted
    across clusters — bit-identical to pooling N sequential runs),
    ``completion``, ``decisions`` (total policy decisions taken),
    ``decisions_per_s`` / ``decisions_per_s_per_chip`` over the
    measured wall time, and the ``per_cluster`` arrays behind them."""
    if faults is not None and not isinstance(env_params, EnvParams):
        raise ValueError("fleet fault regimes apply to flat configs "
                         "(the hierarchical env has no fault-process "
                         "support)")
    t0 = time.perf_counter()
    res = replay(apply_fn, net_params, env_params, traces,
                 max_steps=max_steps, stall_guard=stall_guard,
                 faults=faults)
    jax.block_until_ready(res)
    wall = time.perf_counter() - t0
    mean_jct, completion = pooled_avg_jct(res)
    steps = np.asarray(res.steps, np.int64)
    decisions = int(steps.sum())
    n_chips = max(jax.local_device_count(), 1)
    dps = decisions / wall if wall > 0 else 0.0
    return {
        "n_clusters": int(steps.shape[0]),
        "mean_jct": mean_jct,
        "completion": completion,
        "decisions": decisions,
        "wall_s": wall,
        "decisions_per_s": dps,
        "decisions_per_s_per_chip": dps / n_chips,
        "n_chips": n_chips,
        "max_steps": max_steps,
        "per_cluster": {
            "avg_jct": [float(x) for x in np.asarray(res.avg_jct)],
            "n_done": [int(x) for x in np.asarray(res.n_done)],
            "n_valid": [int(x) for x in np.asarray(res.n_valid)],
            "steps": [int(x) for x in steps],
            "makespan": [float(x) for x in np.asarray(res.makespan)],
        },
    }
