"""The network front door: asyncio HTTP in front of the serving stack.

Everything below ``submit()`` already speaks overload fluently — typed
shedding, adaptive bucketing, engine health — but none of it had ever
faced a socket. This module is the thinnest honest wire layer over
:class:`~.batching.PolicyServer` (single engine or
:class:`~.router.EngineRouter` fleet alike), built so that every
failure the serving tier can produce has ONE well-defined HTTP shape:

- ``POST /v1/decide`` carries one request's observation + action-mask
  bytes raw in the body (shapes/dtypes fixed at construction from an
  example request). The body is read once off the socket and viewed
  **zero-copy** with ``np.frombuffer`` — the first copy of a request's
  bytes is the batch stack itself, exactly like an in-process submit.
- ``X-Deadline-Ms`` propagates the client's latency SLO into the
  admission/shedding path. A shed request returns **503** with a
  ``Retry-After`` derived from the LEARNED service-time Ewma (plus the
  predicted excess wait on admission sheds) — the server tells the
  client how long the queue actually needs, instead of a made-up
  constant.
- **Backpressure is connection-level**: past a queue-depth high-water
  mark the listener simply stops reading sockets (an ``asyncio.Event``
  gate ahead of every read), resuming at low-water — unread bytes pile
  up in kernel buffers and TCP pushes back on the client, so overload
  never manifests as an unbounded server-side queue.
- **Graceful drain** (SIGTERM or :meth:`ServeFrontend.drain`): stop
  accepting connections, let every in-flight request resolve, then
  :meth:`~.batching.PolicyServer.close` the server so late submits get
  a typed :class:`~.batching.ServerClosedError` → **503** — never a
  hung future, never a silently dropped request.

Since ISSUE 17 connections are **persistent**: the HTTP/1.1 loop keeps
the connection alive between requests (``Connection: close`` — from the
client, or from the server on drain refusals — ends it), and the same
port speaks a second, cheaper dialect: a connection whose first 4 bytes
are :data:`~.wire.MAGIC` is **framed** for its whole life
(:mod:`.wire` — length-prefixed v2 frames, 32-byte prefix, descriptor
validated by byte equality, no per-request parse; legacy 24-byte v1
frames still decode). Either way ``np.frombuffer`` stays the only
decode, and the views point straight at the arena slot write inside
``submit`` — one copy, wire to slab.

Request causality (ISSUE 20): every decide carries a 64-bit request id
— inbound via the ``X-Request-Id`` header (HTTP) or the v2 frame's
``req_id`` field, minted by the server when absent — and every reply
shape echoes it (the ``request_id`` JSON field / the response frame's
``req_id``), including sheds, timeouts, and drain refusals. The id is
the join key ``obs.report --request`` uses to reconstruct the request's
full timeline across the bus, the flight log, and the canary ledger.

The listener is stdlib-only (``asyncio.start_server`` + hand-rolled
HTTP/1.1) on purpose: no new dependency, and the protocol surface is
small enough to pin completely in tier-1 tests. gRPC and multi-node
ingestion stay ROADMAP open ends.
"""
from __future__ import annotations

import asyncio
import json
import math
import signal
import socket
import threading
from concurrent.futures import Future
from typing import Any

import numpy as np

from . import wire
from .batching import DeadlineSheddedError, PolicyServer, ServerClosedError

DECIDE_PATH = "/v1/decide"
HEALTH_PATH = "/healthz"

# Retry-After sanity band (ISSUE 17 satellite): below 10ms a retry hint
# is noise (the client's RTT dwarfs it), above 30s it reads as an
# outage, and a poisoned/stale estimator must not be able to advertise
# either extreme.
RETRY_AFTER_MIN_S = 0.01
RETRY_AFTER_MAX_S = 30.0


def _response(status: str, payload: dict,
              extra_headers: "tuple[str, ...]" = (),
              close: bool = False) -> bytes:
    body = json.dumps(payload).encode()
    head = [f"HTTP/1.1 {status}",
            "Content-Type: application/json",
            f"Content-Length: {len(body)}",
            "Connection: close" if close else "Connection: keep-alive",
            *extra_headers]
    return ("\r\n".join(head) + "\r\n\r\n").encode() + body


class _BadRequest(Exception):
    """Malformed wire input; maps to 400 without killing the connection."""


class ServeFrontend:
    """One asyncio HTTP listener over a :class:`PolicyServer`.

    Run it natively with ``await fe.start()`` inside an event loop, or
    from synchronous code via :func:`start_frontend` (dedicated loop
    thread). ``example_obs`` / ``example_mask`` fix the wire schema:
    one request's body is exactly ``obs.nbytes + mask.nbytes`` raw
    bytes in that order, C-contiguous, same dtypes.
    """

    def __init__(self, server: PolicyServer, example_obs: Any,
                 example_mask: Any, host: str = "127.0.0.1",
                 port: int = 0, registry=None,
                 high_water: int = 256, low_water: int = 64,
                 poll_s: float = 0.005, request_timeout_s: float = 120.0,
                 drain_grace_s: float = 30.0):
        if not 0 <= low_water < high_water:
            raise ValueError(f"need 0 <= low_water < high_water, got "
                             f"{low_water} / {high_water}")
        self.server = server
        self.host = host
        self.port = int(port)            # 0 = ephemeral; set by start()
        self.high_water = int(high_water)
        self.low_water = int(low_water)
        self.poll_s = float(poll_s)
        self.request_timeout_s = float(request_timeout_s)
        self.drain_grace_s = float(drain_grace_s)
        obs0 = np.ascontiguousarray(example_obs)
        mask0 = np.ascontiguousarray(example_mask)
        self._obs_shape, self._obs_dtype = obs0.shape, obs0.dtype
        self._mask_shape, self._mask_dtype = mask0.shape, mask0.dtype
        self._obs_nbytes, self._mask_nbytes = obs0.nbytes, mask0.nbytes
        # frame mode validates the request schema by byte equality
        # against this descriptor — one ==, no parse on the hot path
        self._req_descriptor = (wire.descriptor(obs0) + b"|"
                                + wire.descriptor(mask0))
        # pre-size the arena from the wire schema so the first request
        # never pays slab construction mid-traffic
        ensure = getattr(server, "ensure_arena", None)
        if callable(ensure):
            ensure(obs0, mask0)
        self._draining = False
        # strong refs to backlog-refusal tasks (see _refuse_backlog);
        # a done callback prunes each when it finishes
        self._backlog_refusals: "list[asyncio.Task]" = []
        self._inflight = 0
        self._tcp: "asyncio.base_events.Server | None" = None
        self._gate: "asyncio.Event | None" = None       # set = reads flow
        self._idle: "asyncio.Event | None" = None       # set = no inflight
        self._bp_task: "asyncio.Task | None" = None
        reg = registry if registry is not None else server.registry
        self._http_requests = reg.counter(
            "serve_frontend_requests_total",
            "HTTP decide requests read off the wire")
        self._http_shed = reg.counter(
            "serve_frontend_shed_total",
            "HTTP decide requests answered 503 with Retry-After "
            "(deadline shed)")
        self._http_closed = reg.counter(
            "serve_frontend_closed_total",
            "HTTP decide requests refused because the server is "
            "draining/closed")
        self._http_bad = reg.counter(
            "serve_frontend_bad_requests_total",
            "HTTP requests answered 400 (malformed wire input)")
        self._pauses = reg.counter(
            "serve_frontend_backpressure_pauses_total",
            "times the listener stopped reading sockets at the "
            "queue-depth high-water mark")
        self._g_paused = reg.gauge(
            "serve_frontend_paused",
            "1 while socket reads are paused for backpressure")

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    @property
    def draining(self) -> bool:
        return self._draining

    # ---- lifecycle ---------------------------------------------------

    async def start(self) -> int:
        """Bind and serve (returns immediately; the listener runs on
        the current event loop). Returns the bound port."""
        if self._tcp is not None:
            raise RuntimeError("frontend already started")
        self._gate = asyncio.Event()
        self._gate.set()
        self._idle = asyncio.Event()
        self._idle.set()
        self._tcp = await asyncio.start_server(
            self._on_connection, self.host, self.port)
        self.port = self._tcp.sockets[0].getsockname()[1]
        self._bp_task = asyncio.get_running_loop().create_task(
            self._backpressure_loop())
        return self.port

    async def drain(self) -> None:
        """Graceful shutdown: stop accepting, flush in-flight requests,
        then permanently close the policy server so any straggler
        submit raises :class:`ServerClosedError` — the never-a-hung-
        future half of the contract. Idempotent."""
        already = self._draining
        self._draining = True
        if self._tcp is not None:
            # A connection that finished its TCP handshake but is not
            # yet a transport when the listener closes is silently
            # orphaned — the client hangs on a dead socket. Two windows:
            # (a) accepted by the selector, accept-task still queued: on
            #     3.10 Server.close() makes Server._attach assert, the
            #     error is swallowed and the socket leaks;
            # (b) still in the kernel accept queue: the listener close
            #     strands it (Linux does NOT reset queued connections).
            # Close both: stop the accept reader FIRST, tick the loop so
            # queued accept tasks attach while the server is still open
            # (their handlers then serve the typed refusal), dup the
            # listening sockets (the accept queue lives on the shared
            # file description), close the listener, and hand every
            # still-queued connection to the normal handler.
            loop = asyncio.get_running_loop()
            for ts in self._tcp.sockets:
                try:
                    loop.remove_reader(ts.fileno())
                except (ValueError, OSError):
                    pass
            await asyncio.sleep(0)
            await asyncio.sleep(0)
            backlog = [ts.dup() for ts in self._tcp.sockets]
            self._tcp.close()
            await self._tcp.wait_closed()
            await self._refuse_backlog(backlog)
        if self._gate is not None:
            # wake paused readers: their next request gets a typed 503
            self._gate.set()
        if self._idle is not None:
            await asyncio.wait_for(self._idle.wait(), self.drain_grace_s)
        if self._bp_task is not None:
            self._bp_task.cancel()   # idempotent; keep the handle
        if not already:
            # PolicyServer.close joins dispatcher threads — off-loop
            await asyncio.to_thread(self.server.close)

    async def _refuse_backlog(self, socks: "list[socket.socket]") -> None:
        """Accept whatever the kernel queued on the (now closed)
        listener and serve each straggler through the normal handler —
        ``_draining`` is already set, so they get the typed 503/ERR
        refusal with ``Connection: close`` instead of dead air. The
        accept pass is non-blocking and the handlers run as loop tasks
        (NOT awaited here — a straggler that connected but never sends
        must not hold the drain hostage in the protocol sniff; it is
        closed when the loop shuts down, which is an EOF to the client,
        not a hang)."""
        for ls in socks:
            ls.setblocking(False)
            while True:
                try:
                    conn, _ = ls.accept()
                except (BlockingIOError, InterruptedError, OSError):
                    break
                reader, writer = await asyncio.open_connection(sock=conn)
                task = asyncio.ensure_future(
                    self._on_connection(reader, writer))
                self._backlog_refusals.append(task)
                task.add_done_callback(self._backlog_refusals.remove)
            ls.close()

    # ---- backpressure ------------------------------------------------

    async def _backpressure_loop(self) -> None:
        """Sample queue depth; gate socket reads between the high- and
        low-water marks (classic hysteresis so the gate cannot flap on
        a depth hovering at one threshold)."""
        assert self._gate is not None
        while not self._draining:
            depth = self.server.queue_depth()
            if self._gate.is_set():
                if depth >= self.high_water:
                    self._gate.clear()
                    self._pauses.inc()
                    self._g_paused.set(1)
            elif depth <= self.low_water:
                self._gate.set()
                self._g_paused.set(0)
            await asyncio.sleep(self.poll_s)

    # ---- connection handling -----------------------------------------

    async def _on_connection(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        assert self._gate is not None and self._idle is not None
        try:
            # protocol sniff: a framed connection announces itself with
            # the 4 magic bytes; anything else is HTTP (the sniffed
            # bytes are re-threaded into the request-line parse)
            sniff = b""
            while len(sniff) < len(wire.MAGIC):
                chunk = await reader.read(len(wire.MAGIC) - len(sniff))
                if not chunk:
                    break
                sniff += chunk
            if not sniff:
                return
            if sniff == wire.MAGIC:
                await self._serve_framed(reader, writer, sniff)
            else:
                await self._serve_http(reader, writer, sniff)
        except (asyncio.IncompleteReadError, ConnectionResetError):
            return   # client went away mid-request; nothing to answer
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError):
                pass

    async def _serve_http(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter,
                          prefix: bytes) -> None:
        """HTTP/1.1 keep-alive loop: one connection serves N requests
        until the client asks ``Connection: close``, EOF, or the server
        refuses further work (drain) — refusals carry
        ``Connection: close`` so a well-behaved client re-resolves
        instead of pipelining into a dying socket."""
        while True:
            # connection-level backpressure: do not even READ the
            # next request while the queue is past high-water
            if not self._gate.is_set():
                await self._gate.wait()
            try:
                req = await self._read_request(reader, prefix)
            except _BadRequest as e:
                # the request FRAMING is broken — answer 400 and close,
                # since the stream cannot be resynchronized
                self._http_bad.inc()
                writer.write(_response("400 Bad Request",
                                       {"error": "bad-request",
                                        "detail": str(e)}, close=True))
                await writer.drain()
                return
            prefix = b""
            if req is None:
                return
            try:
                resp, close = await self._handle(*req)
            except _BadRequest as e:
                self._http_bad.inc()
                resp, close = _response("400 Bad Request",
                                        {"error": "bad-request",
                                         "detail": str(e)}), False
            headers = req[2]
            if headers.get("connection", "").lower() == "close":
                if not close:
                    resp = resp.replace(b"Connection: keep-alive",
                                        b"Connection: close", 1)
                close = True
            writer.write(resp)
            await writer.drain()
            if close:
                return

    async def _read_request(self, reader: asyncio.StreamReader,
                            prefix: bytes = b""):
        line = await reader.readline()
        if not line and not prefix:
            return None       # clean EOF between requests
        line = prefix + line
        parts = line.decode("latin-1").split()
        if len(parts) != 3:
            raise _BadRequest("malformed request line")
        method, path = parts[0], parts[1]
        headers: dict[str, str] = {}
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            key, _, val = h.decode("latin-1").partition(":")
            headers[key.strip().lower()] = val.strip()
        try:
            length = int(headers.get("content-length", "0") or "0")
        except ValueError as e:
            raise _BadRequest("bad Content-Length") from e
        body = await reader.readexactly(length) if length > 0 else b""
        return method, path, headers, body

    def _parse_body(self, body: bytes) -> "tuple[Any, Any]":
        """Decode (obs, mask) as read-only **views** over ``body`` —
        never copies; the first copy is the batch stack, same as an
        in-process submit. The views are only safe while ``body`` is
        alive, which submit guarantees by memcpying into the arena slab
        before this frame returns."""
        expected = self._obs_nbytes + self._mask_nbytes
        if len(body) != expected:
            raise _BadRequest(
                f"body must be exactly {expected} bytes "
                f"(obs {self._obs_shape} {self._obs_dtype} + mask "
                f"{self._mask_shape} {self._mask_dtype}), got {len(body)}")
        obs = np.frombuffer(
            body, dtype=self._obs_dtype,
            count=int(np.prod(self._obs_shape, dtype=np.int64)),
        ).reshape(self._obs_shape)
        mask = np.frombuffer(
            body, dtype=self._mask_dtype, offset=self._obs_nbytes,
            count=int(np.prod(self._mask_shape, dtype=np.int64)),
        ).reshape(self._mask_shape)
        return obs, mask

    def _retry_after_s(self, exc: DeadlineSheddedError) -> float:
        """Honest backoff hint: one learned service time (the cost of
        the dispatch that has to finish before the queue moves), plus
        the predicted excess wait on admission sheds. Always finite and
        positive; 1s only when the estimator is still cold (a shed with
        a cold estimator can only be an in-queue expiry — and a
        ``set_active`` weight-swap re-warm RESETS the estimator, so a
        stale pre-swap value can never leak into this hint). Clamped to
        [``RETRY_AFTER_MIN_S``, ``RETRY_AFTER_MAX_S``]: a degenerate
        estimate must not advertise a microsecond retry storm or an
        hour-long outage."""
        svc = self.server.service_time_s()
        retry = svc if svc is not None else 1.0
        if exc.predicted_wait_s is not None:
            retry += max(exc.predicted_wait_s - exc.deadline_s, 0.0)
        return min(max(retry, RETRY_AFTER_MIN_S), RETRY_AFTER_MAX_S)

    async def _decide(self, obs, mask, stall: int,
                      deadline_s: "float | None", req_id: int = 0):
        """The transport-agnostic decide core: submit, await, classify.
        Returns ``(status, payload)`` where status is one of ``"ok"``
        (payload = :class:`~.batching.ServeResult`), ``"shed"``
        (payload = (exc, retry_after_s)), ``"closed"`` (payload = detail
        str), ``"timeout"``. ``req_id`` threads the causality key into
        the server (0 = let ``submit`` mint one)."""
        assert self._idle is not None
        self._inflight += 1
        self._idle.clear()
        try:
            try:
                fut = self.server.submit(obs, mask, stall=stall,
                                         deadline_s=deadline_s,
                                         req_id=req_id)
            except ServerClosedError:
                return "closed", "server is draining"
            try:
                result = await asyncio.wait_for(
                    asyncio.wrap_future(fut), self.request_timeout_s)
            except DeadlineSheddedError as e:
                return "shed", (e, self._retry_after_s(e))
            except ServerClosedError:
                return "closed", "server closed mid-request"
            except asyncio.TimeoutError:
                return "timeout", None
            return "ok", result
        finally:
            self._inflight -= 1
            if self._inflight == 0:
                self._idle.set()

    async def _handle(self, method: str, path: str, headers: dict,
                      body: bytes) -> "tuple[bytes, bool]":
        """One HTTP request -> (response bytes, close-connection flag).
        Drain/closed refusals close: a kept-alive client pipelining
        into a draining server gets the typed 503 AND the signal to
        re-resolve, never a hang."""
        if method == "GET" and path == HEALTH_PATH:
            return _response("200 OK", {
                "status": "draining" if self._draining else "ok",
                "queue_depth": self.server.queue_depth()}), False
        if method != "POST" or path != DECIDE_PATH:
            return _response("404 Not Found", {"error": "unknown route",
                                               "path": path}), False
        self._http_requests.inc()
        if self._draining:
            self._http_closed.inc()
            return _response("503 Service Unavailable",
                             {"error": "closed",
                              "detail": "server is draining"},
                             close=True), True
        obs, mask = self._parse_body(body)
        deadline_s = None
        if "x-deadline-ms" in headers:
            try:
                deadline_s = float(headers["x-deadline-ms"]) / 1e3
            except ValueError as e:
                raise _BadRequest("bad X-Deadline-Ms") from e
            if not (math.isfinite(deadline_s) and deadline_s > 0):
                raise _BadRequest("X-Deadline-Ms must be finite and > 0")
        try:
            stall = int(headers.get("x-stall", "0") or "0")
        except ValueError as e:
            raise _BadRequest("bad X-Stall") from e
        req_id = 0
        if "x-request-id" in headers:
            try:
                req_id = int(headers["x-request-id"], 0)
            except ValueError as e:
                raise _BadRequest("bad X-Request-Id") from e
            if not 0 <= req_id < (1 << 63):
                raise _BadRequest("X-Request-Id must be in [0, 2**63)")
        if not req_id:
            req_id = self.server.mint_request_id()

        status, payload = await self._decide(obs, mask, stall, deadline_s,
                                             req_id)
        if status == "closed":
            self._http_closed.inc()
            return _response("503 Service Unavailable",
                             {"error": "closed", "detail": payload,
                              "request_id": req_id},
                             close=True), True
        if status == "shed":
            exc, retry = payload
            self._http_shed.inc()
            return _response(
                "503 Service Unavailable",
                {"error": "shed", "reason": exc.reason,
                 "deadline_ms": exc.deadline_s * 1e3,
                 "waited_ms": exc.waited_s * 1e3,
                 "retry_after_s": retry,
                 "request_id": req_id},
                (f"Retry-After: {retry:.3f}",)), False
        if status == "timeout":
            return _response("504 Gateway Timeout",
                             {"error": "timeout",
                              "timeout_s": self.request_timeout_s,
                              "request_id": req_id}), False
        result = payload
        import jax
        action = jax.tree.map(lambda x: np.asarray(x).tolist(),
                              result.action)
        return _response("200 OK",
                         {"action": action,
                          "latency_ms": result.latency_s * 1e3,
                          "request_id": req_id}), False

    # ---- frame mode --------------------------------------------------

    async def _read_frame(self, reader: asyncio.StreamReader,
                          preread: bytes = b""):
        # sniff the version byte: v1 prefixes are 24 bytes, v2 are 32
        # (8 extra bytes of req_id) — same logic as wire.recv_frame
        head = preread + await reader.readexactly(
            wire.PREFIX_V1_SIZE - len(preread))
        if head[4] == wire.VERSION:
            head += await reader.readexactly(
                wire.PREFIX_SIZE - wire.PREFIX_V1_SIZE)
        kind, hlen, blen, meta64, meta32, req_id = wire.unpack_prefix(head)
        header = await reader.readexactly(hlen) if hlen else b""
        body = await reader.readexactly(blen) if blen else b""
        return kind, header, body, meta64, meta32, req_id

    async def _serve_framed(self, reader: asyncio.StreamReader,
                            writer: asyncio.StreamWriter,
                            sniffed: bytes) -> None:
        """The binary dialect: one persistent connection, N request
        frames, same shedding/drain semantics as HTTP — an ERR frame
        with reason ``closed`` is terminal for the connection, exactly
        like ``Connection: close`` on a 503."""
        preread = sniffed
        while True:
            if not self._gate.is_set():
                await self._gate.wait()
            try:
                frame = await self._read_frame(reader, preread)
            except wire.WireError as e:
                self._http_bad.inc()
                writer.write(wire.pack_error("bad-request",
                                             {"detail": str(e)}))
                await writer.drain()
                return      # framing is lost; the stream cannot resync
            preread = b""
            kind, header, body, meta64, meta32, req_id = frame
            resp, close = await self._handle_frame(kind, header, body,
                                                   meta64, meta32, req_id)
            writer.write(resp)
            await writer.drain()
            if close:
                return

    async def _handle_frame(self, kind: int, header: bytes, body: bytes,
                            meta64: int, meta32: int, req_id: int = 0):
        if kind != wire.KIND_REQ:
            self._http_bad.inc()
            return wire.pack_error(
                "bad-request",
                {"detail": f"expected KIND_REQ, got {kind}"},
                req_id=req_id), True
        if req_id >= (1 << 63):
            # the wire field is uint64 but the causality lane is int64
            # (flight-log column) — reject rather than truncate
            self._http_bad.inc()
            return wire.pack_error(
                "bad-request",
                {"detail": "req_id must be < 2**63"}), False
        self._http_requests.inc()
        if not req_id:
            req_id = self.server.mint_request_id()
        if self._draining:
            self._http_closed.inc()
            return wire.pack_error(
                "closed", {"detail": "server is draining"},
                req_id=req_id), True
        if header != self._req_descriptor:
            self._http_bad.inc()
            return wire.pack_error(
                "bad-request",
                {"detail": f"descriptor mismatch: got {header!r}, "
                           f"serving {self._req_descriptor.decode()}"},
                req_id=req_id), False
        expected = self._obs_nbytes + self._mask_nbytes
        if len(body) != expected:
            self._http_bad.inc()
            return wire.pack_error(
                "bad-request",
                {"detail": f"body must be exactly {expected} bytes, "
                           f"got {len(body)}"},
                req_id=req_id), False
        obs, mask = self._parse_body(body)
        deadline_s = meta64 / 1e6 if meta64 else None
        status, payload = await self._decide(obs, mask, int(meta32),
                                             deadline_s, req_id)
        if status == "closed":
            self._http_closed.inc()
            return wire.pack_error("closed", {"detail": payload},
                                   req_id=req_id), True
        if status == "shed":
            exc, retry = payload
            self._http_shed.inc()
            return wire.pack_error(
                f"shed:{exc.reason}",
                {"deadline_ms": exc.deadline_s * 1e3,
                 "waited_ms": exc.waited_s * 1e3,
                 "retry_after_s": retry},
                retry_after_s=retry, req_id=req_id), False
        if status == "timeout":
            return wire.pack_error(
                "timeout", {"timeout_s": self.request_timeout_s},
                req_id=req_id), False
        result = payload
        return wire.pack_response(np.asarray(result.action),
                                  result.latency_s, req_id=req_id), False


class FrontendHandle:
    """Synchronous handle over a :class:`ServeFrontend` running on its
    own event-loop thread (:func:`start_frontend`). Every wait is
    bounded — a handle can never hang its caller."""

    def __init__(self, frontend: ServeFrontend,
                 loop: asyncio.AbstractEventLoop,
                 thread: threading.Thread):
        self.frontend = frontend
        self._loop = loop
        self._thread = thread
        self._prev_sigterm = None

    @property
    def port(self) -> int:
        return self.frontend.port

    @property
    def url(self) -> str:
        return self.frontend.url

    def drain(self, timeout: float = 60.0) -> None:
        """Run the graceful drain to completion (blocking, bounded)."""
        asyncio.run_coroutine_threadsafe(
            self.frontend.drain(), self._loop).result(timeout=timeout)

    def install_sigterm(self) -> None:
        """SIGTERM → graceful drain (scheduled on the loop thread; the
        signal handler itself never blocks). Main thread only."""
        def _on_sigterm(signum, frame):
            asyncio.run_coroutine_threadsafe(
                self.frontend.drain(), self._loop)
        self._prev_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)

    def close(self, timeout: float = 60.0) -> None:
        """Drain (if not already) then stop and join the loop thread."""
        try:
            self.drain(timeout=timeout)
        finally:
            if self._prev_sigterm is not None:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
                self._prev_sigterm = None
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=10)


def start_frontend(server: PolicyServer, example_obs: Any,
                   example_mask: Any, **kw: Any) -> FrontendHandle:
    """Start a :class:`ServeFrontend` on a dedicated event-loop thread
    and block (bounded) until it is bound. Keyword args pass through to
    the :class:`ServeFrontend` constructor."""
    fe = ServeFrontend(server, example_obs, example_mask, **kw)
    loop = asyncio.new_event_loop()
    bound: Future = Future()

    def _frontend_loop():
        asyncio.set_event_loop(loop)
        try:
            port = loop.run_until_complete(fe.start())
        except BaseException as e:   # bind failure must not hang callers
            bound.set_exception(e)
            loop.close()
            return
        bound.set_result(port)
        try:
            loop.run_forever()
        finally:
            # cancel stragglers so close() leaves a clean loop behind
            for task in asyncio.all_tasks(loop):
                task.cancel()
            loop.run_until_complete(
                loop.shutdown_asyncgens())
            loop.close()

    t = threading.Thread(target=_frontend_loop, name="serve-frontend",
                         daemon=True)
    t.start()
    bound.result(timeout=30)
    return FrontendHandle(fe, loop, t)
