"""Continuous-batching front end: queue -> coalesce -> pad -> scatter.

The TF-Agents batched-environment insight (PAPERS.md: arXiv 1709.02878)
applied to serving: many independent decision streams become ONE
dispatch when their observations are stacked along a batch axis. The
front end's whole job is managing that axis on the host side:

- **coalesce**: pending requests are drained FIFO and rounded up to the
  next power-of-two *bucket* (``next_bucket``), so the jitted policy
  step compiles once per bucket instead of once per request count;
- **pad**: the tail of the bucket is filled with neutral rows (zero
  observations, all-actions-legal masks — a padded row must never
  produce ``-inf``-everywhere logits or NaNs, its action is discarded
  anyway);
- **scatter**: the batched action array is split back to the submitting
  requests in FIFO order.

Since ISSUE 17 the hot path is the **arena data plane**
(``data_plane="arena"``, the default): requests land directly in
preallocated bucket-sized slabs (one memcpy from the wire bytes into
the slot row — ``submit`` IS the stack), ``pump`` seals a slab in place
(tail rows neutralized by slice assignment, no ``np.concatenate``) and
dispatches a contiguous view, and ``scatter`` hands back views into the
single device-fetched actions buffer. Steady state allocates ZERO new
host ndarrays per batch (asserted by test; ``serve_arena_allocs_total``
counts slab allocations and must stay flat after warmup). The handoff
is **lock-light**: producers take one tiny O(1) critical section to
reserve a sequence-numbered slot (CPython's GIL rules out a true CAS
loop, so "lock-free reservation" is not expressible — the honest
version is a lock held for a handful of bytecodes, never across a copy
or a dispatch), the row memcpy and the publish flag happen outside any
lock, and the consumer side never holds the producers' lock during its
O(batch) stacking/accounting work (the legacy plane shared ONE lock for
all of that).

The pre-arena plane survives as ``data_plane="legacy"`` — the measured
"before" arm of ``serve.bench.run_host_path`` (BENCH_r09) and a
fallback — via ``stack_requests``/``pad_batch``, which also remain the
public padding utilities for non-hot-path callers (router probes,
engine warmup).

Everything operates on HOST pytrees (numpy leaves, leading request
axis); device placement is the engine's job, so the queue never holds
device buffers hostage.
"""
from __future__ import annotations

import collections
import dataclasses
import itertools
import os
import random
import threading
import time
from concurrent.futures import Future
from typing import Any

import numpy as np

from ..obs.trace import NULL_TRACER


class Reservoir:
    """Bounded uniform sample of an unbounded stream (Vitter's
    Algorithm R): the first ``capacity`` observations are kept verbatim,
    after which each new observation replaces a random kept one with
    probability ``capacity / count``. Memory stays flat forever while
    every observation ever made has EQUAL probability of being in the
    sample — unlike a ``deque(maxlen=)`` ring, whose percentiles only
    describe the last ``capacity`` observations of a long soak run.
    Seeded so two servers replaying one workload keep identical samples.

    Sequence protocol (``len``/indexing/iteration) so ``np.asarray``
    and ``np.percentile`` consume it directly; ``count`` is the total
    number of observations ever offered.
    """

    def __init__(self, capacity: int, seed: int = 0):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = int(capacity)
        self.count = 0
        self._rng = random.Random(seed)
        self._samples: list[float] = []

    def append(self, v: float) -> None:
        self.count += 1
        if len(self._samples) < self.capacity:
            self._samples.append(v)
            return
        j = self._rng.randrange(self.count)
        if j < self.capacity:
            self._samples[j] = v

    def __len__(self) -> int:
        return len(self._samples)

    def __getitem__(self, i):
        return self._samples[i]

    def __iter__(self):
        return iter(self._samples)


def next_bucket(n: int, max_bucket: int) -> int:
    """The power-of-two batch bucket for ``n`` requests (smallest power
    of two >= n, capped by ``max_bucket``). Compiling one executable per
    bucket bounds the jit cache at log2(max_bucket)+1 entries while
    wasting at most half a batch of padding."""
    if n <= 0:
        raise ValueError(f"need at least one request, got {n}")
    if max_bucket <= 0 or (max_bucket & (max_bucket - 1)):
        raise ValueError(f"max_bucket must be a positive power of two, "
                         f"got {max_bucket}")
    if n > max_bucket:
        raise ValueError(f"{n} requests exceed max_bucket={max_bucket}; "
                         f"drain in max_bucket-sized dispatches")
    return 1 << (n - 1).bit_length()


def stack_requests(rows: "list[Any]") -> Any:
    """Stack per-request pytrees (no leading axis) into one batched host
    pytree (leading axis = len(rows), FIFO order preserved). Legacy-
    plane / probe utility: the arena plane never stacks — rows are
    written into the slab at submit time."""
    import jax

    def stack(*xs):
        # jsan: disable=alloc-in-hot-loop -- legacy data plane (the bench
        # before-arm) and rare router probes; the arena plane never stacks
        return np.stack([np.asarray(x) for x in xs])

    return jax.tree.map(stack, *rows)


# Padding fill constants, hoisted out of the per-call path (ISSUE 17
# satellite): keyed by (pad rows, row tail shape, dtype, mask fill), so
# the bool-mask-pads-True / everything-else-pads-zero branch and the
# constant construction happen ONCE per bucket shape instead of per
# call, and the fill dtype is the leaf dtype by construction — padding
# can never promote (pinned by a dtype-stability test). The cache is
# bounded by the number of distinct (bucket, leaf) shapes a process
# serves — a handful.
_PAD_FILL_CACHE: "dict[tuple, np.ndarray]" = {}


def _pad_fill(rows: int, tail: tuple, dtype: np.dtype,
              mask_true: bool) -> np.ndarray:
    key = (rows, tail, dtype, bool(mask_true))
    fill = _PAD_FILL_CACHE.get(key)
    if fill is None:
        value = True if (mask_true and dtype == np.bool_) else 0
        fill = np.full((rows,) + tail, value, dtype)
        fill.setflags(write=False)      # shared across batches: immutable
        _PAD_FILL_CACHE[key] = fill
    return fill


def pad_batch(batch: Any, bucket: int, fill_mask_true: bool = False) -> Any:
    """Pad a batched host pytree from n rows up to ``bucket`` rows.

    Padding rows are zeros, EXCEPT boolean leaves when
    ``fill_mask_true``: action masks pad with every action legal, so the
    padded rows' logits stay finite under the ``-inf`` masking scheme
    (an all-masked row is the degenerate case the models never see in
    training). A full bucket (n == bucket) returns the input unchanged —
    the arena plane relies on this no-op to dispatch slab views without
    a copy."""
    import jax

    def pad(x):
        x = np.asarray(x)
        n = x.shape[0]
        if n > bucket:
            raise ValueError(f"batch of {n} rows exceeds bucket {bucket}")
        if n == bucket:
            return x
        fill = _pad_fill(bucket - n, x.shape[1:], x.dtype, fill_mask_true)
        # jsan: disable=alloc-in-hot-loop -- legacy data plane only: the
        # arena plane always dispatches full-bucket views (n == bucket)
        return np.concatenate([x, fill])

    return jax.tree.map(pad, batch)


def scatter_results(actions: Any, n: int) -> "list[Any]":
    """Split a batched action pytree back into ``n`` per-request pytrees
    in submission order, dropping the padding tail."""
    import jax
    return [jax.tree.map(lambda x: np.asarray(x)[i], actions)
            for i in range(n)]


@dataclasses.dataclass
class ServeResult:
    """What a request's future resolves to."""
    action: Any            # per-request action pytree (numpy)
    latency_s: float       # submit -> result, queue wait included
    req_id: int = 0        # request-causality id (ISSUE 20); 0 = unassigned


class DeadlineSheddedError(RuntimeError):
    """Typed rejection a shed request's future resolves with.

    Shedding is NEVER a silent drop: the future completes exceptionally
    with this error, carrying why (``reason``: ``"admission"`` — the
    predicted wait at submit already exceeded the deadline — or
    ``"expired"`` — the deadline passed while queued) and the numbers
    behind the verdict, so a client can retry elsewhere, relax its
    deadline, or back off — the load-shedding contract from the lost-
    computation accounting school: reject loudly at the door rather
    than time out quietly inside."""

    def __init__(self, reason: str, deadline_s: float, waited_s: float,
                 predicted_wait_s: "float | None" = None, req_id: int = 0):
        self.reason = reason
        self.deadline_s = float(deadline_s)
        self.waited_s = float(waited_s)
        self.predicted_wait_s = predicted_wait_s
        self.req_id = int(req_id)   # causality id, echoed on shed replies
        pred = (f", predicted wait {predicted_wait_s * 1e3:.1f}ms"
                if predicted_wait_s is not None else "")
        super().__init__(
            f"request shed ({reason}): deadline {deadline_s * 1e3:.1f}ms"
            f", waited {waited_s * 1e3:.1f}ms{pred}")


class ServerClosedError(RuntimeError):
    """Typed refusal for submits against a stopped or closed server.

    Raised by :meth:`PolicyServer.submit` while a :meth:`PolicyServer.stop`
    drain is in flight and forever after :meth:`PolicyServer.close` — the
    drain half of the no-silent-drop contract: a client racing a shutdown
    gets a typed, catchable refusal at the door instead of a future that
    no dispatcher will ever resolve. Distinguishable from
    :class:`DeadlineSheddedError` (overload, retry later with backoff)
    and from a bare ``RuntimeError`` (a bug): closed means *this server
    is going away — re-resolve and connect elsewhere*."""


class Ewma:
    """Streaming exponentially-weighted mean — the arrival-rate /
    service-time estimator behind adaptive batching. O(1) memory, no
    sample window to size; ``alpha`` is the forgetting factor (higher =
    faster tracking, noisier). ``value`` is ``None`` until the first
    observation — callers must not act on an unlearned estimate."""

    def __init__(self, alpha: float = 0.2):
        if not (0.0 < alpha <= 1.0):
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = float(alpha)
        self.value: "float | None" = None
        self.count = 0

    def update(self, x: float) -> float:
        x = float(x)
        # jsan: disable=shared-state-unlocked -- every Ewma instance is written under exactly one lock (arrival gap: the producers' ring/queue lock; service time: the dispatchers' server lock); the per-class model cannot split instances
        self.count += 1
        # jsan: disable=shared-state-unlocked -- same per-instance single-lock discipline as above
        self.value = (x if self.value is None
                      else self.alpha * x + (1 - self.alpha) * self.value)
        return self.value

    def reset(self) -> None:
        """Forget the learned estimate, returning to the cold state
        (``value is None``). Used when the world the estimate described
        is gone — e.g. a ``set_active`` weight-swap re-warm invalidates
        the learned per-dispatch service time, and acting on the stale
        value would mis-shed / mis-advertise Retry-After."""
        self.value = None
        self.count = 0


@dataclasses.dataclass
class _Pending:
    obs: Any
    mask: Any
    stall: int
    t_submit: float
    future: Future
    deadline_s: "float | None" = None   # relative to t_submit; None = no SLO
    req_id: int = 0                     # request-causality id (ISSUE 20)


class _SlotRef:
    """Read-only view of one pending arena slot for estimator scans
    (duck-typed like :class:`_Pending` where ``_effective_wait`` needs
    it: ``t_submit`` and ``deadline_s``)."""
    __slots__ = ("t_submit", "deadline_s")

    def __init__(self, t_submit: float, deadline_s: "float | None"):
        self.t_submit = t_submit
        self.deadline_s = deadline_s


class _ArenaBlock:
    """One bucket-sized slab of the request ring: per-leaf preallocated
    host arrays (leading axis = ``capacity`` slots) plus parallel
    per-slot metadata lists. Slots are claimed in order (``claimed`` is
    the reservation high-water mark); ``published[i]`` flips True — a
    GIL-atomic list store, no lock — only after slot ``i``'s rows and
    metadata are fully written, so a consumer never reads a torn row."""

    __slots__ = ("obs", "mask", "stall", "req", "futures", "t_submit",
                 "deadline", "published", "dead", "claimed", "n_dead",
                 "n_deadlined")

    def __init__(self, obs_leaves, mask_leaves, capacity: int):
        self.obs = [np.zeros((capacity,) + l.shape, l.dtype)
                    for l in obs_leaves]
        self.mask = [np.zeros((capacity,) + l.shape, l.dtype)
                     for l in mask_leaves]
        self.stall = np.zeros(capacity, np.int32)
        # request-causality sidecar lane (ISSUE 20): the 64-bit request
        # id rides the slab next to the row it describes, so dispatch/
        # scatter/flight-log all read it as one more preallocated
        # column — zero per-request allocations, like the stall lane
        self.req = np.zeros(capacity, np.int64)
        self.futures: "list[Future | None]" = [None] * capacity
        self.t_submit = [0.0] * capacity
        self.deadline: "list[float | None]" = [None] * capacity
        self.published = [False] * capacity
        self.dead = [False] * capacity
        self.claimed = 0
        self.n_dead = 0
        self.n_deadlined = 0

    def reset(self) -> None:
        """Return the block to the empty state for recycling. Slab
        contents are NOT zeroed — the dispatch path neutralizes exactly
        the tail rows it pads with, so stale rows are never read."""
        for i in range(self.claimed):
            self.futures[i] = None
            self.deadline[i] = None
            self.published[i] = False
            self.dead[i] = False
        self.claimed = 0
        self.n_dead = 0
        self.n_deadlined = 0


class _ArenaRing:
    """Fixed-capacity MPSC ring of :class:`_ArenaBlock` slabs.

    Producers reserve a slot under ``lock`` — an O(1) critical section
    (sequence bump; on block rollover, one deque rotation) — then write
    the row and publish OUTSIDE the lock. The consumer takes whole
    blocks (FIFO: sealed blocks first, else it force-seals the current
    one) and recycles them after scatter; a full ring back-pressures
    producers on ``cond`` until a block frees (the bounded-memory
    contract — the legacy deque grew without bound)."""

    def __init__(self, obs_leaves, mask_leaves, bucket: int,
                 n_blocks: int, alloc_counter=None):
        self.bucket = int(bucket)
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self._obs_leaves = obs_leaves
        self._mask_leaves = mask_leaves
        self._alloc_counter = alloc_counter
        self.n_blocks = 0
        self.depth = 0              # live (not shed) slots not yet taken
        self.sealed: "collections.deque[_ArenaBlock]" = collections.deque()
        self.free: "collections.deque[_ArenaBlock]" = collections.deque()
        self.cur = self._new_block()
        for _ in range(max(2, n_blocks) - 1):
            self.free.append(self._new_block())

    def _new_block(self) -> _ArenaBlock:
        blk = _ArenaBlock(self._obs_leaves, self._mask_leaves, self.bucket)
        self.n_blocks += 1
        if self._alloc_counter is not None:
            # slabs + the stall and req-id lanes; metadata lists are
            # not ndarrays
            self._alloc_counter.inc(
                len(self._obs_leaves) + len(self._mask_leaves) + 2)
        return blk

    def grow(self, n_blocks: int) -> None:
        """Ensure at least ``n_blocks`` blocks exist (construction /
        ``start()`` time only — never on the steady-state path)."""
        with self.lock:
            while self.n_blocks < n_blocks:
                self.free.append(self._new_block())
            self.cond.notify_all()

    def blocks(self) -> "list[_ArenaBlock]":
        """Ring-resident blocks in FIFO order (caller holds ``lock``)."""
        return [*self.sealed, self.cur]

    def take_block(self) -> "_ArenaBlock | None":
        """Remove and return the oldest block with claimed slots (the
        current block is force-sealed when nothing older is waiting), or
        None when the ring is empty. Once taken, a block is invisible to
        producers and shed scans until :meth:`recycle`."""
        with self.lock:
            if self.sealed:
                blk = self.sealed.popleft()
            elif self.cur.claimed > 0 and self.free:
                blk = self.cur
                self.cur = self.free.popleft()
            else:
                return None
            self.depth -= blk.claimed - blk.n_dead
            return blk

    def recycle(self, blk: _ArenaBlock) -> None:
        blk.reset()
        with self.lock:
            self.free.append(blk)
            self.cond.notify_all()

    def head_t_submit(self) -> "float | None":
        """Submit time of the oldest live published slot (the static
        hold-wait anchor). Lock-free racy read: a concurrent take makes
        the anchor momentarily stale, which only shortens a hold."""
        for blk in (self.sealed[0] if self.sealed else self.cur,):
            for i in range(blk.claimed):
                if blk.published[i] and not blk.dead[i]:
                    return blk.t_submit[i]
        return None

    def pending_slots(self) -> "list[_SlotRef]":
        """Snapshot of live pending slots for estimator scans."""
        out: list[_SlotRef] = []
        with self.lock:
            for blk in self.blocks():
                for i in range(blk.claimed):
                    if blk.published[i] and not blk.dead[i]:
                        out.append(_SlotRef(blk.t_submit[i],
                                            blk.deadline[i]))
        return out


class _RingPending:
    """Duck-type of the legacy pending deque over the arena ring, so the
    shared estimator code (and tests that poke ``server._pending``) see
    one surface: ``len()``/truthiness is the live pending depth,
    iteration yields :class:`_SlotRef` snapshots."""

    def __init__(self, server: "PolicyServer"):
        self._server = server

    def __len__(self) -> int:
        ring = self._server._ring
        return ring.depth if ring is not None else 0

    def __bool__(self) -> bool:
        return len(self) > 0

    def __iter__(self):
        ring = self._server._ring
        return iter(ring.pending_slots() if ring is not None else ())


_DATA_PLANES = ("arena", "legacy")


class PolicyServer:
    """The continuous-batching request queue over one
    :class:`~.engine.InferenceEngine`.

    ``submit`` enqueues a request and returns a
    :class:`concurrent.futures.Future` resolving to :class:`ServeResult`;
    ``pump`` drains up to ``engine.max_bucket`` pending requests into
    one coalesced dispatch. Drive it either inline (submit-then-pump —
    deterministic batch composition; what ``serve --bench`` does so its
    measured dispatch sizes are exactly the request sizes) or via the
    background dispatcher thread (:meth:`start` / :meth:`stop`) for live
    continuous batching, where a dispatch grabs whatever is pending the
    moment the previous one finishes.

    **Data planes** (ISSUE 17). ``data_plane="arena"`` (default) is the
    zero-copy hot path: ``submit`` memcpys the request row straight into
    a preallocated slab slot (reserved under a tiny O(1) ring lock, the
    copy itself outside any lock), ``pump`` seals and dispatches slab
    views, and steady state allocates no host ndarrays per batch. Slabs
    are sized from ``example_obs``/``example_mask`` at construction when
    given, else lazily from the first submitted request (row shapes and
    dtypes are then FIXED: later submits must match, and float inputs
    are cast to the arena dtype instead of silently promoting the
    batch). ``data_plane="legacy"`` keeps the pre-arena
    stack/pad/scatter path — the measured "before" arm of
    ``serve.bench.run_host_path``.

    SLO surface (the ``registry`` gauges/counters, re-rendered by both
    the ``metrics.prom`` snapshot and the live scrape endpoint):
    ``serve_requests_total``, ``serve_dispatches_total``,
    ``serve_queue_depth``, ``serve_batch_occupancy`` (real rows /
    bucket, last dispatch), the ``serve_decision_latency_seconds``
    histogram (observed per request at scatter — the aggregatable
    latency surface; scrape-side ``histogram_quantile`` beats exporting
    pre-computed percentiles), ``serve_latency_sample_window`` (live
    reservoir size), ``serve_decision_latency_p50_ms`` / ``_p99_ms``
    and ``serve_decisions_per_s`` (+ ``_per_chip``) via
    :meth:`slo_snapshot`, and ``serve_arena_allocs_total`` (host
    ndarrays allocated by the arena — warmup/ring-growth only; a moving
    value in steady state is a regression and the ci.sh host-path stage
    gates on it). Since ISSUE 20 the percentile/throughput gauges are
    refreshed by a registry pre-scrape collector hook (scrapes are
    never stale), ``serve_queue_wait_seconds`` buckets the
    submit->dispatch wait separately from service time, and
    ``self.slo`` is an :class:`~..obs.slo.SLOEngine` evaluating
    availability / queue-latency / engine-health burn rates
    (``slo_burn_rate``, ``slo_error_budget_remaining``,
    ``slo_burn_alert`` bus events) on every collect.

    **Request causality** (ISSUE 20): every submit carries a 64-bit
    ``req_id`` (caller-supplied or minted here) that rides an int64
    sidecar lane of the arena slab — same zero-steady-state-allocation
    contract as the data lanes — and is stamped on the
    enqueue/shed/served instants, the latency exemplar reservoir, the
    flight log's ``req_id`` column, and the resolved
    :class:`ServeResult`.

    With a ``tracer`` attached (``serve --trace-spans``) the request
    lifecycle lands on the flight recorder: an ``enqueue`` instant per
    submit, then ``bucket_wait`` -> ``serve_batch`` (``arena_seal`` on
    the arena plane / ``stack`` on the legacy plane -> engine
    ``pad``/``dispatch`` -> ``scatter``) per pump.

    When the engine exposes ``add_rewarm_listener`` (the router does),
    the server registers a callback that RESETS the learned service-time
    Ewma on weight-swap re-warm: the estimate described the old fleet
    shape/weights, and stale values would mis-shed admissions and
    mis-advertise ``Retry-After``.
    """

    def __init__(self, engine, registry=None, latency_window: int = 8192,
                 clock=time.perf_counter, max_wait_s: float | None = None,
                 tracer=None, sample_seed: int = 0,
                 adaptive_wait: bool = False, data_plane: str = "arena",
                 example_obs: Any = None, example_mask: Any = None,
                 arena_blocks: "int | None" = None, flight_log=None,
                 bus=None):
        from ..obs import Registry
        from ..obs.slo import SLOEngine, SLOSpec, histogram_sli
        self.engine = engine
        # data-flywheel tap: a capture-mode engine returns
        # (actions, behavior log-prob, value) per dispatch; the server
        # unpacks the triple and, when a flight log is attached, appends
        # every SERVED row (shed rows never dispatch, so rows_logged ==
        # served is structural, not best-effort)
        self._capture = bool(getattr(engine, "capture", False))
        self._flight_log = flight_log
        if flight_log is not None and not self._capture:
            raise ValueError(
                "flight_log requires a capture-mode engine "
                "(capture=True): the log's behavior log-prob and value "
                "columns come out of the engine's compiled decision "
                "program, never a post-hoc recompute")
        self.registry = registry if registry is not None else Registry()
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.bus = bus
        # request-causality ids (ISSUE 20): 64 bits = [1 zero bit]
        # [7 rank][16 pid][40 seq] — collision-free across ranks and
        # processes without coordination, and the sign bit stays clear
        # so an id survives the int64 flight-log column round trip.
        # seq starts at 1: id 0 means "unassigned" (v1 wire frames).
        rank = int(getattr(bus, "rank", 0) or 0)
        self._req_salt = (((rank & 0x7F) << 56)
                          | ((os.getpid() & 0xFFFF) << 40))
        self._req_seq = itertools.count(1)
        if max_wait_s is not None and max_wait_s < 0:
            raise ValueError(f"max_wait_s must be >= 0, got {max_wait_s}")
        if data_plane not in _DATA_PLANES:
            raise ValueError(f"data_plane must be one of {_DATA_PLANES}, "
                             f"got {data_plane!r}")
        if arena_blocks is not None and arena_blocks < 2:
            raise ValueError(f"arena_blocks must be >= 2, "
                             f"got {arena_blocks}")
        self.max_wait_s = max_wait_s
        self.adaptive_wait = bool(adaptive_wait)
        self.data_plane = data_plane
        self._clock = clock
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._sleepers = 0          # consumers parked on _wake (under _lock)
        self._shed_lock = threading.Lock()   # serializes shed counting
        self._pending: Any = (collections.deque()
                              if data_plane == "legacy"
                              else _RingPending(self))
        self._ring: "_ArenaRing | None" = None
        self._min_blocks = (arena_blocks if arena_blocks is not None
                            else max(4, min(128, 1024
                                            // int(engine.max_bucket))))
        # lifetime-uniform reservoirs, not rings: a soak run's p99 must
        # describe the whole run, not its trailing window
        self._latencies = Reservoir(latency_window, seed=sample_seed)
        self._occupancies = Reservoir(latency_window, seed=sample_seed + 1)
        # exemplar lane: same capacity AND seed as _latencies, appended
        # in lockstep -> Algorithm R draws identical replacement
        # indices, so sample i's request id is _latency_req_ids[i] —
        # ids can't ride float gauges (the salt exceeds 2**53), so the
        # p99 exemplar surfaces through slo_snapshot()'s dict instead
        self._latency_req_ids = Reservoir(latency_window, seed=sample_seed)
        self._threads: list[threading.Thread] = []
        self._stopped = False
        self._closed = False
        self._served = 0
        self._t_first: float | None = None
        self._t_last: float | None = None
        # streaming estimators feeding adaptive batching + admission:
        # inter-arrival gap (how long a bucket slot takes to fill) and
        # per-dispatch service time (how long a queued dispatch costs)
        self._arrival_gap = Ewma(alpha=0.2)
        self._service_time = Ewma(alpha=0.2)
        self._t_prev_submit: "float | None" = None
        self._requests = self.registry.counter(
            "serve_requests_total", "scheduling requests submitted")
        self._shed = self.registry.counter(
            "serve_shed_total",
            "requests rejected with a typed deadline rejection "
            "(admission + in-queue expiry)")
        self._dispatches = self.registry.counter(
            "serve_dispatches_total", "coalesced batch dispatches")
        self._padded = self.registry.counter(
            "serve_padded_slots_total",
            "bucket slots filled with padding instead of requests")
        self._depth = self.registry.gauge(
            "serve_queue_depth", "requests waiting after the last drain")
        self._occupancy = self.registry.gauge(
            "serve_batch_occupancy",
            "real rows / bucket rows of the last dispatch")
        self._sample_window = self.registry.gauge(
            "serve_latency_sample_window",
            "latency samples currently held by the reservoir")
        self._latency_hist = self.registry.histogram(
            "serve_decision_latency_seconds",
            "submit->result decision latency (cumulative histogram; "
            "aggregatable across ranks/restarts, unlike percentile "
            "gauges)")
        self._queue_wait_hist = self.registry.histogram(
            "serve_queue_wait_seconds",
            "submit->dispatch queue wait (the shed-or-scale half of "
            "decision latency: service time is the other half, and "
            "only the split says which knob to turn)")
        self._dispatch_errors = self.registry.counter(
            "serve_dispatch_errors_total",
            "background pumps that raised after resolving their batch's "
            "futures exceptionally (the dispatcher survives and keeps "
            "serving)")
        self._arena_allocs = self.registry.counter(
            "serve_arena_allocs_total",
            "host ndarrays allocated by the arena data plane (slab "
            "construction and ring growth; steady state must stay flat)")
        if (example_obs is None) != (example_mask is None):
            raise ValueError("example_obs and example_mask must be given "
                             "together (the arena is sized from both)")
        if example_obs is not None and data_plane == "arena":
            self.ensure_arena(example_obs, example_mask)
        add_listener = getattr(engine, "add_rewarm_listener", None)
        if callable(add_listener):
            add_listener(self._on_engine_rewarm)
        # the hedge counter is the router's, shared through the common
        # registry (re-registration returns the same object); over a
        # plain engine it simply never moves
        self._hedges = self.registry.counter(
            "serve_retry_hedges_total",
            "dispatches retried on a sibling engine after a failure")
        # declarative SLOs (ISSUE 20): burn rates re-evaluated by the
        # registry's pre-scrape collector hook, never hand-refreshed.
        # Windows are soak-scale (seconds, not SRE-handbook hours)
        # because this process's serving lifetime IS the soak.
        self.slo = SLOEngine(self.registry, bus=bus)
        self.slo.watch(SLOSpec(
            "availability", objective=0.99,
            windows=((5.0, 2.0), (30.0, 1.0)), budget_window_s=30.0,
            description="fraction of admitted requests neither shed "
                        "nor failed"), self._availability_sli)
        self.slo.watch(SLOSpec(
            "queue-latency", objective=0.95,
            windows=((5.0, 2.0), (30.0, 1.0)), budget_window_s=30.0,
            description="fraction of requests dispatched within 250ms "
                        "of submit"),
            histogram_sli(self._queue_wait_hist, 0.25))
        # short windows + a rolling 3s budget: a hedge burst (a sick
        # engine) trips the alert within one collect and the budget
        # gauge visibly recovers seconds after the fault clears — the
        # chaos-soak CI gate pins exactly that cycle
        self.slo.watch(SLOSpec(
            "engine-health", objective=0.999,
            windows=((1.0, 1.0), (3.0, 1.0)), budget_window_s=3.0,
            description="fraction of dispatches served without a "
                        "hedge or failure"), self._engine_health_sli)
        # the percentile/throughput gauges ride the same hook, retiring
        # the manual slo_snapshot() refresh calls (a scrape between
        # refreshes used to read stale gauges)
        self.registry.add_collector(self._refresh_slo_gauges)

    # ---- estimator lifecycle -----------------------------------------

    def _on_engine_rewarm(self) -> None:
        """Engine/router weight-swap re-warm callback: the learned
        per-dispatch service time described the PREVIOUS fleet, so
        forget it (admission goes back to cold-admit until relearned,
        and the frontend's Retry-After falls back to its floor)."""
        with self._lock:
            self._service_time.reset()

    # ---- request-causality ids ---------------------------------------

    def mint_request_id(self) -> int:
        """Next request-causality id. Thread-safe without a lock:
        ``itertools.count.__next__`` is atomic under the GIL, and the
        rank/pid salt makes ids from different processes disjoint. The
        frontend calls this when a client didn't supply an
        ``X-Request-Id`` (or sent a v1 frame), so it knows the id it
        must echo on the response."""
        return self._req_salt | (next(self._req_seq) & 0xFFFFFFFFFF)

    # ---- SLI plumbing ------------------------------------------------

    def _availability_sli(self) -> "tuple[float, float]":
        """(bad, total) for the availability SLO: bad = typed sheds
        plus failed dispatches (a failed pump fails every row it
        carried; counting it once is the cheap conservative floor),
        total = requests admitted at the door."""
        return (self._shed.value + self._dispatch_errors.value,
                self._requests.value)

    def _engine_health_sli(self) -> "tuple[float, float]":
        """(bad, total) for the engine-health SLO: bad = retry hedges
        (each one is a dispatch an engine failed before the hedge
        rescued it) plus dispatches that failed outright, total =
        dispatches attempted."""
        return (self._hedges.value + self._dispatch_errors.value,
                self._dispatches.value + self._dispatch_errors.value)

    def _refresh_slo_gauges(self) -> None:
        """Pre-scrape collector hook: recompute the percentile and
        throughput gauges at render time — the replacement for the
        manual ``slo_snapshot()`` refresh calls the CLIs used to
        sprinkle before every write."""
        self.slo_snapshot()

    # ---- arena construction ------------------------------------------

    def _row_leaves(self, tree: Any) -> "list[np.ndarray]":
        import jax
        return [np.asarray(l) for l in jax.tree.leaves(tree)]

    def ensure_arena(self, example_obs: Any, example_mask: Any) -> None:
        """Build the slab ring from one example request row (no leading
        batch axis). Called from the constructor when examples are
        given, else lazily by the first :meth:`submit`; idempotent.
        Row shapes and dtypes are fixed from the example."""
        if self.data_plane != "arena" or self._ring is not None:
            return
        import jax
        with self._lock:
            if self._ring is not None:
                return
            obs_leaves = self._row_leaves(example_obs)
            mask_leaves = self._row_leaves(example_mask)
            self._obs_treedef = jax.tree.structure(example_obs)
            self._mask_treedef = jax.tree.structure(example_mask)
            self._obs_is_leaf = (self._obs_treedef.num_leaves == 1
                                 and isinstance(example_obs, np.ndarray))
            self._mask_is_leaf = (self._mask_treedef.num_leaves == 1
                                  and isinstance(example_mask, np.ndarray))
            self._obs_row_shapes = [l.shape for l in obs_leaves]
            self._mask_row_shapes = [l.shape for l in mask_leaves]
            # single-ndarray-leaf rows take a no-loop submit fast path
            self._fast_rows = self._obs_is_leaf and self._mask_is_leaf
            self._ring = _ArenaRing(
                obs_leaves, mask_leaves, int(self.engine.max_bucket),
                self._min_blocks, alloc_counter=self._arena_allocs)

    def arena_stats(self) -> dict:
        """Arena occupancy/allocation surface for benches and CI gates."""
        ring = self._ring
        return {
            "data_plane": self.data_plane,
            "blocks": ring.n_blocks if ring is not None else 0,
            "rows": (ring.n_blocks * ring.bucket
                     if ring is not None else 0),
            "slab_allocs": int(self._arena_allocs.value),
        }

    # ---- shed plumbing -----------------------------------------------

    def _reject(self, fut: Future, exc: DeadlineSheddedError,
                reason: str) -> None:
        """Resolve ``fut`` with a typed shed rejection and count it in
        ``serve_shed_total`` — counting gated on WINNING the future's
        state transition, so a request raced by two dispatchers' expiry
        scans (or abandoned via ``Future.cancel``) is counted at most
        once, and only when someone will actually observe the rejection.
        Conservation (submitted == resolved + shed) is structural, not
        best-effort. The counter bump takes its own tiny lock: rejects
        fire from producer threads (admission) and dispatcher threads
        (expiry) which no longer share a queue lock."""
        try:
            fut.set_exception(exc)
        except BaseException:   # cancelled, or already resolved elsewhere
            return
        with self._shed_lock:
            self._shed.inc()
        self.tracer.instant("shed", reason=reason, req_id=exc.req_id)

    # ---- submit ------------------------------------------------------

    def submit(self, obs: Any, mask: Any, stall: int = 0,
               deadline_s: "float | None" = None,
               req_id: "int | None" = None) -> Future:
        """Enqueue one scheduling request (host pytrees, NO leading batch
        axis). ``stall`` is the client's consecutive-zero-dt count for
        the stall gate (preemptive configs; 0 = gate disengaged).

        ``req_id`` is the request-causality key (ISSUE 20): minted here
        when the caller didn't bring one (``None``/0 — the frontend
        mints eagerly instead, so it can echo the id even on a shed).
        The id rides the arena sidecar lane through dispatch and
        scatter, is stamped on the enqueue/shed/served instants and the
        latency exemplars, lands in the flight log's ``req_id`` column,
        and comes back on the resolved :class:`ServeResult` — one key
        joining every observation of this request's life.

        ``deadline_s`` is the request's latency SLO, relative to submit.
        A deadlined request is subject to **load shedding**: if the
        predicted queue wait at submit time (queued dispatches ahead ×
        learned service time) already exceeds the deadline, or the
        deadline expires while queued, the returned future resolves
        exceptionally with :class:`DeadlineSheddedError` — typed, never
        a silent drop — and ``serve_shed_total`` counts it. Admission
        only rejects once the service-time estimator has observations
        (a cold server admits everything rather than guessing).

        On the arena plane this call performs the ONE host copy of the
        request's life: the row lands directly in the current slab slot
        (wire bytes -> arena when called from the frontend's
        ``np.frombuffer`` views). Rows that don't match the arena's
        fixed shapes raise ``ValueError`` here, at the door."""
        req_id = self.mint_request_id() if not req_id else int(req_id)
        if self.data_plane == "legacy":
            return self._submit_legacy(obs, mask, stall, deadline_s,
                                       req_id)
        return self._submit_arena(obs, mask, stall, deadline_s, req_id)

    def _submit_legacy(self, obs, mask, stall, deadline_s,
                       req_id) -> Future:
        now = self._clock()
        fut: Future = Future()
        req = _Pending(obs=obs, mask=mask, stall=int(stall),
                       t_submit=now, future=fut,
                       deadline_s=(None if deadline_s is None
                                   else float(deadline_s)),
                       req_id=req_id)
        with self._wake:
            if self._closed:
                raise ServerClosedError(
                    "PolicyServer is closed (drained for shutdown)")
            if self._stopped:
                raise ServerClosedError(
                    "PolicyServer is stopped (drain in flight)")
            self._requests.inc()
            if self._t_prev_submit is not None:
                self._arrival_gap.update(now - self._t_prev_submit)
            self._t_prev_submit = now
            svc = self._service_time.value
            if (req.deadline_s is not None and svc is not None):
                # dispatches ahead of this request if it joins the queue,
                # itself included — each costs ~one learned service time
                ahead = -(-(len(self._pending) + 1)
                          // self.engine.max_bucket)
                predicted = ahead * svc
                if predicted > req.deadline_s:
                    self._reject(fut, DeadlineSheddedError(
                        "admission", req.deadline_s, waited_s=0.0,
                        predicted_wait_s=predicted, req_id=req_id),
                        reason="admission")
                    return fut
            self._pending.append(req)
            self._wake.notify()
        self.tracer.instant("enqueue", stall=int(stall), req_id=req_id)
        return fut

    def _write_row(self, blk: _ArenaBlock, i: int, obs, mask,
                   stall: int) -> None:
        """The one memcpy: request row -> slab slot ``i``. Shape
        mismatches raise before any slab write (no torn rows)."""
        if self._obs_is_leaf and isinstance(obs, np.ndarray):
            obs_leaves = (obs,)
        else:
            import jax
            obs_leaves = jax.tree.leaves(obs)
        if self._mask_is_leaf and isinstance(mask, np.ndarray):
            mask_leaves = (mask,)
        else:
            import jax
            mask_leaves = jax.tree.leaves(mask)
        if len(obs_leaves) != len(blk.obs):
            raise ValueError(
                f"obs has {len(obs_leaves)} leaves, arena expects "
                f"{len(blk.obs)}")
        if len(mask_leaves) != len(blk.mask):
            raise ValueError(
                f"mask has {len(mask_leaves)} leaves, arena expects "
                f"{len(blk.mask)}")
        for j, leaf in enumerate(obs_leaves):
            if np.shape(leaf) != self._obs_row_shapes[j]:
                raise ValueError(
                    f"obs leaf {j} has shape {np.shape(leaf)}, arena row "
                    f"is {self._obs_row_shapes[j]}")
            blk.obs[j][i] = leaf
        for j, leaf in enumerate(mask_leaves):
            if np.shape(leaf) != self._mask_row_shapes[j]:
                raise ValueError(
                    f"mask leaf {j} has shape {np.shape(leaf)}, arena row "
                    f"is {self._mask_row_shapes[j]}")
            blk.mask[j][i] = leaf
        blk.stall[i] = stall

    def _submit_arena(self, obs, mask, stall, deadline_s,
                      req_id) -> Future:
        if self._ring is None:
            self.ensure_arena(obs, mask)     # lazy sizing, first request
        ring = self._ring
        now = self._clock()
        fut: Future = Future()
        deadline_s = None if deadline_s is None else float(deadline_s)
        shed_exc = None
        with ring.lock:
            if self._closed:
                raise ServerClosedError(
                    "PolicyServer is closed (drained for shutdown)")
            if self._stopped:
                raise ServerClosedError(
                    "PolicyServer is stopped (drain in flight)")
            self._requests.inc()
            if self._t_prev_submit is not None:
                self._arrival_gap.update(now - self._t_prev_submit)
            self._t_prev_submit = now
            svc = self._service_time.value
            if deadline_s is not None and svc is not None:
                # dispatches ahead of this request if it joins the queue,
                # itself included — each costs ~one learned service time
                ahead = -(-(ring.depth + 1) // self.engine.max_bucket)
                predicted = ahead * svc
                if predicted > deadline_s:
                    shed_exc = DeadlineSheddedError(
                        "admission", deadline_s, waited_s=0.0,
                        predicted_wait_s=predicted, req_id=req_id)
            if shed_exc is None:
                # common case inlined: current block has a free slot
                blk = ring.cur
                i = blk.claimed
                if i < ring.bucket:
                    blk.claimed = i + 1
                    ring.depth += 1
                else:
                    blk, i = self._reserve_slot_locked(ring)
        if shed_exc is not None:
            self._reject(fut, shed_exc, reason="admission")
            return fut
        # outside every lock: the row copy and the publish store
        try:
            # single-leaf fast path inlined: this is the per-request hot
            # path the host bench measures, and the generic tree walk in
            # _write_row costs more than the memcpy itself
            if (self._fast_rows and type(obs) is np.ndarray
                    and type(mask) is np.ndarray
                    and obs.shape == self._obs_row_shapes[0]
                    and mask.shape == self._mask_row_shapes[0]):
                blk.obs[0][i] = obs
                blk.mask[0][i] = mask
                blk.stall[i] = stall
            else:
                self._write_row(blk, i, obs, mask, int(stall))
        except BaseException:
            # the slot is already reserved — kill it in place (typed
            # error to the CALLER; there is no future holder to strand)
            with ring.lock:
                blk.dead[i] = True
                blk.n_dead += 1
                ring.depth -= 1
            blk.published[i] = True
            raise
        blk.req[i] = req_id          # sidecar lane: one int64 store
        blk.t_submit[i] = now
        blk.deadline[i] = deadline_s
        blk.futures[i] = fut
        if deadline_s is not None:
            blk.n_deadlined += 1
        blk.published[i] = True      # GIL-atomic store: slot now visible
        if self._sleepers:           # wake a parked consumer (rare in
            with self._wake:         # steady state: dispatchers stay hot)
                self._wake.notify_all()
        if self.tracer is not NULL_TRACER:
            self.tracer.instant("enqueue", stall=int(stall),
                                req_id=req_id)
        return fut

    def _reserve_slot_locked(self, ring: _ArenaRing):
        """Claim the next slot (caller holds ``ring.lock``). Rolls the
        current block over when full; a completely full ring waits for
        the consumer to recycle a block (bounded slices so a close()
        during the wait raises instead of hanging)."""
        while True:
            blk = ring.cur
            i = blk.claimed
            if i < ring.bucket:
                blk.claimed = i + 1
                ring.depth += 1
                return blk, i
            if ring.free:               # rollover: seal, swap in a free
                ring.sealed.append(blk)
                ring.cur = ring.free.popleft()
                continue
            # ring full: producer backpressure until a block recycles
            ring.cond.wait(timeout=0.05)
            if self._closed or self._stopped:
                raise ServerClosedError(
                    "PolicyServer is closing (arena ring drained for "
                    "shutdown)")

    # ---- expiry ------------------------------------------------------

    def _shed_expired(self, now: float) -> None:
        if self.data_plane == "legacy":
            self._shed_expired_legacy(now)
        else:
            self._shed_expired_arena(now)

    def _shed_expired_legacy(self, now: float) -> None:
        """Drop queued requests whose deadline already passed (called
        under ``self._lock``); their futures resolve with the typed
        rejection. Head-first scan is NOT enough: deadlines are
        per-request, so a generous-deadline head can hide an expired
        tail."""
        if not any(r.deadline_s is not None for r in self._pending):
            return
        keep: collections.deque[_Pending] = collections.deque()
        for r in self._pending:
            if (r.deadline_s is not None
                    and now - r.t_submit > r.deadline_s):
                self._reject(r.future, DeadlineSheddedError(
                    "expired", r.deadline_s,
                    waited_s=now - r.t_submit,
                    req_id=r.req_id), reason="expired")
            else:
                keep.append(r)
        self._pending = keep

    def _shed_expired_arena(self, now: float) -> None:
        """Arena expiry: expired slots are marked dead IN PLACE (their
        slab rows become padding at dispatch) instead of being removed
        from a queue; the typed rejections fire outside the ring lock.
        Full scan, same reason as the legacy plane: per-request
        deadlines mean a generous head can hide an expired tail."""
        ring = self._ring
        if ring is None:
            return
        expired: "list[tuple[Future, float, float, int]]" = []
        with ring.lock:
            blocks = ring.blocks()
            if not any(b.n_deadlined for b in blocks):
                return
            for blk in blocks:
                for i in range(blk.claimed):
                    if not blk.published[i] or blk.dead[i]:
                        continue
                    d = blk.deadline[i]
                    if d is None:
                        continue
                    waited = now - blk.t_submit[i]
                    if waited > d:
                        blk.dead[i] = True
                        blk.n_dead += 1
                        blk.n_deadlined -= 1
                        ring.depth -= 1
                        expired.append((blk.futures[i], d, waited,
                                        int(blk.req[i])))
                        blk.futures[i] = None
        for fut, d, waited, rid in expired:
            self._reject(fut, DeadlineSheddedError(
                "expired", d, waited_s=waited, req_id=rid),
                reason="expired")

    # ---- adaptive hold -----------------------------------------------

    def _effective_wait(self) -> "float | None":
        """The partial-bucket hold time for THIS pump (called under
        ``self._lock``, queue non-empty). Static mode returns the
        constructor knob. Adaptive mode learns it: hold for the
        estimated time to FILL the bucket at the observed arrival rate
        (waiting longer than that buys nothing), clipped to the
        head-of-line deadline slack (dispatch a partial bucket rather
        than shed the head), and capped by ``max_wait_s`` when given."""
        if not self.adaptive_wait:
            return self.max_wait_s
        waits = []
        if self.max_wait_s is not None:
            waits.append(self.max_wait_s)
        gap = self._arrival_gap.value
        if gap is not None:
            free = max(self.engine.max_bucket - len(self._pending), 0)
            waits.append(gap * free)
        now = self._clock()
        slacks = [r.t_submit + r.deadline_s - now
                  for r in self._pending if r.deadline_s is not None]
        if slacks:
            # keep one learned service time in hand for the dispatch
            svc = self._service_time.value or 0.0
            waits.append(max(min(slacks) - svc, 0.0))
        return min(waits) if waits else None

    # ---- pump --------------------------------------------------------

    def pump(self, max_wait_s: float | None = None) -> int:
        """Drain one coalesced batch: take up to ``engine.max_bucket``
        pending requests (FIFO), dispatch, scatter results to their
        futures. Returns the number of requests served (0 = queue was
        empty). On the arena plane the "batch" is one slab: tail slots
        are neutralized in place and the engine sees a contiguous
        full-bucket view — no stacking, no padding copies.

        ``max_wait_s`` (default: the constructor's policy; ``None`` = no
        wait) is the batching deadline: a PARTIAL bucket holds off
        dispatching until either the bucket fills or the batching
        deadline passes — trading a bounded latency floor for occupancy
        (the classic continuous-batching knob). ``0`` keeps the
        dispatch-whatever-is-pending behavior while still being
        explicit about it. With ``adaptive_wait`` the hold time is
        LEARNED per pump (:meth:`_effective_wait`): the estimated
        bucket fill time at the observed arrival rate, cut short when
        the head-of-line deadline slack runs out — the deadline-aware
        partial-bucket dispatch. Expired deadlines shed before and
        after the hold (:meth:`_shed_expired`). A :meth:`stop` drain
        cuts the wait short so shutdown never hangs on a sparse
        queue."""
        if self.data_plane == "legacy":
            return self._pump_legacy(max_wait_s)
        return self._pump_arena(max_wait_s)

    def _hold_for_bucket(self, pending_depth, max_wait_s: "float | None",
                         head_t_submit) -> None:
        """Shared partial-bucket hold loop (caller holds ``self._lock``).
        ``pending_depth``/``head_t_submit`` are callables so both planes
        reuse the anchor/deadline policy. The sleep re-checks depth
        AFTER advertising itself in ``_sleepers`` — with arena producers
        publishing outside this lock, that ordering (producer: publish
        then read ``_sleepers``; consumer: increment then re-check) is
        what makes the wakeup race-free without a per-submit lock."""
        wait = (max_wait_s if max_wait_s is not None
                else self._effective_wait())
        if wait is None:
            return
        # static mode anchors at the head's submit time (total head wait
        # bounded by the knob); adaptive mode anchors NOW — its estimate
        # already folds in the head's remaining slack
        if max_wait_s is None and self.adaptive_wait:
            anchor = self._clock()
        else:
            head = head_t_submit()
            anchor = head if head is not None else self._clock()
        deadline = anchor + wait
        with self.tracer.span("bucket_wait"):
            while (pending_depth() < self.engine.max_bucket
                   and not self._stopped):
                remaining = deadline - self._clock()
                if remaining <= 0:
                    break
                self._sleepers += 1
                try:
                    if (pending_depth() < self.engine.max_bucket
                            and not self._stopped):
                        self._wake.wait(timeout=remaining)
                finally:
                    self._sleepers -= 1

    def _split_capture(self, out):
        """Unpack one engine dispatch output: a capture engine returns
        the ``(actions, behavior log-prob, value)`` triple, a plain
        engine just actions (then log-prob/value are ``None``)."""
        if self._capture:
            actions, blp, bval = out
            return actions, blp, bval
        return out, None, None

    def _log_rows(self, obs, mask, stall, actions, blp, bval, n: int,
                  lats: "list[float]", deads, req_ids) -> None:
        """Append this dispatch's ``n`` SERVED rows to the flight log.
        Deadline outcome per row: 0 = no deadline, 1 = met, 2 = served
        late (resolved past its SLO but not shed). Shed rows never reach
        a dispatch, so the log's row count equals ``serve_dispatches``'
        served total exactly — the flywheel's conservation contract."""
        import jax
        # per-call outcome buffer, NOT a shared scratch: N dispatcher
        # threads reach here concurrently outside self._lock, and the
        # flight log only copies rows under ITS lock — a shared slab
        # would let one thread's fill interleave with another's copy
        # jsan: disable=alloc-in-hot-loop -- n int8s per dispatch (noise next to this call's obs/mask slab memcpys); a shared scratch raced across dispatcher threads
        outcome = np.zeros(n, np.int8)
        for i, d in enumerate(deads):
            if d is not None:
                outcome[i] = 1 if lats[i] <= d else 2
        self._flight_log.append_batch(
            jax.tree.map(lambda l: np.asarray(l)[:n], obs),
            jax.tree.map(lambda l: np.asarray(l)[:n], mask),
            jax.tree.map(lambda l: np.asarray(l)[:n], actions),
            np.asarray(blp)[:n], np.asarray(bval)[:n],
            np.asarray(stall)[:n], outcome,
            req_id=np.asarray(req_ids, np.int64)[:n])

    def _pump_legacy(self, max_wait_s: "float | None") -> int:
        with self._lock:
            self._shed_expired(self._clock())
            if self._pending:
                self._hold_for_bucket(
                    lambda: len(self._pending), max_wait_s,
                    lambda: (self._pending[0].t_submit
                             if self._pending else None))
                self._shed_expired(self._clock())
            batch = [self._pending.popleft()
                     for _ in range(min(len(self._pending),
                                        self.engine.max_bucket))]
            self._depth.set(len(self._pending))
        if not batch:
            return 0
        n = len(batch)
        rids = [r.req_id for r in batch]
        t_disp = self._clock()
        try:
            with self.tracer.span("serve_batch", n=n):
                with self.tracer.span("stack"):
                    obs = stack_requests([r.obs for r in batch])
                    mask = stack_requests([r.mask for r in batch])
                    stall = np.asarray([r.stall for r in batch], np.int32)
                out, bucket = self.engine.decide(obs, mask, stall)
                actions, blp, bval = self._split_capture(out)
                now = self._clock()
                with self.tracer.span("scatter"):
                    per_req = scatter_results(actions, n)
            lats = [now - r.t_submit for r in batch]
            if self._flight_log is not None:
                # inside the try: the dispatcher loop's no-silent-drop
                # invariant is that a raising pump has already resolved
                # its batch's futures — a failing flight-log append must
                # fail the batch loudly, never strand it
                self._log_rows(obs, mask, stall, actions, blp, bval, n,
                               lats, [r.deadline_s for r in batch],
                               rids)
        except BaseException as e:
            for r in batch:
                if not r.future.cancelled():
                    r.future.set_exception(e)
            if self.tracer is not NULL_TRACER:
                self.tracer.instant("dispatch_failed", req_ids=rids,
                                    error=type(e).__name__)
            raise
        t_subs = [r.t_submit for r in batch]
        self._account_dispatch(now, t_disp, n, bucket, lats, t_subs, rids)
        for r, a, lat in zip(batch, per_req, lats):
            r.future.set_result(ServeResult(action=a, latency_s=lat,
                                            req_id=r.req_id))
        if self.tracer is not NULL_TRACER:
            self.tracer.instant(
                "served", bucket=bucket, req_ids=rids,
                wait_ms=[round((t_disp - t) * 1e3, 3) for t in t_subs],
                lat_ms=[round(l * 1e3, 3) for l in lats])
        return n

    def _seal_block(self, blk: _ArenaBlock):
        """Turn a taken block into a dispatchable contiguous prefix:
        wait out in-flight row copies (bounded by one memcpy — the
        producer published its reservation before we took the block),
        compact live rows over dead ones (shed slots become padding),
        and neutralize the pad tail IN PLACE (zero obs, all-legal bool
        masks, zero stall, zero req id) — pure slice assignment, no
        allocation. Returns ``(n_live, bucket, futures, t_submits,
        deadlines, req_ids)`` — ``req_ids`` is a view into the slab's
        sidecar lane, valid until the block recycles."""
        spin_deadline = time.monotonic() + 5.0
        while not all(blk.published[:blk.claimed]):
            if time.monotonic() > spin_deadline:
                # a producer died mid-copy (interpreter teardown); its
                # slot has no future holder — treat it as dead padding
                for i in range(blk.claimed):
                    if not blk.published[i]:
                        blk.published[i] = True
                        blk.dead[i] = True
                        blk.n_dead += 1
                break
            time.sleep(50e-6)
        live = [i for i in range(blk.claimed) if not blk.dead[i]]
        n_live = len(live)
        if n_live == 0:
            return 0, 0, [], [], [], []
        if n_live != blk.claimed:
            # compact: shift live rows down over dead ones (dst <= src,
            # so in-place row moves are safe); rare — shed path only
            for dst, src in enumerate(live):
                if dst == src:
                    continue
                for leaf in blk.obs:
                    leaf[dst] = leaf[src]
                for leaf in blk.mask:
                    leaf[dst] = leaf[src]
                blk.stall[dst] = blk.stall[src]
                blk.req[dst] = blk.req[src]
                blk.futures[dst] = blk.futures[src]
                blk.t_submit[dst] = blk.t_submit[src]
                blk.deadline[dst] = blk.deadline[src]
        bucket = next_bucket(n_live, self.engine.max_bucket)
        if n_live < bucket:
            for leaf in blk.obs:
                leaf[n_live:bucket] = 0
            for leaf in blk.mask:
                leaf[n_live:bucket] = (True if leaf.dtype == np.bool_
                                       else 0)
            blk.stall[n_live:bucket] = 0
            blk.req[n_live:bucket] = 0
        return (n_live, bucket, blk.futures[:n_live],
                blk.t_submit[:n_live], blk.deadline[:n_live],
                blk.req[:n_live])

    def _arena_views(self, blk: _ArenaBlock, bucket: int):
        """Contiguous ``[:bucket]`` views of the slab, re-assembled into
        the caller's pytree structure (views, never copies)."""
        if self._obs_is_leaf:
            obs = blk.obs[0][:bucket]
        else:
            import jax
            obs = jax.tree.unflatten(
                self._obs_treedef, [l[:bucket] for l in blk.obs])
        if self._mask_is_leaf:
            mask = blk.mask[0][:bucket]
        else:
            import jax
            mask = jax.tree.unflatten(
                self._mask_treedef, [l[:bucket] for l in blk.mask])
        return obs, mask, blk.stall[:bucket]

    def _scatter_arena(self, blk: _ArenaBlock, actions: Any, n_live: int):
        """Per-request action views into the single device-fetched
        actions buffer. If the engine echoed its INPUT back (host-stub
        engines do), the buffer aliases the slab we are about to
        recycle — detected with a bounds-only overlap check and copied
        once, so resolved results can never be corrupted by slab
        reuse."""
        import jax
        leaves = [np.asarray(l) for l in jax.tree.leaves(actions)]
        slabs = blk.obs + blk.mask + [blk.stall]
        safe = []
        for leaf in leaves:
            if any(np.may_share_memory(leaf, s) for s in slabs):
                leaf = leaf.copy()
            safe.append(leaf)
        if len(safe) == 1 and isinstance(actions, np.ndarray):
            buf = safe[0]
            return [buf[i] for i in range(n_live)]
        treedef = jax.tree.structure(actions)
        return [jax.tree.unflatten(treedef, [l[i] for l in safe])
                for i in range(n_live)]

    def _pump_arena(self, max_wait_s: "float | None") -> int:
        ring = self._ring
        if ring is None:
            return 0
        with self._lock:
            self._shed_expired(self._clock())
            if ring.depth > 0:
                self._hold_for_bucket(lambda: ring.depth, max_wait_s,
                                      ring.head_t_submit)
                self._shed_expired(self._clock())
            blk = ring.take_block()
            self._depth.set(ring.depth)
        if blk is None:
            return 0
        t_disp = self._clock()
        try:
            n_live, bucket, futs, t_subs, deads, rids = \
                self._seal_block(blk)
        except BaseException:
            ring.recycle(blk)
            raise
        if n_live == 0:
            ring.recycle(blk)
            return 0
        try:
            if self.tracer is NULL_TRACER:   # span-free hot path
                obs, mask, stall = self._arena_views(blk, bucket)
                out, bucket = self.engine.decide(obs, mask, stall)
                actions, blp, bval = self._split_capture(out)
                now = self._clock()
                per_req = self._scatter_arena(blk, actions, n_live)
            else:
                with self.tracer.span("serve_batch", n=n_live):
                    with self.tracer.span("arena_seal"):
                        obs, mask, stall = self._arena_views(blk, bucket)
                    out, bucket = self.engine.decide(obs, mask, stall)
                    actions, blp, bval = self._split_capture(out)
                    now = self._clock()
                    with self.tracer.span("scatter"):
                        per_req = self._scatter_arena(blk, actions, n_live)
            lats = [now - t for t in t_subs]
            if self._flight_log is not None:
                # tap point: the slab views stay valid until ring.recycle
                # below (donation consumed the DEVICE copies, not these
                # host slabs), and the flight log copies rows into its
                # own recycled shard buffer before returning. Inside the
                # try: a failing append must resolve this batch's
                # futures with the exception (the dispatcher loop's
                # no-silent-drop invariant), never strand them
                self._log_rows(obs, mask, stall, actions, blp, bval,
                               n_live, lats, deads, rids)
        except BaseException as e:
            for fut in futs:
                if not fut.cancelled():
                    fut.set_exception(e)
            if self.tracer is not NULL_TRACER:
                self.tracer.instant("dispatch_failed",
                                    req_ids=[int(r) for r in rids],
                                    error=type(e).__name__)
            ring.recycle(blk)
            raise
        self._account_dispatch(now, t_disp, n_live, bucket, lats,
                               t_subs, rids)
        for fut, a, lat, rid in zip(futs, per_req, lats, rids):
            try:
                fut.set_result(ServeResult(action=a, latency_s=lat,
                                           req_id=int(rid)))
            except BaseException:   # cancelled while in flight
                pass
        if self.tracer is not NULL_TRACER:
            # one instant per DISPATCH, not per request: the causality
            # record for n_live requests costs one bus write
            self.tracer.instant(
                "served", bucket=bucket,
                req_ids=[int(r) for r in rids],
                wait_ms=[round((t_disp - t) * 1e3, 3) for t in t_subs],
                lat_ms=[round(l * 1e3, 3) for l in lats])
        ring.recycle(blk)
        return n_live

    def _account_dispatch(self, now: float, t_disp: float, n: int,
                          bucket: int, lats: "list[float]",
                          t_subs, req_ids) -> None:
        """Per-dispatch accounting under the consumer lock: concurrent
        dispatcher threads (start(dispatchers=N) over a router) share
        every reservoir, counter, and estimator below. Producers never
        take this lock — that is the lock-light contract."""
        with self._lock:
            self._service_time.update(now - t_disp)
            self._dispatches.inc()
            self._padded.inc(bucket - n)
            self._occupancy.set(n / bucket)
            self._occupancies.append(n / bucket)
            if self._t_first is None:
                self._t_first = min(t_subs)
            self._t_last = now if self._t_last is None else max(
                self._t_last, now)
            self._served += n
            for lat, t_sub, rid in zip(lats, t_subs, req_ids):
                self._latencies.append(lat)
                self._latency_req_ids.append(int(rid))   # exemplar lane
                self._latency_hist.observe(lat)
                self._queue_wait_hist.observe(max(t_disp - t_sub, 0.0))
            self._sample_window.set(len(self._latencies))

    # ---- live dispatcher thread --------------------------------------

    def _has_work(self) -> bool:
        if self.data_plane == "legacy":
            return bool(self._pending)
        ring = self._ring
        return ring is not None and ring.depth > 0

    def start(self, dispatchers: int = 1) -> None:
        """Start the background dispatchers: pump whenever requests are
        pending (continuous batching — each dispatch coalesces whatever
        arrived while the previous one ran). ``dispatchers > 1`` keeps
        that many pumps in flight at once so a multi-engine router can
        run its engines concurrently; over a single engine extra
        dispatchers only shrink batch occupancy (and the router is the
        layer that owns device-level thread safety — see
        ``serve.router.EngineRouter``)."""
        if self._threads:
            raise RuntimeError("dispatcher already running")
        if self._closed:
            raise ServerClosedError("PolicyServer is closed")
        if dispatchers < 1:
            raise ValueError(f"dispatchers must be >= 1, got {dispatchers}")
        # every in-flight dispatcher can hold one block while another is
        # current and one stays free — guarantee the ring never wedges
        self._min_blocks = max(self._min_blocks, dispatchers + 2)
        if self._ring is not None:
            self._ring.grow(self._min_blocks)
        self._stopped = False

        def loop():
            while True:
                with self._wake:
                    while not self._has_work() and not self._stopped:
                        self._sleepers += 1
                        try:
                            if not self._has_work() and not self._stopped:
                                self._wake.wait()
                        finally:
                            self._sleepers -= 1
                    if self._stopped and not self._has_work():
                        return
                try:
                    self.pump()
                except Exception:
                    # the pump already resolved its batch's futures with
                    # the exception (no silent drop); a dead dispatcher
                    # would strand every LATER request as a hung future,
                    # so survive the failed dispatch and keep draining
                    self._dispatch_errors.inc()

        for i in range(dispatchers):
            t = threading.Thread(target=loop,
                                 name=f"serve-dispatcher-{i}",
                                 daemon=True)
            self._threads.append(t)
            t.start()

    def stop(self) -> None:
        """Stop the dispatchers after draining the queue. Submits are
        refused while the drain is in flight; once stopped the server
        is back in inline mode (submit-then-:meth:`pump`) and
        :meth:`start` may be called again."""
        with self._wake:
            self._stopped = True
            self._wake.notify_all()
        for t in self._threads:
            t.join(timeout=30)
        self._threads = []
        with self._wake:
            # a close() drain is terminal; a stop() drain returns the
            # server to inline mode
            self._stopped = self._closed

    def close(self) -> None:
        """Permanent :meth:`stop`: drain the queue, stop the dispatchers,
        then refuse every later :meth:`submit` (and :meth:`start`) with
        :class:`ServerClosedError` forever. The terminal half of the
        frontend's graceful-drain contract — after ``close`` returns,
        every future ever handed out has resolved (result, shed, or
        dispatch error) and no future will ever be created that can't.
        Idempotent."""
        with self._wake:
            self._closed = True
        self.stop()
        # inline-mode close: no dispatcher drained the queue, so flush it
        # here — every already-accepted future must resolve (each pump
        # consumes its batch even when the dispatch raises, so this
        # terminates)
        while True:
            try:
                if not self.pump():
                    break
            except Exception:
                self._dispatch_errors.inc()
        # one final refresh, then detach from the scrape surface: a
        # scrape after close reads the last computed SLO values instead
        # of running collectors against a dead server
        self.registry.collect()
        self.registry.remove_collector(self._refresh_slo_gauges)
        self.slo.close()

    @property
    def closed(self) -> bool:
        return self._closed

    def queue_depth(self) -> int:
        """Requests currently queued (the frontend's backpressure
        signal — sampled, so momentarily stale values are fine)."""
        if self.data_plane == "legacy":
            with self._lock:
                return len(self._pending)
        ring = self._ring
        return ring.depth if ring is not None else 0

    def service_time_s(self) -> "float | None":
        """The learned per-dispatch service time (Ewma), ``None`` until
        the first dispatch — what the frontend derives ``Retry-After``
        from for shed responses."""
        with self._lock:
            return self._service_time.value

    # ---- SLO surface -------------------------------------------------

    def slo_snapshot(self) -> dict:
        """Compute and publish the SLO numbers: p50/p99 decision latency
        (ms), decisions/s and decisions/s/chip over the serving span,
        mean batch occupancy. Also writes the latency/throughput gauges
        into the registry so a scrape observes them."""
        import jax
        lats = np.asarray(self._latencies, np.float64)
        span = ((self._t_last - self._t_first)
                if self._served and self._t_last is not None
                and self._t_first is not None else 0.0)
        n_chips = max(jax.local_device_count(), 1)
        dps = self._served / span if span > 0 else 0.0
        snap = {
            "requests": int(self._served),
            "dispatches": int(self._dispatches.value),
            "latency_p50_ms": (float(np.percentile(lats, 50)) * 1e3
                               if lats.size else None),
            "latency_p99_ms": (float(np.percentile(lats, 99)) * 1e3
                               if lats.size else None),
            "decisions_per_s": dps,
            "decisions_per_s_per_chip": dps / n_chips,
            "n_chips": n_chips,
            "batch_occupancy_mean": (float(np.mean(self._occupancies))
                                     if self._occupancies else None),
            "serving_span_s": span,
            "slo": self.slo.status(),
        }
        if lats.size and len(self._latency_req_ids) == lats.size:
            # exemplar: the request id of the sample nearest the p99 —
            # the concrete request a p99 regression points at (ids
            # exceed a float gauge's 2**53 precision, so the exemplar
            # only rides this dict, never the registry)
            p99 = float(np.percentile(lats, 99))
            snap["latency_p99_exemplar_req_id"] = int(
                self._latency_req_ids[int(np.argmin(np.abs(lats - p99)))])
        if lats.size:
            self.registry.gauge(
                "serve_decision_latency_p50_ms",
                "median submit->result decision latency").set(
                snap["latency_p50_ms"])
            self.registry.gauge(
                "serve_decision_latency_p99_ms",
                "p99 submit->result decision latency").set(
                snap["latency_p99_ms"])
        self.registry.gauge(
            "serve_decisions_per_s",
            "scheduling decisions served per second").set(dps)
        self.registry.gauge(
            "serve_decisions_per_s_per_chip",
            "decisions/s divided by local device count").set(
            dps / n_chips)
        return snap
