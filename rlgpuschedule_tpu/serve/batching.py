"""Continuous-batching front end: queue -> coalesce -> pad -> scatter.

The TF-Agents batched-environment insight (PAPERS.md: arXiv 1709.02878)
applied to serving: many independent decision streams become ONE
dispatch when their observations are stacked along a batch axis. The
front end's whole job is managing that axis on the host side:

- **coalesce**: pending requests are drained FIFO and rounded up to the
  next power-of-two *bucket* (``next_bucket``), so the jitted policy
  step compiles once per bucket instead of once per request count;
- **pad**: the tail of the bucket is filled with neutral rows (zero
  observations, all-actions-legal masks — a padded row must never
  produce ``-inf``-everywhere logits or NaNs, its action is discarded
  anyway);
- **scatter**: the batched action array is split back to the submitting
  requests in FIFO order (``scatter_results`` — the padding+scatter
  round-trip is property-tested in tests/test_serve.py).

Everything operates on HOST pytrees (numpy leaves, leading request
axis); device placement is the engine's job, so the queue never holds
device buffers hostage.
"""
from __future__ import annotations

import collections
import dataclasses
import random
import threading
import time
from concurrent.futures import Future
from typing import Any

import numpy as np

from ..obs.trace import NULL_TRACER


class Reservoir:
    """Bounded uniform sample of an unbounded stream (Vitter's
    Algorithm R): the first ``capacity`` observations are kept verbatim,
    after which each new observation replaces a random kept one with
    probability ``capacity / count``. Memory stays flat forever while
    every observation ever made has EQUAL probability of being in the
    sample — unlike a ``deque(maxlen=)`` ring, whose percentiles only
    describe the last ``capacity`` observations of a long soak run.
    Seeded so two servers replaying one workload keep identical samples.

    Sequence protocol (``len``/indexing/iteration) so ``np.asarray``
    and ``np.percentile`` consume it directly; ``count`` is the total
    number of observations ever offered.
    """

    def __init__(self, capacity: int, seed: int = 0):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = int(capacity)
        self.count = 0
        self._rng = random.Random(seed)
        self._samples: list[float] = []

    def append(self, v: float) -> None:
        self.count += 1
        if len(self._samples) < self.capacity:
            self._samples.append(v)
            return
        j = self._rng.randrange(self.count)
        if j < self.capacity:
            self._samples[j] = v

    def __len__(self) -> int:
        return len(self._samples)

    def __getitem__(self, i):
        return self._samples[i]

    def __iter__(self):
        return iter(self._samples)


def next_bucket(n: int, max_bucket: int) -> int:
    """The power-of-two batch bucket for ``n`` requests (smallest power
    of two >= n, capped by ``max_bucket``). Compiling one executable per
    bucket bounds the jit cache at log2(max_bucket)+1 entries while
    wasting at most half a batch of padding."""
    if n <= 0:
        raise ValueError(f"need at least one request, got {n}")
    if max_bucket <= 0 or (max_bucket & (max_bucket - 1)):
        raise ValueError(f"max_bucket must be a positive power of two, "
                         f"got {max_bucket}")
    if n > max_bucket:
        raise ValueError(f"{n} requests exceed max_bucket={max_bucket}; "
                         f"drain in max_bucket-sized dispatches")
    return 1 << (n - 1).bit_length()


def stack_requests(rows: "list[Any]") -> Any:
    """Stack per-request pytrees (no leading axis) into one batched host
    pytree (leading axis = len(rows), FIFO order preserved)."""
    import jax
    return jax.tree.map(lambda *xs: np.stack([np.asarray(x) for x in xs]),
                        *rows)


def pad_batch(batch: Any, bucket: int, fill_mask_true: bool = False) -> Any:
    """Pad a batched host pytree from n rows up to ``bucket`` rows.

    Padding rows are zeros, EXCEPT boolean leaves when
    ``fill_mask_true``: action masks pad with every action legal, so the
    padded rows' logits stay finite under the ``-inf`` masking scheme
    (an all-masked row is the degenerate case the models never see in
    training)."""
    import jax

    def pad(x):
        x = np.asarray(x)
        n = x.shape[0]
        if n > bucket:
            raise ValueError(f"batch of {n} rows exceeds bucket {bucket}")
        if n == bucket:
            return x
        fill = (np.ones if (fill_mask_true and x.dtype == np.bool_)
                else np.zeros)
        return np.concatenate(
            [x, fill((bucket - n,) + x.shape[1:], x.dtype)])

    return jax.tree.map(pad, batch)


def scatter_results(actions: Any, n: int) -> "list[Any]":
    """Split a batched action pytree back into ``n`` per-request pytrees
    in submission order, dropping the padding tail."""
    import jax
    return [jax.tree.map(lambda x: np.asarray(x)[i], actions)
            for i in range(n)]


@dataclasses.dataclass
class ServeResult:
    """What a request's future resolves to."""
    action: Any            # per-request action pytree (numpy)
    latency_s: float       # submit -> result, queue wait included


class DeadlineSheddedError(RuntimeError):
    """Typed rejection a shed request's future resolves with.

    Shedding is NEVER a silent drop: the future completes exceptionally
    with this error, carrying why (``reason``: ``"admission"`` — the
    predicted wait at submit already exceeded the deadline — or
    ``"expired"`` — the deadline passed while queued) and the numbers
    behind the verdict, so a client can retry elsewhere, relax its
    deadline, or back off — the load-shedding contract from the lost-
    computation accounting school: reject loudly at the door rather
    than time out quietly inside."""

    def __init__(self, reason: str, deadline_s: float, waited_s: float,
                 predicted_wait_s: "float | None" = None):
        self.reason = reason
        self.deadline_s = float(deadline_s)
        self.waited_s = float(waited_s)
        self.predicted_wait_s = predicted_wait_s
        pred = (f", predicted wait {predicted_wait_s * 1e3:.1f}ms"
                if predicted_wait_s is not None else "")
        super().__init__(
            f"request shed ({reason}): deadline {deadline_s * 1e3:.1f}ms"
            f", waited {waited_s * 1e3:.1f}ms{pred}")


class ServerClosedError(RuntimeError):
    """Typed refusal for submits against a stopped or closed server.

    Raised by :meth:`PolicyServer.submit` while a :meth:`PolicyServer.stop`
    drain is in flight and forever after :meth:`PolicyServer.close` — the
    drain half of the no-silent-drop contract: a client racing a shutdown
    gets a typed, catchable refusal at the door instead of a future that
    no dispatcher will ever resolve. Distinguishable from
    :class:`DeadlineSheddedError` (overload, retry later with backoff)
    and from a bare ``RuntimeError`` (a bug): closed means *this server
    is going away — re-resolve and connect elsewhere*."""


class Ewma:
    """Streaming exponentially-weighted mean — the arrival-rate /
    service-time estimator behind adaptive batching. O(1) memory, no
    sample window to size; ``alpha`` is the forgetting factor (higher =
    faster tracking, noisier). ``value`` is ``None`` until the first
    observation — callers must not act on an unlearned estimate."""

    def __init__(self, alpha: float = 0.2):
        if not (0.0 < alpha <= 1.0):
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = float(alpha)
        self.value: "float | None" = None
        self.count = 0

    def update(self, x: float) -> float:
        x = float(x)
        self.count += 1
        self.value = (x if self.value is None
                      else self.alpha * x + (1 - self.alpha) * self.value)
        return self.value


@dataclasses.dataclass
class _Pending:
    obs: Any
    mask: Any
    stall: int
    t_submit: float
    future: Future
    deadline_s: "float | None" = None   # relative to t_submit; None = no SLO


class PolicyServer:
    """The continuous-batching request queue over one
    :class:`~.engine.InferenceEngine`.

    ``submit`` enqueues a request and returns a
    :class:`concurrent.futures.Future` resolving to :class:`ServeResult`;
    ``pump`` drains up to ``engine.max_bucket`` pending requests into
    one coalesced dispatch. Drive it either inline (submit-then-pump —
    deterministic batch composition; what ``serve --bench`` does so its
    measured dispatch sizes are exactly the request sizes) or via the
    background dispatcher thread (:meth:`start` / :meth:`stop`) for live
    continuous batching, where a dispatch grabs whatever is pending the
    moment the previous one finishes.

    SLO surface (the ``registry`` gauges/counters, re-rendered by both
    the ``metrics.prom`` snapshot and the live scrape endpoint):
    ``serve_requests_total``, ``serve_dispatches_total``,
    ``serve_queue_depth``, ``serve_batch_occupancy`` (real rows /
    bucket, last dispatch), the ``serve_decision_latency_seconds``
    histogram (observed per request at scatter — the aggregatable
    latency surface; scrape-side ``histogram_quantile`` beats exporting
    pre-computed percentiles), ``serve_latency_sample_window`` (live
    reservoir size), ``serve_decision_latency_p50_ms`` / ``_p99_ms``
    and ``serve_decisions_per_s`` (+ ``_per_chip``) via
    :meth:`slo_snapshot`.

    With a ``tracer`` attached (``serve --trace-spans``) the request
    lifecycle lands on the flight recorder: an ``enqueue`` instant per
    submit, then ``bucket_wait`` -> ``serve_batch`` (``stack`` ->
    engine ``pad``/``dispatch`` -> ``scatter``) per pump.
    """

    def __init__(self, engine, registry=None, latency_window: int = 8192,
                 clock=time.perf_counter, max_wait_s: float | None = None,
                 tracer=None, sample_seed: int = 0,
                 adaptive_wait: bool = False):
        from ..obs import Registry
        self.engine = engine
        self.registry = registry if registry is not None else Registry()
        self.tracer = tracer if tracer is not None else NULL_TRACER
        if max_wait_s is not None and max_wait_s < 0:
            raise ValueError(f"max_wait_s must be >= 0, got {max_wait_s}")
        self.max_wait_s = max_wait_s
        self.adaptive_wait = bool(adaptive_wait)
        self._clock = clock
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._pending: collections.deque[_Pending] = collections.deque()
        # lifetime-uniform reservoirs, not rings: a soak run's p99 must
        # describe the whole run, not its trailing window
        self._latencies = Reservoir(latency_window, seed=sample_seed)
        self._occupancies = Reservoir(latency_window, seed=sample_seed + 1)
        self._threads: list[threading.Thread] = []
        self._stopped = False
        self._closed = False
        self._served = 0
        self._t_first: float | None = None
        self._t_last: float | None = None
        # streaming estimators feeding adaptive batching + admission:
        # inter-arrival gap (how long a bucket slot takes to fill) and
        # per-dispatch service time (how long a queued dispatch costs)
        self._arrival_gap = Ewma(alpha=0.2)
        self._service_time = Ewma(alpha=0.2)
        self._t_prev_submit: "float | None" = None
        self._requests = self.registry.counter(
            "serve_requests_total", "scheduling requests submitted")
        self._shed = self.registry.counter(
            "serve_shed_total",
            "requests rejected with a typed deadline rejection "
            "(admission + in-queue expiry)")
        self._dispatches = self.registry.counter(
            "serve_dispatches_total", "coalesced batch dispatches")
        self._padded = self.registry.counter(
            "serve_padded_slots_total",
            "bucket slots filled with padding instead of requests")
        self._depth = self.registry.gauge(
            "serve_queue_depth", "requests waiting after the last drain")
        self._occupancy = self.registry.gauge(
            "serve_batch_occupancy",
            "real rows / bucket rows of the last dispatch")
        self._sample_window = self.registry.gauge(
            "serve_latency_sample_window",
            "latency samples currently held by the reservoir")
        self._latency_hist = self.registry.histogram(
            "serve_decision_latency_seconds",
            "submit->result decision latency (cumulative histogram; "
            "aggregatable across ranks/restarts, unlike percentile "
            "gauges)")
        self._dispatch_errors = self.registry.counter(
            "serve_dispatch_errors_total",
            "background pumps that raised after resolving their batch's "
            "futures exceptionally (the dispatcher survives and keeps "
            "serving)")

    def _reject(self, fut: Future, exc: DeadlineSheddedError,
                reason: str) -> None:
        """Resolve ``fut`` with a typed shed rejection and count it in
        ``serve_shed_total`` — counting gated on WINNING the future's
        state transition, so a request raced by two dispatchers' expiry
        scans (or abandoned via ``Future.cancel``) is counted at most
        once, and only when someone will actually observe the rejection.
        Conservation (submitted == resolved + shed) is structural, not
        best-effort."""
        try:
            fut.set_exception(exc)
        except BaseException:   # cancelled, or already resolved elsewhere
            return
        self._shed.inc()
        self.tracer.instant("shed", reason=reason)

    def submit(self, obs: Any, mask: Any, stall: int = 0,
               deadline_s: "float | None" = None) -> Future:
        """Enqueue one scheduling request (host pytrees, NO leading batch
        axis). ``stall`` is the client's consecutive-zero-dt count for
        the stall gate (preemptive configs; 0 = gate disengaged).

        ``deadline_s`` is the request's latency SLO, relative to submit.
        A deadlined request is subject to **load shedding**: if the
        predicted queue wait at submit time (queued dispatches ahead ×
        learned service time) already exceeds the deadline, or the
        deadline expires while queued, the returned future resolves
        exceptionally with :class:`DeadlineSheddedError` — typed, never
        a silent drop — and ``serve_shed_total`` counts it. Admission
        only rejects once the service-time estimator has observations
        (a cold server admits everything rather than guessing)."""
        now = self._clock()
        fut: Future = Future()
        req = _Pending(obs=obs, mask=mask, stall=int(stall),
                       t_submit=now, future=fut,
                       deadline_s=(None if deadline_s is None
                                   else float(deadline_s)))
        with self._wake:
            if self._closed:
                raise ServerClosedError(
                    "PolicyServer is closed (drained for shutdown)")
            if self._stopped:
                raise ServerClosedError(
                    "PolicyServer is stopped (drain in flight)")
            self._requests.inc()
            if self._t_prev_submit is not None:
                self._arrival_gap.update(now - self._t_prev_submit)
            self._t_prev_submit = now
            svc = self._service_time.value
            if (req.deadline_s is not None and svc is not None):
                # dispatches ahead of this request if it joins the queue,
                # itself included — each costs ~one learned service time
                ahead = -(-(len(self._pending) + 1)
                          // self.engine.max_bucket)
                predicted = ahead * svc
                if predicted > req.deadline_s:
                    self._reject(fut, DeadlineSheddedError(
                        "admission", req.deadline_s, waited_s=0.0,
                        predicted_wait_s=predicted), reason="admission")
                    return fut
            self._pending.append(req)
            self._wake.notify()
        self.tracer.instant("enqueue", stall=int(stall))
        return fut

    def _shed_expired(self, now: float) -> None:
        """Drop queued requests whose deadline already passed (called
        under ``self._lock``); their futures resolve with the typed
        rejection. Head-first scan is NOT enough: deadlines are
        per-request, so a generous-deadline head can hide an expired
        tail."""
        if not any(r.deadline_s is not None for r in self._pending):
            return
        keep: collections.deque[_Pending] = collections.deque()
        for r in self._pending:
            if (r.deadline_s is not None
                    and now - r.t_submit > r.deadline_s):
                self._reject(r.future, DeadlineSheddedError(
                    "expired", r.deadline_s,
                    waited_s=now - r.t_submit), reason="expired")
            else:
                keep.append(r)
        self._pending = keep

    def _effective_wait(self) -> "float | None":
        """The partial-bucket hold time for THIS pump (called under
        ``self._lock``, queue non-empty). Static mode returns the
        constructor knob. Adaptive mode learns it: hold for the
        estimated time to FILL the bucket at the observed arrival rate
        (waiting longer than that buys nothing), clipped to the
        head-of-line deadline slack (dispatch a partial bucket rather
        than shed the head), and capped by ``max_wait_s`` when given."""
        if not self.adaptive_wait:
            return self.max_wait_s
        waits = []
        if self.max_wait_s is not None:
            waits.append(self.max_wait_s)
        gap = self._arrival_gap.value
        if gap is not None:
            free = max(self.engine.max_bucket - len(self._pending), 0)
            waits.append(gap * free)
        now = self._clock()
        slacks = [r.t_submit + r.deadline_s - now
                  for r in self._pending if r.deadline_s is not None]
        if slacks:
            # keep one learned service time in hand for the dispatch
            svc = self._service_time.value or 0.0
            waits.append(max(min(slacks) - svc, 0.0))
        return min(waits) if waits else None

    def pump(self, max_wait_s: float | None = None) -> int:
        """Drain one coalesced batch: pop up to ``engine.max_bucket``
        pending requests (FIFO), pad to the bucket, dispatch, scatter
        results to their futures. Returns the number of requests served
        (0 = queue was empty).

        ``max_wait_s`` (default: the constructor's policy; ``None`` = no
        wait) is the batching deadline: a PARTIAL bucket holds off
        dispatching until either the bucket fills or the batching
        deadline passes — trading a bounded latency floor for occupancy
        (the classic continuous-batching knob). ``0`` keeps the
        dispatch-whatever-is-pending behavior while still being
        explicit about it. With ``adaptive_wait`` the hold time is
        LEARNED per pump (:meth:`_effective_wait`): the estimated
        bucket fill time at the observed arrival rate, cut short when
        the head-of-line deadline slack runs out — the deadline-aware
        partial-bucket dispatch. Expired deadlines shed before and
        after the hold (:meth:`_shed_expired`). A :meth:`stop` drain
        cuts the wait short so shutdown never hangs on a sparse
        queue."""
        with self._lock:
            self._shed_expired(self._clock())
            if self._pending:
                wait = (max_wait_s if max_wait_s is not None
                        else self._effective_wait())
                if wait is not None:
                    # static mode anchors at the head's submit time
                    # (total head wait bounded by the knob); adaptive
                    # mode anchors NOW — its estimate already folds in
                    # the head's remaining slack
                    anchor = (self._clock()
                              if max_wait_s is None and self.adaptive_wait
                              else self._pending[0].t_submit)
                    deadline = anchor + wait
                    with self.tracer.span("bucket_wait"):
                        while (len(self._pending) < self.engine.max_bucket
                               and not self._stopped):
                            remaining = deadline - self._clock()
                            if remaining <= 0:
                                break
                            self._wake.wait(timeout=remaining)
                    self._shed_expired(self._clock())
            batch = [self._pending.popleft()
                     for _ in range(min(len(self._pending),
                                        self.engine.max_bucket))]
            self._depth.set(len(self._pending))
        if not batch:
            return 0
        n = len(batch)
        t_disp = self._clock()
        try:
            with self.tracer.span("serve_batch", n=n):
                with self.tracer.span("stack"):
                    obs = stack_requests([r.obs for r in batch])
                    mask = stack_requests([r.mask for r in batch])
                    stall = np.asarray([r.stall for r in batch], np.int32)
                actions, bucket = self.engine.decide(obs, mask, stall)
                now = self._clock()
                with self.tracer.span("scatter"):
                    per_req = scatter_results(actions, n)
        except BaseException as e:
            for r in batch:
                if not r.future.cancelled():
                    r.future.set_exception(e)
            raise
        # accounting under the lock: concurrent dispatcher threads
        # (start(dispatchers=N) over a router) share every reservoir,
        # counter, and estimator below
        lats = [now - r.t_submit for r in batch]
        with self._lock:
            self._service_time.update(now - t_disp)
            self._dispatches.inc()
            self._padded.inc(bucket - n)
            self._occupancy.set(n / bucket)
            self._occupancies.append(n / bucket)
            if self._t_first is None:
                self._t_first = min(r.t_submit for r in batch)
            self._t_last = now if self._t_last is None else max(
                self._t_last, now)
            self._served += n
            for lat in lats:
                self._latencies.append(lat)
                self._latency_hist.observe(lat)
            self._sample_window.set(len(self._latencies))
        for r, a, lat in zip(batch, per_req, lats):
            r.future.set_result(ServeResult(action=a, latency_s=lat))
        return n

    # ---- live dispatcher thread --------------------------------------

    def start(self, dispatchers: int = 1) -> None:
        """Start the background dispatchers: pump whenever requests are
        pending (continuous batching — each dispatch coalesces whatever
        arrived while the previous one ran). ``dispatchers > 1`` keeps
        that many pumps in flight at once so a multi-engine router can
        run its engines concurrently; over a single engine extra
        dispatchers only shrink batch occupancy (and the router is the
        layer that owns device-level thread safety — see
        ``serve.router.EngineRouter``)."""
        if self._threads:
            raise RuntimeError("dispatcher already running")
        if self._closed:
            raise ServerClosedError("PolicyServer is closed")
        if dispatchers < 1:
            raise ValueError(f"dispatchers must be >= 1, got {dispatchers}")
        self._stopped = False

        def loop():
            while True:
                with self._wake:
                    while not self._pending and not self._stopped:
                        self._wake.wait()
                    if self._stopped and not self._pending:
                        return
                try:
                    self.pump()
                except Exception:
                    # the pump already resolved its batch's futures with
                    # the exception (no silent drop); a dead dispatcher
                    # would strand every LATER request as a hung future,
                    # so survive the failed dispatch and keep draining
                    self._dispatch_errors.inc()

        for i in range(dispatchers):
            t = threading.Thread(target=loop,
                                 name=f"serve-dispatcher-{i}",
                                 daemon=True)
            self._threads.append(t)
            t.start()

    def stop(self) -> None:
        """Stop the dispatchers after draining the queue. Submits are
        refused while the drain is in flight; once stopped the server
        is back in inline mode (submit-then-:meth:`pump`) and
        :meth:`start` may be called again."""
        with self._wake:
            self._stopped = True
            self._wake.notify_all()
        for t in self._threads:
            t.join(timeout=30)
        self._threads = []
        with self._wake:
            # a close() drain is terminal; a stop() drain returns the
            # server to inline mode
            self._stopped = self._closed

    def close(self) -> None:
        """Permanent :meth:`stop`: drain the queue, stop the dispatchers,
        then refuse every later :meth:`submit` (and :meth:`start`) with
        :class:`ServerClosedError` forever. The terminal half of the
        frontend's graceful-drain contract — after ``close`` returns,
        every future ever handed out has resolved (result, shed, or
        dispatch error) and no future will ever be created that can't.
        Idempotent."""
        with self._wake:
            self._closed = True
        self.stop()
        # inline-mode close: no dispatcher drained the queue, so flush it
        # here — every already-accepted future must resolve (each pump
        # consumes its batch even when the dispatch raises, so this
        # terminates)
        while True:
            try:
                if not self.pump():
                    break
            except Exception:
                self._dispatch_errors.inc()

    @property
    def closed(self) -> bool:
        return self._closed

    def queue_depth(self) -> int:
        """Requests currently queued (the frontend's backpressure
        signal — sampled, so momentarily stale values are fine)."""
        with self._lock:
            return len(self._pending)

    def service_time_s(self) -> "float | None":
        """The learned per-dispatch service time (Ewma), ``None`` until
        the first dispatch — what the frontend derives ``Retry-After``
        from for shed responses."""
        with self._lock:
            return self._service_time.value

    # ---- SLO surface -------------------------------------------------

    def slo_snapshot(self) -> dict:
        """Compute and publish the SLO numbers: p50/p99 decision latency
        (ms), decisions/s and decisions/s/chip over the serving span,
        mean batch occupancy. Also writes the latency/throughput gauges
        into the registry so a scrape observes them."""
        import jax
        lats = np.asarray(self._latencies, np.float64)
        span = ((self._t_last - self._t_first)
                if self._served and self._t_last is not None
                and self._t_first is not None else 0.0)
        n_chips = max(jax.local_device_count(), 1)
        dps = self._served / span if span > 0 else 0.0
        snap = {
            "requests": int(self._served),
            "dispatches": int(self._dispatches.value),
            "latency_p50_ms": (float(np.percentile(lats, 50)) * 1e3
                               if lats.size else None),
            "latency_p99_ms": (float(np.percentile(lats, 99)) * 1e3
                               if lats.size else None),
            "decisions_per_s": dps,
            "decisions_per_s_per_chip": dps / n_chips,
            "n_chips": n_chips,
            "batch_occupancy_mean": (float(np.mean(self._occupancies))
                                     if self._occupancies else None),
            "serving_span_s": span,
        }
        if lats.size:
            self.registry.gauge(
                "serve_decision_latency_p50_ms",
                "median submit->result decision latency").set(
                snap["latency_p50_ms"])
            self.registry.gauge(
                "serve_decision_latency_p99_ms",
                "p99 submit->result decision latency").set(
                snap["latency_p99_ms"])
        self.registry.gauge(
            "serve_decisions_per_s",
            "scheduling decisions served per second").set(dps)
        self.registry.gauge(
            "serve_decisions_per_s_per_chip",
            "decisions/s divided by local device count").set(
            dps / n_chips)
        return snap
