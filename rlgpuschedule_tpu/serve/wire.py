"""Length-prefixed binary framing for the serving front door.

HTTP/1.1 costs a request-line + header parse per decide; for high-fan-in
clients that cost dominates the host path once the data plane itself is
zero-copy (ISSUE 17). This module defines the **frame mode** the
frontend speaks on the same port: a connection whose first 4 bytes are
``MAGIC`` is framed for its whole life, anything else is HTTP. One
v2 frame is::

    <4s B  B    H        I         Q        I       Q   >  little-endian
    magic ver kind  header_len  body_len  meta64  meta32  req_id
    [header: header_len bytes][body: body_len bytes]

- ``kind=KIND_REQ``: header is the request **descriptor** — an exact
  ascii encoding of the wire schema (``float32:(6,)|bool:(9,)``) that
  the server validates by BYTE EQUALITY against its own (one ``==``,
  no parsing on the hot path); ``meta64`` is the deadline in
  microseconds (0 = no SLO), ``meta32`` the stall count; the body is
  the raw C-contiguous obs bytes followed by the mask bytes —
  ``np.frombuffer`` views them straight into :meth:`PolicyServer.submit`,
  whose arena slot write is the single copy of the request's life.
- ``kind=KIND_RESP``: header is the action descriptor, ``meta64`` the
  decision latency in microseconds, body the raw action bytes.
- ``kind=KIND_ERR``: header is a short ascii reason (``shed:admission``,
  ``shed:expired``, ``closed``, ``bad-request``), ``meta64`` the
  suggested retry-after in microseconds (0 = do not retry here), body a
  small JSON detail payload mirroring the HTTP error shape.

``req_id`` (v2, ISSUE 20) is the request-causality key: a compact
64-bit id the client may supply (0 = let the server mint one) that the
server threads through the arena, the latency exemplars, the flight
log, and every response/error frame for that request — the join key
``obs.report --request`` reconstructs a timeline from.

**Version compatibility**: ``VERSION`` is 2 and :func:`pack_frame`
always emits the 32-byte v2 prefix, but v1 frames (24-byte prefix, no
``req_id`` field) still decode — :func:`unpack_prefix` accepts both
sizes and :func:`recv_frame` sniffs the version byte before reading the
prefix tail. A v1 frame simply carries ``req_id == 0`` ("unassigned").

The framing is deliberately dumb: fixed-size prefix, no continuation,
no multiplexing — amortizing parse cost over a keep-alive connection is
the whole win, and the protocol stays small enough to pin completely in
tier-1 tests.
"""
from __future__ import annotations

import json
import socket
import struct
from typing import Any

import numpy as np

MAGIC = b"RLSF"
VERSION = 2
KIND_REQ = 1
KIND_RESP = 2
KIND_ERR = 3
_KINDS = (KIND_REQ, KIND_RESP, KIND_ERR)

PREFIX = struct.Struct("<4sBBHIQIQ")
PREFIX_SIZE = PREFIX.size            # 32 bytes (v2)
PREFIX_V1 = struct.Struct("<4sBBHIQI")
PREFIX_V1_SIZE = PREFIX_V1.size      # 24 bytes (v1, no req_id)

# defensive ceiling: a frame is one request/response row, never a
# training batch — anything bigger is a corrupt or hostile prefix
MAX_BODY_BYTES = 64 * 1024 * 1024


class WireError(ValueError):
    """Malformed frame (bad magic/version/kind, oversized, or a
    descriptor mismatch). Maps to the transport's bad-request path."""


def descriptor(tree: Any) -> bytes:
    """Exact ascii schema of a host pytree's leaves, in leaf order:
    ``dtype:(shape)`` joined by ``|``. Validation is byte equality —
    two ends agree iff their descriptors are identical."""
    import jax
    leaves = [np.asarray(l) for l in jax.tree.leaves(tree)]
    return "|".join(
        f"{l.dtype.name}:{l.shape}" for l in leaves).encode("ascii")


def pack_frame(kind: int, header: bytes, body: bytes = b"",
               meta64: int = 0, meta32: int = 0, req_id: int = 0) -> bytes:
    if kind not in _KINDS:
        raise WireError(f"unknown frame kind {kind}")
    if len(header) > 0xFFFF:
        raise WireError(f"header too large ({len(header)} bytes)")
    if len(body) > MAX_BODY_BYTES:
        raise WireError(f"body too large ({len(body)} bytes)")
    return PREFIX.pack(MAGIC, VERSION, kind, len(header), len(body),
                       meta64, meta32, req_id) + header + body


def unpack_prefix(buf: bytes) -> "tuple[int, int, int, int, int, int]":
    """Parse one frame prefix -> (kind, header_len, body_len, meta64,
    meta32, req_id). Accepts the 32-byte v2 prefix AND the legacy
    24-byte v1 prefix (``req_id`` reads as 0); raises
    :class:`WireError` on anything that is not a well-formed, sane
    frame head."""
    if len(buf) == PREFIX_SIZE:
        magic, version, kind, hlen, blen, meta64, meta32, req_id = \
            PREFIX.unpack(buf)
        if version != VERSION:
            raise WireError(f"unsupported wire version {version} for a "
                            f"{PREFIX_SIZE}-byte prefix")
    elif len(buf) == PREFIX_V1_SIZE:
        magic, version, kind, hlen, blen, meta64, meta32 = \
            PREFIX_V1.unpack(buf)
        req_id = 0
        if version != 1:
            raise WireError(f"unsupported wire version {version} for a "
                            f"{PREFIX_V1_SIZE}-byte prefix")
    else:
        raise WireError(f"prefix must be {PREFIX_V1_SIZE} (v1) or "
                        f"{PREFIX_SIZE} (v2) bytes, got {len(buf)}")
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r}")
    if kind not in _KINDS:
        raise WireError(f"unknown frame kind {kind}")
    if blen > MAX_BODY_BYTES:
        raise WireError(f"body length {blen} exceeds {MAX_BODY_BYTES}")
    return kind, hlen, blen, meta64, meta32, req_id


def pack_request(obs: Any, mask: Any, deadline_s: "float | None" = None,
                 stall: int = 0, req_id: int = 0) -> bytes:
    """Client-side helper: one decide request as a single frame."""
    import jax
    obs_b = b"".join(np.ascontiguousarray(l).tobytes()
                     for l in jax.tree.leaves(obs))
    mask_b = b"".join(np.ascontiguousarray(l).tobytes()
                      for l in jax.tree.leaves(mask))
    header = descriptor(obs) + b"|" + descriptor(mask)
    meta64 = 0 if deadline_s is None else max(int(deadline_s * 1e6), 1)
    return pack_frame(KIND_REQ, header, obs_b + mask_b,
                      meta64=meta64, meta32=int(stall), req_id=req_id)


def pack_response(action: Any, latency_s: float, req_id: int = 0) -> bytes:
    arr = np.ascontiguousarray(action)
    return pack_frame(KIND_RESP, descriptor(arr), arr.tobytes(),
                      meta64=max(int(latency_s * 1e6), 0),
                      req_id=req_id)


def pack_error(reason: str, detail: dict,
               retry_after_s: "float | None" = None,
               req_id: int = 0) -> bytes:
    meta64 = (0 if retry_after_s is None
              else max(int(retry_after_s * 1e6), 1))
    return pack_frame(KIND_ERR, reason.encode("ascii"),
                      json.dumps(detail).encode(), meta64=meta64,
                      req_id=req_id)


def recv_frame(
        sock: socket.socket
) -> "tuple[int, bytes, bytes, int, int, int]":
    """Blocking client-side frame read -> (kind, header, body, meta64,
    meta32, req_id). Version-sniffing: reads the 24-byte v1 head, then
    the 8-byte v2 tail iff the version byte says so. Raises
    :class:`ConnectionError` on EOF mid-frame, and ``EOFError`` on a
    clean EOF at a frame boundary."""
    def read_exact(n: int, at_boundary: bool = False) -> bytes:
        chunks = []
        got = 0
        while got < n:
            c = sock.recv(n - got)
            if not c:
                if at_boundary and got == 0:
                    raise EOFError("connection closed at frame boundary")
                raise ConnectionError("connection closed mid-frame")
            chunks.append(c)
            got += len(c)
        return b"".join(chunks)

    head = read_exact(PREFIX_V1_SIZE, at_boundary=True)
    if len(head) > 4 and head[4] == VERSION:
        head += read_exact(PREFIX_SIZE - PREFIX_V1_SIZE)
    kind, hlen, blen, meta64, meta32, req_id = unpack_prefix(head)
    header = read_exact(hlen) if hlen else b""
    body = read_exact(blen) if blen else b""
    return kind, header, body, meta64, meta32, req_id


def unpack_action(header: bytes, body: bytes) -> np.ndarray:
    """Decode a KIND_RESP payload back into the action array (client
    side). The descriptor grammar is ``dtype:(shape)``. Returns a
    read-only **view** over ``body`` (zero-copy — ``bytes`` is
    immutable and the view keeps it alive, so no copy is needed)."""
    try:
        dtype_name, _, shape_s = header.decode("ascii").partition(":")
        shape = tuple(int(d) for d in
                      shape_s.strip("()").split(",") if d.strip())
        return np.frombuffer(body, dtype=np.dtype(dtype_name)).reshape(
            shape)
    except (ValueError, TypeError) as e:
        raise WireError(f"bad action descriptor {header!r}") from e
