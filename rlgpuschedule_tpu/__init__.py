"""rlgpuschedule_tpu — a TPU-native RL GPU-cluster scheduler framework.

A from-scratch rebuild of the capabilities of ``matthewygf/RLGPUSchedule``
(see SURVEY.md; the reference mount was empty, so parity targets come from the
driver's capability spec, provenance tag ``[B]`` in SURVEY.md):

- L0 traces:      Microsoft Philly / Alibaba PAI loaders + synthetic Poisson.
- L1 simulator:   a discrete-event GPU-cluster simulator, twice —
                  * ``sim.oracle``: an exact event-driven Python oracle
                    (executable spec, hosts the baseline schedulers), and
                  * ``sim.core``:   a pure-functional, jit/vmap-able JAX sim
                    with fixed-shape state (the TPU-native hot path).
- L2 env:         gym-style pure-functional env with grid / flat / graph
                  observations, JCT + fairness rewards, action masking.
- L3 models:      Flax MLP / CNN / GNN actor-critic encoders.
- L4 algorithms:  PPO / A2C with fused lax.scan rollouts and reverse-scan GAE.
- L5 parallel:    data-parallel shard_map + psum over a device mesh,
                  hierarchical multi-agent, population-based training.
- L6 driver:      named configs, train/evaluate CLIs, metrics, checkpoints.
"""

__version__ = "0.1.0"
