"""Population-based training controller (L5) — config 5's exploit/explore.

Capability parity: SURVEY.md §2 "PBT controller" and §3.5: periodically
rank members by fitness; the bottom quantile copies weights + optimizer
state + hyperparameters from a random top-quantile member (**exploit**)
and perturbs the copied hyperparameters (**explore**).

TPU-native mechanics: the decision logic (rank, pair losers with winners,
perturb) is tiny host numpy; the weight transfer is ONE jitted gather
``tree.map(lambda x: x[src], stacked_members)`` over the pop-sharded
member stack — XLA lowers it to the cross-``pop`` collective (DCN between
pod slices in a multi-slice deployment), replacing the reference's NCCL
broadcast of state_dicts (SURVEY.md §2 "Distributed comm backend",
"NCCL broadcast/gather (PBT weight exchange)").
"""
from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .population import HPARAM_BOUNDS, HParams


@dataclasses.dataclass(frozen=True)
class PBTConfig:
    ready_iters: int = 10        # iterations between exploit/explore rounds
    exploit_frac: float = 0.25   # bottom quantile replaced from top quantile
    perturb_low: float = 0.8     # explore: multiply each hparam by
    perturb_high: float = 1.25   #   low or high, chosen uniformly
    seed: int = 0


@dataclasses.dataclass
class PBTDecision:
    """One exploit/explore round's outcome (host-side, for logging)."""
    src: np.ndarray        # i32[P] — member i copies from src[i] (i = keep)
    exploited: np.ndarray  # bool[P]
    hparams: HParams       # post-explore stacked [P] hparams


def exploit_explore(rng: np.random.Generator, fitness: np.ndarray,
                    hparams: HParams, cfg: PBTConfig) -> PBTDecision:
    """Truncation-selection PBT: bottom ``exploit_frac`` of members copy a
    uniformly-chosen top-``exploit_frac`` member and perturb its hparams.

    Non-finite fitness (a diverged member) is treated as DEAD, not merely
    last-ranked: every dead member is forcibly exploited — re-seeded from
    the single best finite member — regardless of the truncation quota,
    and winners are drawn from finite members only. Ranking NaN as worst
    (the previous behavior) still let dead members survive whenever more
    members diverged than the bottom quantile holds, and could copy FROM
    a dead member when divergence reached the top quantile. With no
    finite member at all there is nobody to re-seed from; dead members
    then keep their state (the population watchdog's whole-run rollback
    is the recovery for that case)."""
    raw = np.asarray(fitness, np.float64)
    finite = np.isfinite(raw)
    fitness = np.where(finite, raw, -np.inf)
    n = len(fitness)
    k = max(int(np.floor(n * cfg.exploit_frac)), 1) if n > 1 else 0
    order = np.argsort(fitness)           # ascending: losers first
    losers = order[:k]
    winners = order[n - k:][finite[order[n - k:]]] if k else order[:0]
    src = np.arange(n)
    if k and len(winners):
        src[losers] = rng.choice(winners, size=k)
    if finite.any() and not finite.all():
        # dead members re-seed from the best member, quota or not
        src[~finite] = int(np.argmax(fitness))
    exploited = src != np.arange(n)

    hp = jax.tree.map(np.asarray, hparams)
    new_hp = {}
    for name in HParams._fields:
        vals = np.array(hp._asdict()[name][src], dtype=np.float32)
        factors = rng.choice([cfg.perturb_low, cfg.perturb_high], size=n)
        lo, hi = HPARAM_BOUNDS[name]
        vals = np.where(exploited,
                        np.clip(vals * factors, lo, hi), vals)
        new_hp[name] = jnp.asarray(vals.astype(np.float32))
    return PBTDecision(src=src, exploited=exploited,
                       hparams=HParams(**new_hp))


# compiled gather per (treedef, leaf avals+shardings) — a PBT run hits one
# entry, so exploit rounds reuse the compilation instead of re-tracing a
# fresh lambda every round
_GATHER_CACHE: dict = {}


def _gather_fn(t, src):
    return jax.tree.map(lambda x: x[src], t)


def gather_members(stacked: Any, src: np.ndarray | jax.Array) -> Any:
    """Copy member src[i] -> slot i across a stacked [P, ...] pytree (the
    exploit weight transfer). jit-compiled with the inputs' shardings pinned
    on the outputs — a bare jit would let the compiler replicate the
    gathered copies off the ``pop`` axis."""
    src = jnp.asarray(src)
    leaves, treedef = jax.tree.flatten(stacked)
    key = (treedef,
           tuple((l.shape, str(l.dtype), l.sharding) for l in leaves))
    fn = _GATHER_CACHE.get(key)
    if fn is None:
        out_sh = jax.tree.map(lambda x: x.sharding, stacked)
        fn = _GATHER_CACHE[key] = jax.jit(_gather_fn, out_shardings=out_sh)
    return fn(stacked, src)


class PBTController:
    """Host-side fitness accounting + periodic exploit/explore.

    Usage per training iteration ``i``::

        ctrl.record(metrics.mean_reward)        # [P] per-member fitness
        out = ctrl.maybe_update(i, states, hparams)
        if out is not None:
            states, hparams, decision = out
    """

    def __init__(self, n_pop: int, cfg: PBTConfig = PBTConfig()):
        self.cfg = cfg
        self.n_pop = n_pop
        self._rng = np.random.default_rng(cfg.seed)
        # fitness arrives as device arrays and is NOT synced on record —
        # the host loop stays ahead of the device (async dispatch); we only
        # materialize at the ready boundary
        self._pending: list = []
        self._fitness_sum = np.zeros(n_pop)
        self._fitness_n = 0
        self.history: list[PBTDecision] = []

    def record(self, fitness: jax.Array | np.ndarray) -> None:
        """Queue one iteration's per-member fitness [P]; no device sync."""
        self._pending.append(fitness)

    def _drain(self) -> None:
        for f in self._pending:
            self._fitness_sum += np.asarray(f, dtype=np.float64)
            self._fitness_n += 1
        self._pending.clear()

    @property
    def mean_fitness(self) -> np.ndarray:
        """Per-member mean fitness over the current window — or, right
        after an exploit/explore round reset the window, over the window
        that round was decided on (so end-of-run reporting never reads an
        empty accumulator as zeros)."""
        self._drain()
        if self._fitness_n == 0 and self.history:
            return self._last_window_fitness
        return self._fitness_sum / max(self._fitness_n, 1)

    def state_dict(self) -> dict:
        """JSON-able snapshot of EVERYTHING the next exploit/explore
        decision depends on: the numpy bit-generator state, the fitness
        window accumulator, and the decision history. Checkpointing only
        the member arrays (as round 2 did) silently re-seeds the RNG and
        zeroes the window on resume, so the resumed run's next exploit
        round diverges from the uninterrupted one (VERDICT r2 weak #2);
        restoring this dict makes resume bit-exact
        (tests/test_pbt.py resume test)."""
        self._drain()
        out = {
            "rng": self._rng.bit_generator.state,
            "fitness_sum": [float(x) for x in self._fitness_sum],
            "fitness_n": int(self._fitness_n),
            "history": [
                {"src": [int(x) for x in d.src],
                 "exploited": [bool(x) for x in d.exploited],
                 "hparams": {k: [float(x) for x in v] for k, v in
                             jax.tree.map(np.asarray,
                                          d.hparams)._asdict().items()}}
                for d in self.history],
        }
        if hasattr(self, "_last_window_fitness"):
            out["last_window_fitness"] = [float(x) for x in
                                          self._last_window_fitness]
        return out

    def load_state_dict(self, state: dict) -> None:
        """Inverse of :meth:`state_dict` (no-op on an empty/None dict, so
        restoring a pre-upgrade checkpoint degrades to the old behavior
        instead of crashing)."""
        if not state:
            return
        self._rng.bit_generator.state = state["rng"]
        self._fitness_sum = np.asarray(state["fitness_sum"], np.float64)
        self._fitness_n = int(state["fitness_n"])
        self._pending.clear()
        self.history = [
            PBTDecision(
                src=np.asarray(d["src"], np.int64),
                exploited=np.asarray(d["exploited"], bool),
                hparams=HParams(**{k: jnp.asarray(v, jnp.float32)
                                   for k, v in d["hparams"].items()}))
            for d in state["history"]]
        if "last_window_fitness" in state:
            self._last_window_fitness = np.asarray(
                state["last_window_fitness"], np.float64)

    def maybe_update(self, iteration: int, states: Any, hparams: HParams,
                     ) -> tuple[Any, HParams, PBTDecision] | None:
        """After every ``ready_iters`` recorded iterations, run one
        exploit/explore round over the stacked member states. Returns None
        when not due (and then costs no device sync).

        ``iteration`` is accepted for the caller's logging convenience but
        deliberately NOT consulted: readiness depends only on the recorded
        fitness window, which survives checkpoint/resume — a guard on the
        host loop's local index would re-fire differently after a resume
        (the loop restarts at i=0) and break the bit-exact-resume
        contract."""
        if len(self._pending) + self._fitness_n < self.cfg.ready_iters:
            return None
        self._drain()
        fitness = self._fitness_sum / max(self._fitness_n, 1)
        decision = exploit_explore(self._rng, fitness, hparams, self.cfg)
        self._last_window_fitness = fitness
        self._fitness_sum[:] = 0.0
        self._fitness_n = 0
        self.history.append(decision)
        if decision.exploited.any():
            states = gather_members(states, decision.src)
        return states, decision.hparams, decision
