"""Data-parallel training over a device mesh (L5).

Capability parity: SURVEY.md §2 "Distributed comm backend" / §7 step 6 —
the reference's actor-learner gradient sync (NCCL allreduce driven from
torch.distributed) becomes sharding annotations on ONE jitted train step:

- params / optimizer state: replicated (P()),
- env batch (traces, rollout carry): sharded over the ``data`` mesh axis,
- GSPMD auto-partitions the fused rollout scan over local env shards and
  inserts the gradient all-reduce (psum over ICI) where sharded-batch
  gradients meet replicated params — the TPU-native replacement for the
  reference's hand-driven NCCL calls.

The rollout carry's PRNG key is replicated: per-env action sampling is
already independent per batch row, so replicas compute identical updates
(replicated-param invariance is asserted in tests/test_parallel.py).
"""
from __future__ import annotations

from typing import Any, Callable

import jax
from jax.sharding import Mesh

from ..algos.rollout import RolloutCarry
from .mesh import DATA_AXIS, env_sharded, replicated


def carry_sharding_prefix(mesh: Mesh) -> RolloutCarry:
    """RolloutCarry sharding prefix-tree: PRNG key replicated, everything
    env-batched split over ``data``."""
    env = env_sharded(mesh)
    return RolloutCarry(env_state=env, obs=env, mask=env,
                        key=replicated(mesh))


def put_carry(mesh: Mesh, carry: RolloutCarry) -> RolloutCarry:
    env = env_sharded(mesh)
    return RolloutCarry(
        env_state=jax.device_put(carry.env_state, env),
        obs=jax.device_put(carry.obs, env),
        mask=jax.device_put(carry.mask, env),
        key=jax.device_put(carry.key, replicated(mesh)))


def shard_train(mesh: Mesh, train_step: Callable, train_state, carry,
                traces) -> tuple[Callable, Any, RolloutCarry, Any]:
    """Place (state, carry, traces) on the mesh and wrap ``train_step``
    (an UNjitted step from algos.ppo/a2c, axis_name=None) in a jit with
    explicit in/out shardings. Returns (jitted_step, state, carry, traces)
    for the host loop. n_envs must be divisible by the ``data`` axis."""
    n_data = mesh.shape[DATA_AXIS]
    n_envs = int(traces.submit.shape[0])
    if n_envs % n_data != 0:
        raise ValueError(f"n_envs={n_envs} not divisible by data axis "
                         f"size {n_data}")
    env = env_sharded(mesh)
    rep = replicated(mesh)
    carry_sh = carry_sharding_prefix(mesh)
    jitted = jax.jit(train_step,
                     in_shardings=(rep, carry_sh, env, rep),
                     out_shardings=(rep, carry_sh, rep),
                     donate_argnums=(0, 1))
    return (jitted,
            jax.device_put(train_state, rep),
            put_carry(mesh, carry),
            jax.device_put(traces, env))
