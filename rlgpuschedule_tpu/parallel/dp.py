"""Data-parallel training over a device mesh (L5).

Capability parity: SURVEY.md §2 "Distributed comm backend" / §7 step 6 —
the reference's actor-learner gradient sync (NCCL allreduce driven from
torch.distributed) becomes XLA collectives over the mesh, via either of
two equivalent assemblies:

1. **GSPMD** (:func:`shard_train`, the default production path): sharding
   annotations on ONE jitted train step — params/optimizer replicated
   (P()), env batch (traces, rollout carry) sharded over the ``data``
   axis — and GSPMD auto-partitions the fused rollout scan and inserts
   the gradient all-reduce (psum over ICI) where sharded-batch gradients
   meet replicated params. The carry's PRNG key is replicated: action
   sampling is per batch row, so replicas compute identical updates and
   DP matches single-device training bit-for-bit
   (tests/test_parallel.py).
2. **Explicit collectives** (:func:`shard_map_train`): the same step built
   with ``axis_name=DATA_AXIS`` (``lax.pmean`` on gradients and advantage
   moments — algos.ppo/a2c) wrapped in ``shard_map``, the hand-written
   twin of what GSPMD derives. Each shard rolls out its local envs under
   a per-shard PRNG key (decorrelated exploration noise), so this path is
   NOT bit-identical to single-device training — it is the multi-process
   form that generalizes to multi-host meshes where a single GSPMD
   program spans hosts but explicit per-shard control is wanted.
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..algos.rollout import RolloutCarry
from .mesh import DATA_AXIS, data_shard_slices, env_sharded, replicated


def shard_map_compat(fn, mesh, in_specs, out_specs, check: bool = True):
    """``shard_map`` across jax versions: newer jax exposes it at top
    level with a ``check_vma`` kwarg; 0.4/0.5 at
    ``jax.experimental.shard_map`` with the same knob named
    ``check_rep``. The seed imported only the new location, so the whole
    explicit-collective path was an ImportError on the pinned jax."""
    try:
        from jax import shard_map as sm
        kw = {"check_vma": check}
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm
        kw = {"check_rep": check}
    return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def put_global(tree: Any, sharding: NamedSharding) -> Any:
    """``device_put`` every leaf of ``tree`` onto ``sharding``, including
    in MULTI-CONTROLLER runs. Plain ``jax.device_put`` refuses a host
    value destined for a sharding that spans non-addressable devices (the
    multihost mesh — this is what killed the 2-process dryrun's ranks);
    there each process instead contributes its addressable shards of its
    local copy via ``jax.make_array_from_process_local_data``. Leaves
    that are already global (non-fully-addressable) jax.Arrays — e.g.
    traces assembled by ``multihost.global_traces`` — are passed through
    untouched, since their shards cannot be re-placed host-side."""
    import numpy as np

    def put(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            return x
        if sharding.is_fully_addressable:
            return jax.device_put(x, sharding)
        arr = np.asarray(x)
        return jax.make_array_from_process_local_data(
            sharding, arr, arr.shape)

    return jax.tree.map(put, tree)


def carry_sharding_prefix(mesh: Mesh) -> RolloutCarry:
    """RolloutCarry sharding prefix-tree: PRNG key replicated, everything
    env-batched split over ``data``."""
    env = env_sharded(mesh)
    return RolloutCarry(env_state=env, obs=env, mask=env,
                        key=replicated(mesh))


def put_carry(mesh: Mesh, carry: RolloutCarry,
              key_sharding: NamedSharding | None = None) -> RolloutCarry:
    """Mesh-place a carry: env-batched fields over ``data``; the key
    replicated (GSPMD path) unless ``key_sharding`` overrides it (the
    shard_map path stacks per-shard keys over ``data``)."""
    env = env_sharded(mesh)
    return RolloutCarry(
        env_state=put_global(carry.env_state, env),
        obs=put_global(carry.obs, env),
        mask=put_global(carry.mask, env),
        key=put_global(carry.key, key_sharding or replicated(mesh)))


def shrink_env_rows(tree: Any, *, old_n_envs: int, old_world: int,
                    surviving_ranks) -> Any:
    """Shrink-to-fit an env-batched pytree to the surviving data shards:
    every leaf whose leading dim is ``old_n_envs`` keeps ONLY the row
    blocks that lived on ``surviving_ranks`` (contiguous per-shard blocks
    under ``env_sharded``'s layout — ``mesh.data_shard_slices``); leaves
    with any other leading dim (replicated params, PRNG keys, scalars)
    pass through untouched. Host-side numpy op: the shrunk tree is
    re-placed on the new mesh by the caller (``put_global``/``put_carry``
    accept any world size — that is the elastic contract).

    Caveat: "env-batched" is recognized by leading-dim equality, so an
    ``old_n_envs`` that collides with an unrelated leaf's leading dim
    (e.g. 2, a raw PRNG key's length) would mis-slice it — callers keep
    key leaves out of the tree or use batches > 2 (every real config
    does)."""
    import numpy as np

    surv = sorted(set(int(r) for r in surviving_ranks))
    if not surv:
        raise ValueError("shrink_env_rows: no surviving ranks")
    if surv[0] < 0 or surv[-1] >= old_world:
        raise ValueError(f"surviving_ranks {surv} outside the saved world "
                         f"range [0, {old_world})")
    slices = data_shard_slices(old_n_envs, old_world)

    def shrink(x):
        arr = np.asarray(x)
        if arr.ndim >= 1 and arr.shape[0] == old_n_envs:
            return np.concatenate([arr[slices[r]] for r in surv], axis=0)
        return arr

    return jax.tree.map(shrink, tree)


def _check_env_divisible(mesh: Mesh, traces) -> None:
    n_data = mesh.shape[DATA_AXIS]
    n_envs = int(traces.submit.shape[0])
    if n_envs % n_data != 0:
        raise ValueError(f"n_envs={n_envs} not divisible by data axis "
                         f"size {n_data}")


def shard_train(mesh: Mesh, train_step: Callable, train_state, carry,
                traces) -> tuple[Callable, Any, RolloutCarry, Any]:
    """Place (state, carry, traces) on the mesh and wrap ``train_step``
    (an UNjitted step from algos.ppo/a2c, axis_name=None) in a jit with
    explicit in/out shardings. Returns (jitted_step, state, carry, traces)
    for the host loop. n_envs must be divisible by the ``data`` axis."""
    _check_env_divisible(mesh, traces)
    env = env_sharded(mesh)
    rep = replicated(mesh)
    carry_sh = carry_sharding_prefix(mesh)
    jitted = jax.jit(train_step,
                     in_shardings=(rep, carry_sh, env, rep),
                     out_shardings=(rep, carry_sh, rep),
                     donate_argnums=(0, 1))
    return (jitted,
            put_global(train_state, rep),
            put_carry(mesh, carry),
            put_global(traces, env))


def shard_map_train(mesh: Mesh, train_step_axis: Callable, train_state,
                    carry, traces) -> tuple[Callable, Any, RolloutCarry, Any]:
    """Explicit-collective twin of :func:`shard_train` (module docstring
    path 2). ``train_step_axis`` must be built with
    ``axis_name=DATA_AXIS`` (``make_ppo_step``/``make_a2c_step``) so its
    gradient/advantage ``lax.pmean`` calls bind to the mesh axis here.

    The rollout carry's key becomes a per-shard key stack ``[n_data, 2]``
    (split from the original): each shard rolls out under its own key, so
    exploration noise decorrelates across shards instead of repeating the
    replicated key's draws on every shard. Metrics are pmean'd before
    leaving the shard so the host sees one replicated value, same as the
    GSPMD path."""
    _check_env_divisible(mesh, traces)
    n_data = mesh.shape[DATA_AXIS]

    env_spec, rep_spec = P(DATA_AXIS), P()
    carry_spec = RolloutCarry(env_state=env_spec, obs=env_spec,
                              mask=env_spec, key=env_spec)

    def wrapped(state, carry_in, tr, key):
        local = carry_in._replace(key=carry_in.key[0])
        state, local, metrics = train_step_axis(state, local, tr, key)
        metrics = jax.tree.map(
            lambda m: jax.lax.pmean(m, DATA_AXIS), metrics)
        return state, local._replace(key=local.key[None]), metrics

    jitted = jax.jit(shard_map_compat(
        wrapped, mesh=mesh,
        in_specs=(rep_spec, carry_spec, env_spec, rep_spec),
        out_specs=(rep_spec, carry_spec, rep_spec),
        check=False), donate_argnums=(0, 1))

    keys = jax.random.split(jnp.asarray(carry.key), n_data)
    carry = carry._replace(key=keys)
    carry_sh = put_carry(mesh, carry,
                         key_sharding=NamedSharding(mesh, P(DATA_AXIS)))
    return (jitted, jax.device_put(train_state, replicated(mesh)), carry_sh,
            jax.device_put(traces, env_sharded(mesh)))
