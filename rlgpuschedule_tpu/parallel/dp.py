"""Data-parallel training over a device mesh (L5).

Capability parity: SURVEY.md §2 "Distributed comm backend" / §7 step 6 —
the reference's actor-learner gradient sync (NCCL allreduce driven from
torch.distributed) becomes XLA collectives over the mesh, via either of
two equivalent assemblies:

1. **GSPMD** (:func:`shard_train`, the default production path): sharding
   annotations on ONE jitted train step — params/optimizer replicated
   (P()), env batch (traces, rollout carry) sharded over the ``data``
   axis — and GSPMD auto-partitions the fused rollout scan and inserts
   the gradient all-reduce (psum over ICI) where sharded-batch gradients
   meet replicated params. The carry's PRNG key is replicated: action
   sampling is per batch row, so replicas compute identical updates and
   DP matches single-device training bit-for-bit
   (tests/test_parallel.py).
2. **Explicit collectives** (:func:`shard_map_train`): the same step built
   with ``axis_name=DATA_AXIS`` (``lax.pmean`` on gradients and advantage
   moments — algos.ppo/a2c) wrapped in ``shard_map``, the hand-written
   twin of what GSPMD derives. Each shard rolls out its local envs under
   a per-shard PRNG key (decorrelated exploration noise), so this path is
   NOT bit-identical to single-device training — it is the multi-process
   form that generalizes to multi-host meshes where a single GSPMD
   program spans hosts but explicit per-shard control is wanted.
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..algos.rollout import RolloutCarry
from .mesh import DATA_AXIS, env_sharded, replicated
from .sharding import put_global as _put_global
from .sharding import shrink_env_rows_by_rule as _shrink_by_rule


def shard_map_compat(fn, mesh, in_specs, out_specs, check: bool = True):
    """``shard_map`` across jax versions: newer jax exposes it at top
    level with a ``check_vma`` kwarg; 0.4/0.5 at
    ``jax.experimental.shard_map`` with the same knob named
    ``check_rep``. The seed imported only the new location, so the whole
    explicit-collective path was an ImportError on the pinned jax."""
    try:
        from jax import shard_map as sm
        kw = {"check_vma": check}
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm
        kw = {"check_rep": check}
    return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def put_global(tree: Any, sharding: NamedSharding) -> Any:
    """DEPRECATED shim: the implementation moved to
    ``parallel.sharding.put_global`` (the rule engine owns placement).
    Delegates and warns; external callers keep working for one
    release."""
    import warnings

    warnings.warn(
        "parallel.dp.put_global is deprecated; use "
        "parallel.sharding.put_global",
        DeprecationWarning, stacklevel=2)
    return _put_global(tree, sharding)


def carry_sharding_prefix(mesh: Mesh) -> RolloutCarry:
    """RolloutCarry sharding prefix-tree: PRNG key replicated, everything
    env-batched split over ``data``."""
    env = env_sharded(mesh)
    return RolloutCarry(env_state=env, obs=env, mask=env,
                        key=replicated(mesh))


def put_carry(mesh: Mesh, carry: RolloutCarry,
              key_sharding: NamedSharding | None = None) -> RolloutCarry:
    """Mesh-place a carry: env-batched fields over ``data``; the key
    replicated (GSPMD path) unless ``key_sharding`` overrides it (the
    shard_map path stacks per-shard keys over ``data``)."""
    env = env_sharded(mesh)
    return RolloutCarry(
        env_state=_put_global(carry.env_state, env),
        obs=_put_global(carry.obs, env),
        mask=_put_global(carry.mask, env),
        key=_put_global(carry.key, key_sharding or replicated(mesh)))


def shrink_env_rows(tree: Any, *, old_n_envs: int, old_world: int,
                    surviving_ranks) -> Any:
    """DEPRECATED shim: elastic shrink-to-fit moved to
    ``parallel.sharding.shrink_env_rows_by_rule``, which decides per-leaf
    by partition RULE instead of this shim's leading-dim heuristic (the
    documented key-length collision caveat is fixed there by keying PRNG
    keys by name). The shim reproduces the old dim-keyed behavior
    exactly — every leaf treated as data-axis-resident, sliced iff its
    leading dim equals ``old_n_envs`` — and warns."""
    import warnings

    from jax.sharding import PartitionSpec

    warnings.warn(
        "parallel.dp.shrink_env_rows is deprecated; use "
        "parallel.sharding.shrink_env_rows_by_rule with a rule table",
        DeprecationWarning, stacklevel=2)
    return _shrink_by_rule(tree, [(r".*", PartitionSpec(DATA_AXIS))],
                           old_n_envs=old_n_envs, old_world=old_world,
                           surviving_ranks=surviving_ranks)


def _check_env_divisible(mesh: Mesh, traces) -> None:
    n_data = mesh.shape[DATA_AXIS]
    n_envs = int(traces.submit.shape[0])
    if n_envs % n_data != 0:
        raise ValueError(f"n_envs={n_envs} not divisible by data axis "
                         f"size {n_data}")


def shard_train(mesh: Mesh, train_step: Callable, train_state, carry,
                traces) -> tuple[Callable, Any, RolloutCarry, Any]:
    """Place (state, carry, traces) on the mesh and wrap ``train_step``
    (an UNjitted step from algos.ppo/a2c, axis_name=None) in a jit with
    explicit in/out shardings. Returns (jitted_step, state, carry, traces)
    for the host loop. n_envs must be divisible by the ``data`` axis."""
    _check_env_divisible(mesh, traces)
    env = env_sharded(mesh)
    rep = replicated(mesh)
    carry_sh = carry_sharding_prefix(mesh)
    jitted = jax.jit(train_step,
                     in_shardings=(rep, carry_sh, env, rep),
                     out_shardings=(rep, carry_sh, rep),
                     donate_argnums=(0, 1))
    return (jitted,
            _put_global(train_state, rep),
            put_carry(mesh, carry),
            _put_global(traces, env))


def shard_map_train(mesh: Mesh, train_step_axis: Callable, train_state,
                    carry, traces) -> tuple[Callable, Any, RolloutCarry, Any]:
    """Explicit-collective twin of :func:`shard_train` (module docstring
    path 2). ``train_step_axis`` must be built with
    ``axis_name=DATA_AXIS`` (``make_ppo_step``/``make_a2c_step``) so its
    gradient/advantage ``lax.pmean`` calls bind to the mesh axis here.

    The rollout carry's key becomes a per-shard key stack ``[n_data, 2]``
    (split from the original): each shard rolls out under its own key, so
    exploration noise decorrelates across shards instead of repeating the
    replicated key's draws on every shard. Metrics are pmean'd before
    leaving the shard so the host sees one replicated value, same as the
    GSPMD path."""
    from ..configs import validate_mode_combination
    # shard_map is a build-path mode with no CLI flag, so its refusal
    # rows are enforced here, at the mode's activation site. The
    # companion modes are False by construction on this path: the
    # shard_map build is the synchronous single-policy loop (no async
    # engine, no PBT controller), takes the whole train step (no fused
    # chunk), and IS the explicit-collective alternative to the GSPMD
    # --mesh build.
    validate_mode_combination({"shard_map": True, "pbt": False,
                               "async": False, "fused_chunk": False,
                               "mesh": False})
    _check_env_divisible(mesh, traces)
    n_data = mesh.shape[DATA_AXIS]

    env_spec, rep_spec = P(DATA_AXIS), P()
    carry_spec = RolloutCarry(env_state=env_spec, obs=env_spec,
                              mask=env_spec, key=env_spec)

    def wrapped(state, carry_in, tr, key):
        local = carry_in._replace(key=carry_in.key[0])
        state, local, metrics = train_step_axis(state, local, tr, key)
        metrics = jax.tree.map(
            lambda m: jax.lax.pmean(m, DATA_AXIS), metrics)
        return state, local._replace(key=local.key[None]), metrics

    jitted = jax.jit(shard_map_compat(
        wrapped, mesh=mesh,
        in_specs=(rep_spec, carry_spec, env_spec, rep_spec),
        out_specs=(rep_spec, carry_spec, rep_spec),
        check=False), donate_argnums=(0, 1))

    keys = jax.random.split(jnp.asarray(carry.key), n_data)
    carry = carry._replace(key=keys)
    carry_sh = put_carry(mesh, carry,
                         key_sharding=NamedSharding(mesh, P(DATA_AXIS)))
    return (jitted, jax.device_put(train_state, replicated(mesh)), carry_sh,
            jax.device_put(traces, env_sharded(mesh)))
