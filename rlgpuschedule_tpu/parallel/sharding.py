"""Regex-keyed partition-rule sharding engine (L5).

One rule table per model family maps parameter *names* (the '/'-joined
pytree path, flax-style: ``params/params/Dense_0/kernel``) to
``PartitionSpec``s over the unified ``Mesh(pop × data × model)``
(:func:`mesh.make_unified_mesh`). The style is the battle-tested
EasyLM/levanter idiom (SNIPPETS.md [1]/[3]):

- :func:`match_partition_rules` walks any pytree, names each leaf by its
  path, and returns the first-matching rule's spec (``re.search``, order
  matters). Scalars and size-1 leaves short-circuit to ``P()`` —
  optimizer step counters never need a rule. A leaf no rule matches is a
  hard error, so a new parameter cannot silently default to the wrong
  layout. The shipped tables end in an explicit ``(".*", P())``
  replicate catch-all; tests assert each family's params are fully
  covered *before* the catch-all.
- Because optimizer state mirrors parameter paths (``opt_state/1/mu/
  params/Dense_0/kernel``), the same rules shard Adam moments with zero
  extra configuration — that is why matching uses ``re.search`` rather
  than full-path equality.
- :func:`make_shard_and_gather_fns` turns a spec tree into per-leaf
  place/fetch callables for checkpoint restore paths that must not
  materialize the full tree on one device.

Constraint helpers: jax 0.4 has no ambient-mesh context for
``with_sharding_constraint``, so :func:`bind_mesh` wraps a step function
and installs the mesh for the duration of its *trace*; :func:`constrain`
is then an identity outside any bound mesh and a
``lax.with_sharding_constraint`` inside one. Library code (e.g. the
rollout's trajectory stack) calls ``constrain`` unconditionally and
mesh-free callers pay nothing.

Elastic restore: :func:`shrink_env_rows_by_rule` replaces
``dp.shrink_env_rows``'s leading-dim heuristic — leaves are shrunk iff
their *rule* puts them on the data axis, so a PRNG key whose length
happens to equal ``old_n_envs`` can no longer be mis-sliced (the caveat
documented on the old path is fixed by construction).
"""
from __future__ import annotations

import hashlib
import re
import threading
from contextlib import contextmanager
from typing import Any, Callable

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, MODEL_AXIS, POP_AXIS, data_shard_slices

# A rule table: ordered (regex, PartitionSpec) pairs, first re.search
# match wins. Specs name axes of the unified mesh.
Rules = list[tuple[str, P]]


# --------------------------------------------------------------------------
# Named tree walking
# --------------------------------------------------------------------------

def _key_name(k) -> str:
    """One path entry -> its bare name (dict key, attr name, or index)."""
    if hasattr(k, "key"):
        return str(k.key)
    if hasattr(k, "name"):
        return str(k.name)
    if hasattr(k, "idx"):
        return str(k.idx)
    return str(k)


def named_tree_map(fn: Callable[[str, Any], Any], tree: Any,
                   sep: str = "/") -> Any:
    """``jax.tree.map`` with the leaf's '/'-joined path as first arg."""
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = [fn(sep.join(_key_name(k) for k in path), leaf)
           for path, leaf in paths_leaves]
    return jax.tree_util.tree_unflatten(treedef, out)


def tree_leaf_names(tree: Any, sep: str = "/") -> list[str]:
    """The '/'-joined path of every leaf, in flatten order."""
    paths_leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [sep.join(_key_name(k) for k in path) for path, _ in paths_leaves]


# --------------------------------------------------------------------------
# Rule matching
# --------------------------------------------------------------------------

def match_rule(rules: Rules, name: str) -> P:
    """First rule whose regex ``re.search``-matches ``name``. Raises if
    none does — a silent default is how a new param ends up replicated
    when it should be sharded (or vice versa)."""
    for pattern, spec in rules:
        if re.search(pattern, name):
            return spec
    raise ValueError(f"Partition rule not found for param: {name!r}")


def match_partition_rules(rules: Rules, tree: Any) -> Any:
    """Resolve a PartitionSpec for every leaf of ``tree`` by name.
    Scalars and size-1 leaves (step counters, EMA scalars) get ``P()``
    without consulting the table."""
    def get_spec(name: str, leaf: Any) -> P:
        ndim = getattr(leaf, "ndim", np.ndim(leaf))
        size = getattr(leaf, "size", np.size(leaf))
        if ndim == 0 or size == 1:
            return P()
        return match_rule(rules, name)
    return named_tree_map(get_spec, tree)


def prune_spec(spec: P, mesh: Mesh) -> P:
    """Drop axis names ``mesh`` does not carry. The rule tables name all
    three unified axes; a caller-supplied legacy mesh (e.g. a bare
    pop x data test mesh) then gets those dims replicated instead of a
    hard "resource axis not found" error — on such a mesh that is the
    same layout the wholesale pre-rule shardings produced."""
    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a in mesh.shape)
            if not kept:
                return None
            return kept[0] if len(kept) == 1 else kept
        return entry if entry in mesh.shape else None
    kept = [keep(e) for e in spec]
    while kept and kept[-1] is None:   # P('pop', None, None) == P('pop')
        kept.pop()
    return P(*kept)


def tree_shardings(tree: Any, rules: Rules, mesh: Mesh) -> Any:
    """Rule-resolved ``NamedSharding`` tree for ``tree`` on ``mesh``."""
    specs = match_partition_rules(rules, tree)
    return jax.tree.map(lambda s: NamedSharding(mesh, prune_spec(s, mesh)),
                        specs)


def rule_table_hash(rules: Rules) -> str:
    """Stable short fingerprint of a rule table — recorded by bench.py so
    two benchmark JSONs are comparable only when their layouts were."""
    text = "|".join(f"{pat}=>{tuple(spec)}" for pat, spec in rules)
    return hashlib.sha256(text.encode()).hexdigest()[:12]


# --------------------------------------------------------------------------
# Per-model-family rule tables
# --------------------------------------------------------------------------
# Head kernels [hidden, n_actions] shard the *input* dim on model (the
# output dim is tiny: n_actions or 1); encoder Dense kernels [in, out]
# shard the output dim (megatron column split); Conv kernels [H, W, Cin,
# Cout] shard output channels. Biases / LayerNorm scales are small —
# replicate. On a model axis of size 1 all of this degrades to exact
# replication (the bit-identity tests pin that).

_HEADS = r"(^|/)((slot_|preempt_|noop_|top_|pod_)?policy|value)/kernel$"

FLAT_RULES: Rules = [
    (_HEADS, P(MODEL_AXIS, None)),
    (r"Dense_\d+/kernel$", P(None, MODEL_AXIS)),
    (r"LayerNorm_\d+/(scale|bias)$", P()),
    (r"(^|/)bias$", P()),
    (r".*", P()),
]

GRID_RULES: Rules = [
    (_HEADS, P(MODEL_AXIS, None)),
    (r"Conv_\d+/kernel$", P(None, None, None, MODEL_AXIS)),
    (r"Dense_\d+/kernel$", P(None, MODEL_AXIS)),
    (r"LayerNorm_\d+/(scale|bias)$", P()),
    (r"(^|/)bias$", P()),
    (r".*", P()),
]

# GNN encoder is Dense+LayerNorm message passing; hier is two MLP trunks
# + three Dense heads — both are the flat table's patterns.
GRAPH_RULES: Rules = FLAT_RULES
HIER_RULES: Rules = FLAT_RULES

RULE_TABLES: dict[str, Rules] = {
    "flat": FLAT_RULES,
    "grid": GRID_RULES,
    "graph": GRAPH_RULES,
    "hier": HIER_RULES,
}


def rules_for(cfg) -> Rules:
    """The rule table for an ExperimentConfig's model family."""
    if getattr(cfg, "n_pods", 1) > 1:
        return RULE_TABLES["hier"]
    return RULE_TABLES[cfg.obs_kind]


# --------------------------------------------------------------------------
# Placement (subsumes dp.put_global)
# --------------------------------------------------------------------------

def put_global(tree: Any, sharding: NamedSharding) -> Any:
    """``device_put`` every leaf of ``tree`` onto ``sharding``, including
    in MULTI-CONTROLLER runs. Plain ``jax.device_put`` refuses a host
    value destined for a sharding that spans non-addressable devices (the
    multihost mesh); there each process instead contributes its
    addressable shards of its local copy via
    ``jax.make_array_from_process_local_data``. Leaves that are already
    global (non-fully-addressable) jax.Arrays — e.g. traces assembled by
    ``multihost.global_traces`` — pass through untouched, since their
    shards cannot be re-placed host-side."""
    def put(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            return x
        if sharding.is_fully_addressable:
            return jax.device_put(x, sharding)
        arr = np.asarray(x)
        return jax.make_array_from_process_local_data(
            sharding, arr, arr.shape)

    return jax.tree.map(put, tree)


def put_tree(tree: Any, shardings: Any) -> Any:
    """Per-leaf :func:`put_global` against a matching tree of
    ``NamedSharding``s (what :func:`tree_shardings` returns)."""
    return jax.tree.map(put_global, tree, shardings)


def make_shard_and_gather_fns(specs: Any, mesh: Mesh
                              ) -> tuple[Any, Any]:
    """Per-leaf (shard_fn, gather_fn) trees for a spec tree: ``shard_fn``
    places a host leaf on its rule-resolved sharding (multihost-safe);
    ``gather_fn`` fetches a placed leaf back to one host numpy array.
    Restore paths apply shard_fns leaf-by-leaf so a big tree never has to
    exist fully replicated on one device."""
    def make_shard(spec):
        sh = NamedSharding(mesh, spec)
        return lambda x: jax.tree.leaves(put_global(x, sh))[0]

    def make_gather(_spec):
        return lambda x: np.asarray(jax.device_get(x))  # jsan: disable=host-sync -- gather_fns ARE the host materialization step (checkpoint save path), never traced

    shard_fns = jax.tree.map(make_shard, specs)
    gather_fns = jax.tree.map(make_gather, specs)
    return shard_fns, gather_fns


# --------------------------------------------------------------------------
# with_sharding_constraint helpers (trace-scoped ambient mesh)
# --------------------------------------------------------------------------

_ACTIVE = threading.local()


def active_mesh() -> Mesh | None:
    """The mesh bound by the innermost :func:`use_mesh`/:func:`bind_mesh`
    on this thread, or None."""
    return getattr(_ACTIVE, "mesh", None)


@contextmanager
def use_mesh(mesh: Mesh | None):
    prev = active_mesh()
    _ACTIVE.mesh = mesh
    try:
        yield mesh
    finally:
        _ACTIVE.mesh = prev


def bind_mesh(fn: Callable, mesh: Mesh) -> Callable:
    """Wrap ``fn`` so the mesh is active while it runs. Under ``jax.jit``
    the wrapper body executes at TRACE time, which is exactly when
    :func:`constrain` needs the mesh — so only steps built against a mesh
    get constraints baked into their jaxpr, deterministically."""
    def bound(*args, **kwargs):
        with use_mesh(mesh):
            return fn(*args, **kwargs)
    return bound


def constrain(x: Any, *axes) -> Any:
    """``with_sharding_constraint`` against the active mesh, or identity
    when no mesh is bound (single-device and legacy dp paths trace the
    very same code with zero overhead). ``axes`` are PartitionSpec
    entries for the leading dims; trailing dims are unconstrained."""
    mesh = active_mesh()
    if mesh is None:
        return x
    sh = NamedSharding(mesh, P(*axes))
    return jax.lax.with_sharding_constraint(x, sh)


def constrain_tree(tree: Any, *axes) -> Any:
    """:func:`constrain` every leaf of a pytree with the same spec."""
    return jax.tree.map(lambda x: constrain(x, *axes), tree)


# --------------------------------------------------------------------------
# Elastic restore by rule (subsumes dp.shrink_env_rows)
# --------------------------------------------------------------------------

# What lives in an elastic checkpoint's "extra" tree: rollout carry +
# trajectory leaves are env-batched; PRNG keys are replicated state and
# MUST NOT be row-sliced — keyed by NAME, not by a leading-dim
# coincidence.
ELASTIC_EXTRA_RULES: Rules = [
    (r"(^|/)keys?$", P()),
    (r".*", P(DATA_AXIS)),
]


def shrink_env_rows_by_rule(tree: Any, rules: Rules, *, old_n_envs: int,
                            old_world: int, surviving_ranks) -> Any:
    """Shrink-to-fit an env-batched pytree to the surviving data shards,
    deciding per-leaf by RULE: a leaf is sliced iff its matched spec puts
    the leading dim on the data axis AND the leading dim equals
    ``old_n_envs`` (geometry sanity; replicated-by-rule leaves pass
    through whole regardless of shape). Row blocks follow
    ``mesh.data_shard_slices`` — the same contiguous layout
    ``env_sharded`` places, which is what makes "rows that lived on
    surviving ranks" well-defined. Host-side numpy; the caller re-places
    the shrunk tree on the new mesh (:func:`put_global`)."""
    surv = sorted(set(int(r) for r in surviving_ranks))
    if not surv:
        raise ValueError("shrink_env_rows_by_rule: no surviving ranks")
    if surv[0] < 0 or surv[-1] >= old_world:
        raise ValueError(f"surviving_ranks {surv} outside the saved world "
                         f"range [0, {old_world})")
    slices = data_shard_slices(old_n_envs, old_world)
    specs = match_partition_rules(rules, tree)

    def shrink(spec, x):
        arr = np.asarray(x)
        on_data = len(spec) > 0 and spec[0] == DATA_AXIS
        if on_data and arr.ndim >= 1 and arr.shape[0] == old_n_envs:
            return np.concatenate([arr[slices[r]] for r in surv], axis=0)
        return arr

    return jax.tree.map(shrink, specs, tree)
