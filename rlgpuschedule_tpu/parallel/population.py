"""Population training over the ``pop`` mesh axis (L5) — the substrate for
PBT / hierarchical config 5.

Capability parity: SURVEY.md §2 "PBT controller" / §2 "Parallelism
strategies — Population parallelism": the reference trains population
members as separate processes exchanging weights over NCCL; here the whole
population is ONE jitted program — the member train step is ``vmap``-ped
over a stacked member axis and the stack is sharded over the mesh's ``pop``
axis, so each pod slice trains its members locally and the only cross-pod
traffic is the rare PBT exploit weight copy (a gather over ``pop``, riding
DCN in a real multi-slice deployment — SURVEY.md §5 "Distributed
communication backend").

Per-member hyperparameters (lr, entropy coef, clip eps) are **traced
scalars** (:class:`HParams`), not Python config constants — PBT's explore
step rewrites them between iterations without recompiling, and one compiled
step serves every member. The learning rate is applied manually after
``scale_by_adam`` for the same reason (optimizer state holds no lr).
"""
from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..algos.ppo import (PPOConfig, PPOMetrics, compute_advantages,
                         run_ppo_epochs)
from ..algos.rollout import PolicyApply, RolloutCarry, rollout
from ..env.env import EnvParams
from .mesh import Mesh, env_sharded, pop_env_sharded, pop_sharded


class HParams(NamedTuple):
    """PBT-explorable hyperparameters — traced f32 scalars (stacked [P]
    across the population)."""
    lr: jax.Array
    ent_coef: jax.Array
    clip_eps: jax.Array


# Legal range per hyperparameter; initial sampling and PBT explore both
# clip to these.
HPARAM_BOUNDS: dict[str, tuple[float, float]] = {
    "lr": (1e-5, 1e-2),
    "ent_coef": (1e-4, 0.3),
    "clip_eps": (0.05, 0.5),
}


class MemberState(NamedTuple):
    """One member's learnable state (stacked [P, ...] across the
    population). Plain pytree (not flax TrainState) because the lr lives in
    :class:`HParams`, not in the optimizer."""
    params: Any
    opt_state: optax.OptState
    step: jax.Array


def make_member_tx(config: PPOConfig) -> optax.GradientTransformation:
    """Adam preconditioner without a learning rate — the per-member lr is
    applied by the member step from traced ``HParams``."""
    return optax.chain(optax.clip_by_global_norm(config.max_grad_norm),
                       optax.scale_by_adam(eps=1e-5))


def init_member(net, key: jax.Array, example_obs, example_mask,
                config: PPOConfig, extra_apply_args: tuple = ()) -> MemberState:
    params = net.init(key, example_obs, *extra_apply_args, example_mask)
    tx = make_member_tx(config)
    return MemberState(params=params, opt_state=tx.init(params),
                       step=jnp.int32(0))


def make_member_learn_step(apply_fn: PolicyApply,
                           config: PPOConfig) -> Callable:
    """The learn half of one member's PPO iteration with traced
    hyperparameters: (member_state, tr, last_value, key, hp) ->
    (member_state', metrics). Advantage targets come from the shared
    fused pipeline (``algos.ppo.compute_advantages``) — so a population
    config with ``correction="vtrace"`` gets per-member importance
    correction, which is what makes the async PBT engine's deep
    staleness bounds safe. The update core is
    ``algos.ppo.run_ppo_epochs`` with hp.{clip_eps, ent_coef} fed into
    the loss and hp.lr applied to the adam-preconditioned updates (so
    optax.adam == scale_by_adam + our scale is preserved exactly when hp
    matches the config). Split out of :func:`make_member_step` so the
    async engine can vmap/compile it alone on the learner group —
    identical code on both paths, same factoring contract as
    ``algos.ppo.make_learn_step``."""
    tx = make_member_tx(config)
    if config.reward_norm:
        raise ValueError(
            "reward_norm is not supported in the PBT population: "
            "MemberState carries no reward_stats (per-member streaming "
            "moments would make fitness incomparable across members)")

    def member_learn_step(state: MemberState, tr, last_value: jax.Array,
                          key: jax.Array, hp: HParams):
        state, advantages, returns, rho_stats = compute_advantages(
            apply_fn, config, state, tr, last_value)

        def apply_grads(state: MemberState, grads) -> MemberState:
            updates, opt_state = tx.update(grads, state.opt_state,
                                           state.params)
            updates = jax.tree.map(lambda u: -hp.lr * u, updates)
            return MemberState(
                params=optax.apply_updates(state.params, updates),
                opt_state=opt_state, step=state.step + 1)

        state, metrics = run_ppo_epochs(
            apply_fn, config, state, tr, advantages, returns, key,
            apply_grads, clip_eps=hp.clip_eps, ent_coef=hp.ent_coef,
            rho_stats=rho_stats)
        return state, metrics

    return member_learn_step


def make_member_step(apply_fn: PolicyApply, env_params: EnvParams,
                     config: PPOConfig) -> Callable:
    """One member's full PPO iteration:
    (member_state, carry, traces, key, hp) -> (member_state', carry',
    metrics) — the rollout composed with :func:`make_member_learn_step`."""
    learn = make_member_learn_step(apply_fn, config)

    def member_step(state: MemberState, carry: RolloutCarry, traces,
                    key: jax.Array, hp: HParams, faults=None):
        carry, tr, last_value = rollout(apply_fn, state.params, env_params,
                                        traces, carry, config.n_steps,
                                        faults)
        state, metrics = learn(state, tr, last_value, key, hp)
        return state, carry, metrics

    return member_step


def make_population_step(apply_fn: PolicyApply, env_params: EnvParams,
                         config: PPOConfig,
                         with_faults: bool = False) -> Callable:
    """vmap the member step over the stacked population axis:
    (states[P], carries[P], traces, keys[P], hps[P][, faults[P, E]]) ->
    (states', carries', metrics[P]).

    ``traces`` is NOT stacked per member (``in_axes=None``): every member
    trains on the same env windows (PBT fitness must be comparable), so the
    trace lives once — replicated across ``pop``, env-sharded over
    ``data``. Fault schedules, by contrast, ARE member-stacked
    (``with_faults``): each member draws its own per-env schedules
    (seeded (seed, member, env)), so the population covers the fault
    distribution P×E-wide while fitness stays comparable in expectation
    (same regime, independent draws)."""
    member = make_member_step(apply_fn, env_params, config)
    if with_faults:
        return jax.vmap(member, in_axes=(0, 0, None, 0, 0, 0))
    return jax.vmap(member, in_axes=(0, 0, None, 0, 0))


def member_stack_specs(stacked_states: MemberState, rules) -> Any:
    """Per-leaf PartitionSpecs for a stacked ``[P, ...]`` member tree: the
    member axis maps onto ``pop`` and each leaf's *within-member* layout
    comes from the partition-rule table (``parallel.sharding``), matched
    on the '/'-joined leaf path — so a CNN/GNN population shards its
    kernels over ``model`` exactly like the single-run path does, one
    rule table for both. Leaves with no within-member extent beyond the
    stack axis (step counters, stacked scalars like Adam's ``count``) get
    plain ``P(pop)``."""
    from jax.sharding import PartitionSpec as P

    from . import sharding as shardlib
    from .mesh import POP_AXIS

    def spec_for(name: str, leaf: Any) -> P:
        if getattr(leaf, "ndim", np.ndim(leaf)) <= 1:
            return P(POP_AXIS)
        inner = shardlib.match_rule(rules, name)
        return P(POP_AXIS, *inner)

    return shardlib.named_tree_map(spec_for, stacked_states)


def population_shardings(mesh: Mesh, states: MemberState | None = None,
                         rules=None):
    """(member_state, carry, traces, keys, hps) shardings: member axis over
    ``pop``, env axis over ``data`` — gradients never cross members, so the
    only collective GSPMD inserts is the per-member env-batch reduction
    within a ``pop`` row. Traces carry no member axis (see
    make_population_step): env axis over ``data``, replicated over
    ``pop``.

    With ``states`` + ``rules`` given, the member-state sharding is
    resolved per-leaf from the partition-rule table
    (:func:`member_stack_specs`) instead of wholesale ``P(pop)`` — on a
    model axis of size 1 the two are the same layout."""
    from jax.sharding import NamedSharding

    from . import sharding as shardlib

    pop = pop_sharded(mesh)
    pop_env = pop_env_sharded(mesh)
    if states is not None and rules is not None:
        specs = member_stack_specs(states, rules)
        state = jax.tree.map(
            lambda s: NamedSharding(mesh, shardlib.prune_spec(s, mesh)),
            specs)
    else:
        state = MemberState(params=pop, opt_state=pop, step=pop)
    carry = RolloutCarry(env_state=pop_env, obs=pop_env, mask=pop_env,
                         key=pop)
    hp = HParams(lr=pop, ent_coef=pop, clip_eps=pop)
    return state, carry, env_sharded(mesh), pop, hp


def jit_population_step(mesh: Mesh, pop_step: Callable,
                        states: MemberState | None = None,
                        rules=None, with_faults: bool = False) -> Callable:
    state_sh, carry_sh, trace_sh, key_sh, hp_sh = population_shardings(
        mesh, states, rules)
    in_sh = (state_sh, carry_sh, trace_sh, key_sh, hp_sh)
    if with_faults:
        # per-member [P, E] schedule stacks lay out like the carries:
        # member axis over pop, env axis over data
        in_sh = in_sh + (pop_env_sharded(mesh),)
    metrics_sh = jax.tree.map(lambda _: pop_sharded(mesh),
                              PPOMetrics(*[0.0] * len(PPOMetrics._fields)))
    return jax.jit(pop_step,
                   in_shardings=in_sh,
                   out_shardings=(state_sh, carry_sh, metrics_sh),
                   donate_argnums=(0, 1))


def stack_members(members: list) -> Any:
    """Stack per-member pytrees into one [P, ...] pytree."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *members)


def sample_hparams(base: PPOConfig, n_pop: int, seed: int,
                   spread: float = 3.0) -> HParams:
    """Initial population hyperparameters: log-uniform over
    [base/spread, base*spread] around the config values (standard PBT
    initialization), clipped to HPARAM_BOUNDS. Returns stacked [P] arrays."""
    rng = np.random.default_rng(seed)

    def draw(name: str, center: float) -> jnp.ndarray:
        lo, hi = np.log(center / spread), np.log(center * spread)
        vals = np.exp(rng.uniform(lo, hi, size=n_pop)).astype(np.float32)
        return jnp.asarray(np.clip(vals, *HPARAM_BOUNDS[name]))

    return HParams(lr=draw("lr", base.lr),
                   ent_coef=draw("ent_coef", base.ent_coef),
                   clip_eps=draw("clip_eps", base.clip_eps))
