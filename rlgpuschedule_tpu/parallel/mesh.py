"""Device-mesh construction and sharding helpers (L5).

Capability parity: SURVEY.md §2 "Distributed comm backend" — the
reference's NCCL process groups become a `jax.sharding.Mesh`; collectives
are inserted by XLA (GSPMD) from sharding annotations and ride ICI within
a slice (SURVEY.md §5 "Distributed communication backend"). Axes:

- ``data``: env-batch / gradient data parallelism (allreduce → psum).
- ``pop``:  population members (PBT) / pods (hierarchical config 5);
  laid out on the *outer* mesh dim so cross-member traffic (rare:
  exploit/explore weight copies) maps to the slower links and the
  per-step gradient psum stays on the inner, fastest ICI loop.
- ``model``: parameter/optimizer sharding for encoders that outgrow one
  chip (the partition-rule tables in ``parallel.sharding`` name this
  axis); innermost so the per-matmul allreduce rides the fastest links.

:func:`make_mesh` (2-axis, legacy) is kept for the hand-wired dp path;
:func:`make_unified_mesh` is the ONE ``Mesh(pop × data × model)`` every
entry point — train, PBT, async groups, serve — now resolves placements
from.
"""
from __future__ import annotations


import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
POP_AXIS = "pop"
MODEL_AXIS = "model"


def make_mesh(n_devices: int | None = None, n_pop: int = 1,
              devices=None) -> Mesh:
    """(pop, data) mesh over the available devices. ``n_pop`` must divide
    the device count; n_pop=1 is plain data parallelism."""
    devices = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    if n % n_pop != 0:
        raise ValueError(f"{n} devices not divisible by n_pop={n_pop}")
    arr = np.asarray(devices).reshape(n_pop, n // n_pop)
    return Mesh(arr, (POP_AXIS, DATA_AXIS))


def make_unified_mesh(n_pop: int = 1, n_model: int = 1,
                      devices=None) -> Mesh:
    """The shared 3-axis ``Mesh(pop × data × model)``. ``n_pop`` and
    ``n_model`` must tile the device count; the data axis absorbs the
    rest. Axis order is (pop, data, model): population traffic (rare) on
    the outer/slowest links, the model axis's per-matmul collectives on
    the inner/fastest. Size-1 axes cost nothing — specs naming them
    degrade to replication — so a plain DP run and a model-sharded run
    share one mesh type and one rule table."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n_pop < 1 or n_model < 1:
        raise ValueError(f"mesh axes must be >= 1, got n_pop={n_pop}, "
                         f"n_model={n_model}")
    if n % (n_pop * n_model) != 0:
        raise ValueError(f"{n} devices not divisible by n_pop={n_pop} * "
                         f"n_model={n_model}")
    n_data = n // (n_pop * n_model)
    arr = np.asarray(devices).reshape(n_pop, n_data, n_model)
    return Mesh(arr, (POP_AXIS, DATA_AXIS, MODEL_AXIS))


_UNIFIED_CACHE: dict[tuple, Mesh] = {}


def unified_mesh(n_pop: int = 1, n_model: int = 1) -> Mesh:
    """Process-wide cached :func:`make_unified_mesh` over ALL visible
    devices — the "constructed once" mesh the entry points share. Cached
    per axis shape so train, async groups, and serve resolving the same
    geometry get the *same* Mesh object (submesh/device identity checks
    stay cheap and exact)."""
    key = (n_pop, n_model, jax.device_count())
    if key not in _UNIFIED_CACHE:
        _UNIFIED_CACHE[key] = make_unified_mesh(n_pop, n_model)
    return _UNIFIED_CACHE[key]


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def env_sharded(mesh: Mesh) -> NamedSharding:
    """Leading env axis split over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def pop_sharded(mesh: Mesh) -> NamedSharding:
    """Leading population axis split over the pop axis."""
    return NamedSharding(mesh, P(POP_AXIS))


def pop_env_sharded(mesh: Mesh) -> NamedSharding:
    """[pop, env, ...] arrays: population × env-batch."""
    return NamedSharding(mesh, P(POP_AXIS, DATA_AXIS))


def serve_devices(mesh: Mesh | None = None) -> list:
    """The per-engine device walk for multi-engine serving (PR 13): one
    serving slot per DATA-axis coordinate of the unified mesh, at
    ``pop=0``/``model=0`` — the same column the single-engine path's
    "first device" came from, generalized along the axis that carries
    request-batch parallelism. A deployment that pins the unified mesh
    to a chip subset moves the whole engine fleet with it.

    ``model > 1`` serving (one engine spanning a model-axis column)
    is refused for now rather than silently serving from a single
    shard of a sharded parameter layout."""
    mesh = mesh if mesh is not None else unified_mesh()
    if MODEL_AXIS in mesh.axis_names and mesh.shape[MODEL_AXIS] > 1:
        raise ValueError(
            f"serve_devices: model axis size {mesh.shape[MODEL_AXIS]} "
            f"> 1 — per-engine serving resolves one device per data-"
            f"axis slot and would serve from one shard of a model-"
            f"sharded layout; model-parallel serving engines are not "
            f"wired yet")
    arr = mesh.devices
    # (pop, data, model) unified layout; tolerate the legacy 2-axis
    # (pop, data) mesh the dp shims still build
    if arr.ndim == 3:
        return list(arr[0, :, 0])
    if arr.ndim == 2:
        return list(arr[0, :])
    return list(arr.reshape(-1))


def data_shard_slices(n_rows: int, n_shards: int) -> list[slice]:
    """The contiguous row block each of ``n_shards`` equal data shards
    owns in a ``[n_rows, ...]`` env-batched array under ``env_sharded``'s
    layout (shard r ↦ rows ``[r*per, (r+1)*per)``). This is the mapping
    elastic recovery relies on to identify which rows SURVIVE when a
    shard's host is lost, so it lives here next to the sharding it
    mirrors. Raises on a ragged split — the same tileability contract
    ``dp.shard_train`` enforces."""
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    if n_rows % n_shards:
        raise ValueError(f"n_rows={n_rows} not divisible by "
                         f"n_shards={n_shards}")
    per = n_rows // n_shards
    return [slice(r * per, (r + 1) * per) for r in range(n_shards)]
