"""L5 distributed execution: device mesh, data-parallel training,
population-based training (PBT)."""
from .mesh import (make_mesh, replicated, env_sharded, pop_sharded,
                   pop_env_sharded, DATA_AXIS, POP_AXIS)
from .dp import (shard_train, shard_map_train, carry_sharding_prefix,
                 put_carry)
from .groups import DeviceGroups, split_devices, parse_group_spec
from .population import (HParams, MemberState, init_member,
                         make_member_step, make_population_step,
                         jit_population_step, population_shardings,
                         sample_hparams, stack_members)
from .pbt import (PBTConfig, PBTController, PBTDecision, exploit_explore,
                  gather_members, HPARAM_BOUNDS)

__all__ = [
    "make_mesh", "replicated", "env_sharded", "pop_sharded",
    "pop_env_sharded", "DATA_AXIS", "POP_AXIS",
    "shard_train", "shard_map_train", "carry_sharding_prefix", "put_carry",
    "DeviceGroups", "split_devices", "parse_group_spec",
    "HParams", "MemberState", "init_member", "make_member_step",
    "make_population_step", "jit_population_step", "population_shardings",
    "sample_hparams", "stack_members",
    "PBTConfig", "PBTController", "PBTDecision", "exploit_explore",
    "gather_members", "HPARAM_BOUNDS",
]
