"""L5 distributed execution: device mesh, partition-rule sharding engine,
data-parallel training, population-based training (PBT)."""
from .mesh import (make_mesh, make_unified_mesh, unified_mesh, replicated,
                   env_sharded, pop_sharded, pop_env_sharded, DATA_AXIS,
                   POP_AXIS, MODEL_AXIS)
from .sharding import (match_partition_rules, match_rule, named_tree_map,
                       tree_shardings, make_shard_and_gather_fns,
                       put_global, put_tree, rules_for, rule_table_hash,
                       RULE_TABLES, constrain, constrain_tree, bind_mesh,
                       use_mesh, active_mesh, shrink_env_rows_by_rule,
                       ELASTIC_EXTRA_RULES)
from .dp import (shard_train, shard_map_train, carry_sharding_prefix,
                 put_carry)
from .groups import DeviceGroups, split_devices, split_mesh, parse_group_spec
from .population import (HParams, MemberState, init_member,
                         make_member_step, make_population_step,
                         jit_population_step, population_shardings,
                         member_stack_specs, sample_hparams, stack_members)
from .pbt import (PBTConfig, PBTController, PBTDecision, exploit_explore,
                  gather_members, HPARAM_BOUNDS)

__all__ = [
    "make_mesh", "make_unified_mesh", "unified_mesh", "replicated",
    "env_sharded", "pop_sharded", "pop_env_sharded", "DATA_AXIS",
    "POP_AXIS", "MODEL_AXIS",
    "match_partition_rules", "match_rule", "named_tree_map",
    "tree_shardings", "make_shard_and_gather_fns", "put_global",
    "put_tree", "rules_for", "rule_table_hash", "RULE_TABLES",
    "constrain", "constrain_tree", "bind_mesh", "use_mesh", "active_mesh",
    "shrink_env_rows_by_rule", "ELASTIC_EXTRA_RULES",
    "shard_train", "shard_map_train", "carry_sharding_prefix", "put_carry",
    "DeviceGroups", "split_devices", "split_mesh", "parse_group_spec",
    "HParams", "MemberState", "init_member", "make_member_step",
    "make_population_step", "jit_population_step", "population_shardings",
    "member_stack_specs", "sample_hparams", "stack_members",
    "PBTConfig", "PBTController", "PBTDecision", "exploit_explore",
    "gather_members", "HPARAM_BOUNDS",
]
