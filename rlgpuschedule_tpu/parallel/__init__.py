"""L5 distributed execution: device mesh + data-parallel training."""
from .mesh import (make_mesh, replicated, env_sharded, pop_sharded,
                   pop_env_sharded, DATA_AXIS, POP_AXIS)
from .dp import shard_train, carry_sharding_prefix, put_carry

__all__ = [
    "make_mesh", "replicated", "env_sharded", "pop_sharded",
    "pop_env_sharded", "DATA_AXIS", "POP_AXIS",
    "shard_train", "carry_sharding_prefix", "put_carry",
]
