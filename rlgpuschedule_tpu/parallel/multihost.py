"""Multi-host / multi-process execution (L5): the DCN story.

Capability parity: SURVEY.md §5 "Distributed communication backend" —
"multislice ``jax.distributed`` initialization; cross-slice transfers via
host or device_put with DCN-aware sharding" — and §1's TPU restatement
("trace shards distributed across the pod over DCN"). Round 2 shipped the
single-process ICI story only (VERDICT r2 missing #2: "v5e-16 is 2 hosts;
the current stack cannot form that mesh at all"); this module adds the
process layer:

- :func:`initialize` — ``jax.distributed.initialize`` wrapper; on the CPU
  platform it selects the gloo collectives backend so the exact same
  multi-controller program is testable on this machine as N local
  processes (SURVEY.md §4 "Distributed without a real cluster").
- :func:`global_mesh` — the (pop, data) mesh over the GLOBAL device list;
  on a real v5e-16 that is 2 hosts × 8 chips with ICI inside a host and
  DCN between them, and the pop axis is laid out over the outer
  (cross-host) dim by ``make_mesh``'s existing axis order.
- :func:`process_env_slice` / :func:`global_traces` — per-host trace
  sharding: every process cuts and uploads ONLY the env windows its
  devices own; ``jax.make_array_from_process_local_data`` stitches the
  process-local shards into one global array, and the jitted GSPMD train
  step (``dp.shard_train``) then runs unchanged — each process executes
  the same program on its addressable shards, XLA routing the gradient
  psum across ICI+DCN.

The 2-process × 4-device CPU dryrun (``__graft_entry__.dryrun_multihost``,
``tests/test_multihost.py``) proves the DP gradient psum and the PBT
exploit gather both cross process boundaries.
"""
from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh

from .mesh import make_mesh


def initialize(coordinator_address: str, num_processes: int,
               process_id: int, connect_attempts: int = 3,
               backoff_s: float = 1.0) -> None:
    """``jax.distributed.initialize`` for one process of a multi-host run,
    with retry-with-backoff on the coordinator connect.

    Call before ANY device access, one call per process. On real TPU pods
    the three arguments are normally auto-detected from the TPU metadata
    (pass them only for non-standard setups); on CPU (CI / this machine)
    they are required, and the gloo cross-process collectives backend is
    selected — without it the CPU client has no cross-host transfer
    implementation and collective lowering fails.

    Retry: a restarted gang races its own coordinator (rank 0 may come up
    seconds after its peers try to connect — exactly the supervised
    restart-from-checkpoint path), so a failed connect is retried
    ``connect_attempts`` times with exponential backoff (``backoff_s``,
    doubled per attempt) before the final failure propagates."""
    # set unconditionally — probing the backend state here would itself
    # initialize a backend (making jax.distributed.initialize refuse), and
    # the gloo selection only affects a CPU backend anyway; if a backend
    # IS already initialized, distributed.initialize raises its own clear
    # error below
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    import sys
    import time
    for attempt in range(max(connect_attempts, 1)):
        try:
            # all three identifiers are explicit, so cluster auto-detect
            # has nothing to contribute — and on a host with libtpu
            # visible but no metadata server (this rig) the TPU detection
            # path stalls each rank ~100s in metadata-fetch retries
            # before the coordinator even starts (measured; it timed the
            # 2-proc dryrun out at jax's 300s initialization_timeout)
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id,
                cluster_detection_method="deactivate")
            return
        except Exception as e:
            if attempt + 1 >= max(connect_attempts, 1):
                raise
            wait = backoff_s * 2 ** attempt
            print(f"multihost: coordinator connect attempt "
                  f"{attempt + 1}/{connect_attempts} failed "
                  f"({type(e).__name__}: {str(e)[:120]}); retrying in "
                  f"{wait:.1f}s", file=sys.stderr, flush=True)
            time.sleep(wait)


def warmup_collectives() -> None:
    """Form the cross-process collective communicator NOW, while every
    rank is still in lockstep from ``jax.distributed.initialize``.

    The gloo context for a device clique is created lazily at the first
    dispatched collective, with a hard ~30s KV rendezvous window. Left
    to the first real train step, that window races each rank's XLA
    compile of the step program — on a loaded 1-core CI host the compile
    skew between two ranks exceeded it and the faster rank died with
    ``Gloo context initialization failed: DEADLINE_EXCEEDED`` (measured,
    2026-08-04 tier-1 run). This barrier's trivial all-device psum
    compiles in well under the window on every rank, and on exit all
    ranks resume simultaneously — so the heavy compiles that follow
    start aligned instead of wherever coordinator-connect jitter left
    them."""
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("rlgpuschedule_tpu.warmup")


def global_mesh(n_pop: int = 1) -> Mesh:
    """The (pop, data) mesh over every device of every process. Identical
    call on all processes (multi-controller SPMD: each process runs the
    same program over the same global mesh and touches only its
    addressable shards)."""
    return make_mesh(devices=jax.devices(), n_pop=n_pop)


def process_env_slice(mesh: Mesh, n_envs: int) -> slice:
    """The contiguous [start, stop) range of global env rows whose shards
    live on THIS process, under the standard env sharding (``env_sharded``
    — the one ``dp.shard_train`` uses, so no cross-process reshard ever
    happens). Derived from the sharding's device→index map (not assumed),
    so a mesh whose data axis interleaves processes is rejected rather
    than silently mis-sliced."""
    from .mesh import env_sharded
    idx_map = env_sharded(mesh).addressable_devices_indices_map((n_envs,))
    if not idx_map:
        raise ValueError("mesh has no addressable devices on this process")
    bounds = sorted({(0 if sl.start is None else sl.start,
                      n_envs if sl.stop is None else sl.stop)
                     for (sl,) in idx_map.values()})
    lo, hi = bounds[0][0], bounds[-1][1]
    covered = sum(b - a for a, b in bounds)
    if covered != hi - lo:
        raise ValueError(
            f"process-local env rows are not one contiguous range "
            f"({bounds}); per-host trace cutting assumes the data axis "
            f"does not interleave processes")
    return slice(lo, hi)


def global_traces(mesh: Mesh, local_traces: Any, n_envs: int) -> Any:
    """Assemble a global [E, ...] env-batched pytree (device Trace, carry
    fields, …) from THIS process's local rows (``process_env_slice``).
    Each leaf becomes one global ``jax.Array`` whose shards this process
    contributes without ever materializing other hosts' windows."""
    from .mesh import env_sharded
    sharding = env_sharded(mesh)

    def put(leaf):
        return jax.make_array_from_process_local_data(
            sharding, np.asarray(leaf),
            global_shape=(n_envs,) + tuple(np.shape(leaf)[1:]))

    return jax.tree.map(put, local_traces)
