"""Actor/learner device-group plumbing for the async engine (L5).

The Sebulba split (PAPERS.md: arXiv 2104.06272) dedicates one subset of
the chips to acting (rollout collection) and a disjoint subset to
learning (the minibatch update), with trajectories crossing between
them through a bounded queue. This module owns the *static* half of
that design: carving the visible device list into the two groups and
giving each its own 1-axis data mesh, so every downstream sharding
(replicated params, env-sharded carry/traces, [T, E]-sharded
trajectories) is the same GSPMD vocabulary :mod:`~.mesh` and
:mod:`~.dp` already speak — a group of size 1 and a group of size N
run identical code.

A single-device rig is allowed to run both roles on the SAME device
(``shared=True``): the phases then only overlap at the host level
(dispatch pipelining), but the queue/staleness semantics — and the
bound-0 bit-identity contract — are exactly the same, which is what
the in-process tests exercise.
"""
from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS


def parse_group_spec(spec: str | int | None):
    """Parse a CLI device-group spec: an int (or digit string) is a
    device COUNT; a comma-separated string ("0,2,3") is explicit device
    indices. Returns ``None`` (defaulted), an int count, or a list of
    indices."""
    if spec is None:
        return None
    if isinstance(spec, int):
        return spec
    s = spec.strip()
    if "," in s:
        try:
            return [int(p) for p in s.split(",") if p.strip() != ""]
        except ValueError:
            raise ValueError(f"bad device-group spec {spec!r}: comma form "
                             f"must be integer device indices") from None
    try:
        return int(s)
    except ValueError:
        raise ValueError(f"bad device-group spec {spec!r}: expected a "
                         f"count or comma-separated indices") from None


@dataclasses.dataclass(frozen=True)
class DeviceGroups:
    """The actor/learner split plus each group's 1-axis data mesh."""
    actor: tuple
    learner: tuple
    actor_mesh: Mesh
    learner_mesh: Mesh
    shared: bool  # both roles on the same device set (1-device rigs)

    # -- sharding vocabulary, per group --------------------------------
    def actor_replicated(self) -> NamedSharding:
        return NamedSharding(self.actor_mesh, P())

    def actor_env(self) -> NamedSharding:
        """[E, ...] arrays, env axis split over the actor group."""
        return NamedSharding(self.actor_mesh, P(DATA_AXIS))

    def learner_replicated(self) -> NamedSharding:
        return NamedSharding(self.learner_mesh, P())

    def learner_env(self) -> NamedSharding:
        return NamedSharding(self.learner_mesh, P(DATA_AXIS))

    def learner_traj(self) -> NamedSharding:
        """[T, E, ...] trajectory batches: env axis split over the
        learner group (the time axis stays whole — the update flattens
        T into the batch)."""
        return NamedSharding(self.learner_mesh, P(None, DATA_AXIS))

    def actor_traj(self) -> NamedSharding:
        return NamedSharding(self.actor_mesh, P(None, DATA_AXIS))

    def describe(self) -> str:
        if self.shared:
            return (f"shared group: {len(self.actor)} device(s) "
                    f"{[d.id for d in self.actor]}")
        return (f"actor {[d.id for d in self.actor]} | "
                f"learner {[d.id for d in self.learner]}")


def _resolve(spec, devices, taken_from_front: bool):
    """Turn a parsed spec into a concrete device list."""
    if isinstance(spec, int):
        if not 1 <= spec <= len(devices):
            raise ValueError(f"group count {spec} out of range for "
                             f"{len(devices)} visible devices")
        return devices[:spec] if taken_from_front else devices[-spec:]
    ids = {d.id: d for d in devices}
    out = []
    for i in spec:
        if i not in ids:
            raise ValueError(f"device index {i} not among visible device "
                             f"ids {sorted(ids)}")
        out.append(ids[i])
    if len(set(spec)) != len(spec):
        raise ValueError(f"duplicate device index in group spec {spec}")
    if not out:
        raise ValueError("empty device group")
    return out


def split_devices(actor: str | int | list | None = None,
                  learner: str | int | list | None = None,
                  devices=None) -> DeviceGroups:
    """Carve the visible devices into actor/learner groups.

    Defaults: one visible device → both roles share it; otherwise the
    first half acts and the second half learns (rollout is the wider
    phase on the CPU workload, so ties round the extra device to the
    actor). Explicit specs (counts or index lists, see
    :func:`parse_group_spec`) must be disjoint — EXCEPT when both name
    the identical set, which requests a shared group explicitly."""
    devices = list(devices if devices is not None else jax.local_devices())
    n = len(devices)
    actor = parse_group_spec(actor) if isinstance(actor, (str, type(None))) \
        else actor
    learner = parse_group_spec(learner) \
        if isinstance(learner, (str, type(None))) else learner

    if actor is None and learner is None:
        if n == 1:
            a = l = devices
        else:
            a, l = devices[:(n + 1) // 2], devices[(n + 1) // 2:]
    elif actor is None:
        l = _resolve(learner, devices, taken_from_front=False)
        a = [d for d in devices if d not in l] or l
    elif learner is None:
        a = _resolve(actor, devices, taken_from_front=True)
        l = [d for d in devices if d not in a] or a
    else:
        a = _resolve(actor, devices, taken_from_front=True)
        l = _resolve(learner, devices, taken_from_front=False)

    shared = set(a) == set(l)
    if not shared and set(a) & set(l):
        raise ValueError(
            f"actor and learner groups overlap ({[d.id for d in a]} vs "
            f"{[d.id for d in l]}): groups must be disjoint, or identical "
            f"to request an explicitly shared group")
    return DeviceGroups(
        actor=tuple(a), learner=tuple(l),
        actor_mesh=Mesh(np.asarray(a), (DATA_AXIS,)),
        learner_mesh=Mesh(np.asarray(l), (DATA_AXIS,)),
        shared=shared)


def split_mesh(mesh: Mesh, actor: str | int | list | None = None,
               learner: str | int | list | None = None) -> DeviceGroups:
    """Carve the actor/learner groups out of the UNIFIED mesh's device
    set (``mesh.make_unified_mesh``) instead of the raw local device
    list — the groups become submeshes of the one mesh every other entry
    point shares, so a deployment that pins the unified mesh to a subset
    of the rig automatically scopes the async split to the same subset.
    Devices walk the mesh in (pop, data, model) raster order, so the
    default first-half/second-half split cuts along the data axis."""
    return split_devices(actor, learner,
                         devices=list(mesh.devices.flatten()))
