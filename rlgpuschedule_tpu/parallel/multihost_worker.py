"""One process of the multi-host CPU dryrun (SURVEY.md §4 "Distributed
without a real cluster"; VERDICT r2 next-round #4).

Run as ``python -m rlgpuschedule_tpu.parallel.multihost_worker --coordinator
127.0.0.1:PORT --num-procs 2 --proc-id K --devices-per-proc 4`` — normally
via ``__graft_entry__.dryrun_multihost`` (plain gate) or
``__graft_entry__.dryrun_multihost_supervised`` (failure-recovery gate),
which spawn all ranks and check their reports agree. Each rank:

1. ``multihost.initialize`` (jax.distributed + gloo CPU collectives,
   retry-with-backoff on the coordinator connect),
2. builds the global (pop, data) mesh spanning both processes,
3. cuts ONLY its own env windows of a config-1-style trace
   (per-host trace sharding) and assembles the global Trace with
   ``multihost.global_traces``,
4. runs ``--steps`` GSPMD DP train steps (gradient psum crosses the
   process boundary) and prints a params fingerprint — identical across
   ranks iff the cross-process allreduce works,
5. runs a PBT exploit gather over a pop axis that spans the two processes
   (the cross-host weight copy, DCN-analog) and prints its fingerprint
   (skippable with ``--no-pbt-check``).

Resilience surface (the supervised dryrun drives all of it):

- ``--heartbeat-dir`` — beat a per-rank file before every step
  (``resilience.HeartbeatWriter``); the supervisor's timeout watchdog
  reads them.
- ``--ckpt-dir`` — after every completed step, atomically persist this
  rank's params + opt_state to a PER-STEP ``rank<r>.step<k>.npz`` (+ a
  ``rank<r>.step`` latest-step sidecar the supervisor can read without
  numpy; last ``_CKPT_KEEP`` step files retained). Plain npz, not Orbax:
  each rank saves only its own replicated copy, so no cross-process
  checkpoint barrier can deadlock a gang that is already dying.
- ``--resume-step S`` — restore ``rank<r>.step<S>.npz`` and continue
  from step S (the supervisor passes the minimum completed step across
  ranks; a rank that durably got further must restore the OLDER state,
  or the gang resumes from divergent replicated params).
- ``--restore-rank R`` — restore RANK R's checkpoint file instead of
  this rank's own (default). This is the shrink-to-fit hook: after a
  permanent rank loss the supervisor relaunches the gang at the
  surviving world size, and new rank i restores surviving old rank
  ``restore_ranks[i]``'s file. Sound because the persisted state is
  replicated (params + optimizer moments) — every rank's file at step S
  holds the same state, so any surviving rank's copy re-seeds the
  shrunk gang at ANY world size (``--num-procs`` is free to differ from
  the world the checkpoint was written at; the update geometry is
  re-validated against the shrunk global batch before anything
  compiles).
- ``--fault kill-rank@T:rank=R | lose-rank@T:rank=R`` — rank R dies
  un-gracefully right before step T, i.e. before entering the step's
  collective, so every rank's last durable checkpoint is step T-1 or
  later. ``kill-rank`` exits restartable (``faults.KILL_RANK_EXIT``);
  ``lose-rank`` exits ``faults.LOSE_RANK_EXIT``, the permanent-loss
  signature the supervisor answers with a shrink instead of a respawn.

Per-step rollout keys are ``PRNGKey(i)`` — a restarted rank replays the
same key sequence from its resume step, so all ranks (including the
respawned one) converge to identical fingerprints; a SHRUNK gang runs a
smaller env batch (fewer global devices), so its fingerprints differ
from the old world's, but they must still AGREE across the surviving
ranks — the cross-rank contract holds at every world size.
"""
from __future__ import annotations

import argparse
import os


_CKPT_KEEP = 4   # per-rank retained step files (bounds disk, >= any lag)


def _save_rank_ckpt(ckpt_dir: str, rank: int, state, completed: int) -> None:
    """Persist this rank's state as a PER-STEP file plus a latest-step
    sidecar. Per-step files are load-bearing: when a rank dies mid-step,
    its PEERS may have durably completed one step more, so the supervisor
    resumes the gang from the MINIMUM completed step — and a rank that is
    ahead must restore that older state, not its own newest (restoring
    divergent per-rank states into a replicated-params DP program
    assembles garbage global arrays; measured as NaN metrics two steps
    after a resume)."""
    import glob
    import jax
    import numpy as np
    leaves = [np.asarray(x) for x in
              jax.tree.leaves((state.params, state.opt_state))]
    path = os.path.join(ckpt_dir, f"rank{rank}.step{completed}.npz")
    tmp = path + ".tmp.npz"
    np.savez(tmp, completed=completed,
             **{f"leaf{j}": l for j, l in enumerate(leaves)})
    os.replace(tmp, path)
    side = os.path.join(ckpt_dir, f"rank{rank}.step")
    with open(side + ".tmp", "w") as f:
        f.write(str(completed))
    os.replace(side + ".tmp", side)
    kept = sorted(glob.glob(os.path.join(ckpt_dir, f"rank{rank}.step*.npz")),
                  key=lambda p: int(p.rsplit("step", 1)[1].split(".")[0]))
    for old in kept[:-_CKPT_KEEP]:
        os.remove(old)


def _load_rank_ckpt(ckpt_dir: str, rank: int, state, step: int):
    """Restore this rank's state AT exactly ``step`` (the gang-wide
    minimum the supervisor chose)."""
    import jax
    import numpy as np
    path = os.path.join(ckpt_dir, f"rank{rank}.step{step}.npz")
    data = np.load(path)
    template = (state.params, state.opt_state)
    treedef = jax.tree.structure(template)
    leaves = [data[f"leaf{j}"] for j in range(treedef.num_leaves)]
    params, opt_state = jax.tree.unflatten(treedef, leaves)
    return state.replace(params=params, opt_state=opt_state)


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--num-procs", type=int, required=True)
    ap.add_argument("--proc-id", type=int, required=True)
    ap.add_argument("--devices-per-proc", type=int, default=4)
    ap.add_argument("--steps", type=int, default=2)
    ap.add_argument("--heartbeat-dir", default=None)
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--resume-step", type=int, default=-1,
                    help=">= 0: restore rank<r>.npz from --ckpt-dir and "
                         "continue from this step")
    ap.add_argument("--restore-rank", type=int, default=-1,
                    help=">= 0: with --resume-step, restore THIS rank's "
                         "checkpoint file instead of our own (shrink-to-"
                         "fit: a surviving old rank's replicated state "
                         "re-seeds the shrunk gang)")
    ap.add_argument("--fault", action="append", default=None,
                    help="kill-rank@T:rank=R | lose-rank@T:rank=R "
                         "(resilience.parse_fault)")
    ap.add_argument("--obs-dir", default=None,
                    help="append this rank's telemetry events "
                         "(obs.EventBus JSONL stream) under this "
                         "directory; the supervisor's report CLI merges "
                         "all ranks into one timeline")
    ap.add_argument("--no-pbt-check", action="store_true",
                    help="skip the PBT exploit-gather section (the "
                         "supervised dryrun tests recovery, not PBT)")
    args = ap.parse_args(argv)

    # platform pins must precede ANY jax device access. The env var alone
    # is NOT enough here: ``python -m`` imports the package __init__s
    # (which import jax) before main() runs, and jax snapshots
    # JAX_PLATFORMS at import — so mutate the live config too. Measured
    # without it (2026-08-04): with the rig's libtpu importable, the
    # first device access probed the TPU plugin through minutes of
    # metadata-fetch retries on ONE rank, desyncing the gang past gloo's
    # ~30s rendezvous window.
    os.environ["JAX_PLATFORMS"] = "cpu"   # for any subprocess readers
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count="
            f"{args.devices_per_proc}").strip()

    import jax
    jax.config.update("jax_platforms", "cpu")
    # no persistent compile cache in multi-controller workers: RELOADING
    # a serialized gloo-collective executable segfaults the rank on this
    # jax (measured; __graft_entry__'s spawners scrub the env var too,
    # but the config can arrive set via enable_compile_cache's export)
    jax.config.update("jax_compilation_cache_dir", None)
    from rlgpuschedule_tpu.parallel import multihost
    from rlgpuschedule_tpu.resilience import (FaultInjector, HeartbeatWriter,
                                              parse_fault)

    bus = None
    from rlgpuschedule_tpu.obs.trace import NULL_TRACER, Tracer
    tracer = NULL_TRACER
    if args.obs_dir:
        from rlgpuschedule_tpu.obs import EventBus
        from rlgpuschedule_tpu.obs import skew as skew_lib
        bus = EventBus(args.obs_dir, rank=args.proc_id)
        bus.emit("worker_start", world=args.num_procs,
                 devices_per_proc=args.devices_per_proc, steps=args.steps,
                 resume_step=(args.resume_step
                              if args.resume_step >= 0 else None),
                 restore_rank=(args.restore_rank
                               if args.restore_rank >= 0 else None))
        # clock-skew handshake: a dedicated (wall, mono) offset sample at
        # start and each step, so the report CLI can rewrite all ranks'
        # timelines onto one corrected monotonic axis
        skew_lib.stamp(bus, source="worker_start")
        tracer = Tracer(bus, enabled=True)
    injector = FaultInjector([parse_fault(s) for s in args.fault or []],
                             bus=bus)
    hb = (HeartbeatWriter(args.heartbeat_dir, args.proc_id)
          if args.heartbeat_dir else None)
    if hb is not None:
        # beat BEFORE the first jax import: startup (backend init +
        # distributed connect + XLA compiles) is the longest beat-free
        # stretch of the whole run, and without this the supervisor's
        # missing-file grace window has to cover all of it
        hb.beat(-1)
    if args.ckpt_dir:
        os.makedirs(args.ckpt_dir, exist_ok=True)

    multihost.initialize(args.coordinator, args.num_procs, args.proc_id)
    n_global = args.num_procs * args.devices_per_proc
    assert len(jax.devices()) == n_global, \
        f"expected {n_global} global devices, got {len(jax.devices())}"
    multihost.warmup_collectives()

    import jax.numpy as jnp
    import numpy as np
    from flax.training.train_state import TrainState

    from rlgpuschedule_tpu.algos import (PPOConfig, init_carry,
                                         make_ppo_step)
    from rlgpuschedule_tpu.algos.ppo import make_optimizer
    from rlgpuschedule_tpu.env import EnvParams, stack_traces
    from rlgpuschedule_tpu.models import make_policy
    from rlgpuschedule_tpu.parallel import dp, mesh as mesh_lib, pbt
    from rlgpuschedule_tpu.sim.core import SimParams
    from rlgpuschedule_tpu.traces import gen_poisson_trace

    # ---- DP across processes (config-1 shape, tiny) ----------------------
    mesh = multihost.global_mesh()
    n_envs = 2 * n_global
    cfg = PPOConfig(n_steps=8, n_epochs=1, n_minibatches=2)
    # elastic fail-fast: the world size may differ from the one the
    # checkpoint was written at (shrink-to-fit relaunch) — re-validate
    # the update geometry against THIS world's global batch before any
    # mesh/compile work, so an untileable shrink dies with a clear error
    # instead of a shape error mid-step
    from rlgpuschedule_tpu.algos import resolve_geometry
    try:
        resolve_geometry(cfg.n_epochs, cfg.n_minibatches,
                         cfg.minibatch_size, cfg.n_steps * n_envs)
    except ValueError as e:
        raise SystemExit(
            f"elastic geometry: world size {args.num_procs} "
            f"({n_global} devices, global batch {cfg.n_steps}x{n_envs}) "
            f"does not tile the update geometry: {e}") from e
    env_params = EnvParams(
        sim=SimParams(n_nodes=4, gpus_per_node=4, max_jobs=12, queue_len=4),
        obs_kind="flat", horizon=32, time_scale=60.0, reward_scale=100.0)

    # per-host trace sharding: cut ONLY the windows this process owns
    sl = multihost.process_env_slice(mesh, n_envs)
    local_windows = [gen_poisson_trace(0.1, 8, seed=e, max_jobs=12,
                                       mean_duration=30.0, gpu_sizes=(1, 2),
                                       gpu_probs=(0.7, 0.3))
                     for e in range(n_envs)[sl]]
    local_traces = stack_traces(local_windows, env_params)
    traces = multihost.global_traces(
        mesh, jax.tree.map(np.asarray, local_traces), n_envs)

    net = make_policy("flat", env_params.n_actions)
    apply_fn = lambda p, o, m: net.apply(p, o, m)
    # distinct streams for the rollout carry and the param init (jsan
    # prng-key-reuse, PR 3 first-run finding: the same PRNGKey(0) fed the
    # carry, the global carry assembly, AND net.init — action sampling
    # and weight draws shared one stream). Every rank computes the same
    # split, so the cross-rank fingerprint contract is untouched.
    carry_key, init_key = jax.random.split(jax.random.PRNGKey(0))
    # carry init needs a local-shape trace: init on the local shard, then
    # assemble the global carry the same way the traces were assembled
    local_carry = init_carry(env_params, local_traces, carry_key)
    carry = dp.RolloutCarry(
        env_state=multihost.global_traces(
            mesh, jax.tree.map(np.asarray, local_carry.env_state), n_envs),
        obs=multihost.global_traces(
            mesh, np.asarray(local_carry.obs), n_envs),
        mask=multihost.global_traces(
            mesh, np.asarray(local_carry.mask), n_envs),
        key=local_carry.key)
    params = net.init(init_key, np.asarray(local_carry.obs[:1]),
                      np.asarray(local_carry.mask[:1]))
    state = TrainState.create(apply_fn=net.apply, params=params,
                              tx=make_optimizer(cfg))
    start = 0
    if args.ckpt_dir and args.resume_step >= 0:
        start = args.resume_step
        src = args.restore_rank if args.restore_rank >= 0 else args.proc_id
        state = _load_rank_ckpt(args.ckpt_dir, src, state, start)
        if bus is not None:
            bus.emit("worker_resumed", step=start, from_rank=src,
                     world=args.num_procs)
        print(f"MULTIHOST_RESUMED proc={args.proc_id} step={start} "
              f"from_rank={src}", flush=True)
    step, state, carry, traces = dp.shard_train(
        mesh, make_ppo_step(apply_fn, env_params, cfg), state, carry, traces)
    for i in range(start, args.steps):
        injector.maybe_exit_rank(args.proc_id, i)
        if hb is not None:
            hb.beat(i)
        # per-rank iteration span (a named ROADMAP residual): every rank
        # records its own step extent, so the merged skew-corrected
        # timeline shows the gang's lockstep (or a straggler's lag)
        with tracer.span("iteration", iteration=i):
            state, carry, metrics = step(state, carry, traces,
                                         jax.random.PRNGKey(i))
            if args.ckpt_dir:
                jax.block_until_ready(state.params)
                with tracer.span("ckpt"):
                    _save_rank_ckpt(args.ckpt_dir, args.proc_id, state,
                                    i + 1)
        if bus is not None:
            bus.emit("worker_step", step=i, completed=i + 1)
            skew_lib.stamp(bus, source="step", step=i)
    jax.block_until_ready(state.params)
    assert all(bool(jnp.isfinite(v)) for v in metrics), metrics
    # replicated-params fingerprint: identical across ranks iff the
    # cross-process gradient psum worked
    fp = float(sum(jnp.sum(jnp.abs(l.astype(jnp.float32)))
                   for l in jax.tree.leaves(state.params)))
    if bus is not None:
        bus.emit("worker_done", world=args.num_procs,
                 fingerprint=round(fp, 6))
        bus.close()
    print(f"MULTIHOST_DP_OK proc={args.proc_id} fingerprint={fp:.6f}",
          flush=True)

    if args.no_pbt_check:
        return

    # ---- PBT exploit gather across the process boundary ------------------
    pop_mesh = multihost.global_mesh(n_pop=args.num_procs)
    pop_sh = mesh_lib.pop_sharded(pop_mesh)
    vals = np.arange(args.num_procs * 4, dtype=np.float32) \
        .reshape(args.num_procs, 4)
    # each process contributes ONLY its own member row (the member stack
    # lives pop-sharded across hosts; exploit must move weights between
    # them — the DCN-analog transfer)
    w = jax.make_array_from_process_local_data(
        pop_sh, vals[args.proc_id:args.proc_id + 1], vals.shape)
    src = np.full((args.num_procs,), args.num_procs - 1, np.int64)
    gathered = pbt.gather_members({"w": w}, src)  # all copy the LAST member
    # verify THIS process's shards now hold the last member's row — data
    # that lived on the other process before the gather (for every rank
    # but the last)
    for shard in gathered["w"].addressable_shards:
        rows = np.asarray(shard.data)
        np.testing.assert_array_equal(
            rows, np.tile(vals[-1], (rows.shape[0], 1)))
    print(f"MULTIHOST_PBT_OK proc={args.proc_id} "
          f"gathered_row={vals[-1].tolist()}", flush=True)


if __name__ == "__main__":
    main()
