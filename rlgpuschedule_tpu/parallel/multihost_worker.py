"""One process of the multi-host CPU dryrun (SURVEY.md §4 "Distributed
without a real cluster"; VERDICT r2 next-round #4).

Run as ``python -m rlgpuschedule_tpu.parallel.multihost_worker --coordinator
127.0.0.1:PORT --num-procs 2 --proc-id K --devices-per-proc 4`` — normally
via ``__graft_entry__.dryrun_multihost``, which spawns all ranks and
checks their reports agree. Each rank:

1. ``multihost.initialize`` (jax.distributed + gloo CPU collectives),
2. builds the global (pop, data) mesh spanning both processes,
3. cuts ONLY its own env windows of a config-1-style trace
   (per-host trace sharding) and assembles the global Trace with
   ``multihost.global_traces``,
4. runs 2 GSPMD DP train steps (gradient psum crosses the process
   boundary) and prints a params fingerprint — identical across ranks iff
   the cross-process allreduce works,
5. runs a PBT exploit gather over a pop axis that spans the two processes
   (the cross-host weight copy, DCN-analog) and prints its fingerprint.
"""
from __future__ import annotations

import argparse
import os


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--num-procs", type=int, required=True)
    ap.add_argument("--proc-id", type=int, required=True)
    ap.add_argument("--devices-per-proc", type=int, default=4)
    args = ap.parse_args(argv)

    # platform pins must precede ANY jax device access
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count="
            f"{args.devices_per_proc}").strip()

    import jax
    from rlgpuschedule_tpu.parallel import multihost

    multihost.initialize(args.coordinator, args.num_procs, args.proc_id)
    n_global = args.num_procs * args.devices_per_proc
    assert len(jax.devices()) == n_global, \
        f"expected {n_global} global devices, got {len(jax.devices())}"

    import jax.numpy as jnp
    import numpy as np
    from flax.training.train_state import TrainState

    from rlgpuschedule_tpu.algos import (PPOConfig, init_carry,
                                         make_ppo_step)
    from rlgpuschedule_tpu.algos.ppo import make_optimizer
    from rlgpuschedule_tpu.env import EnvParams, stack_traces
    from rlgpuschedule_tpu.models import make_policy
    from rlgpuschedule_tpu.parallel import dp, mesh as mesh_lib, pbt
    from rlgpuschedule_tpu.sim.core import SimParams
    from rlgpuschedule_tpu.traces import gen_poisson_trace

    # ---- DP across processes (config-1 shape, tiny) ----------------------
    mesh = multihost.global_mesh()
    n_envs = 2 * n_global
    env_params = EnvParams(
        sim=SimParams(n_nodes=4, gpus_per_node=4, max_jobs=12, queue_len=4),
        obs_kind="flat", horizon=32, time_scale=60.0, reward_scale=100.0)

    # per-host trace sharding: cut ONLY the windows this process owns
    sl = multihost.process_env_slice(mesh, n_envs)
    local_windows = [gen_poisson_trace(0.1, 8, seed=e, max_jobs=12,
                                       mean_duration=30.0, gpu_sizes=(1, 2),
                                       gpu_probs=(0.7, 0.3))
                     for e in range(n_envs)[sl]]
    local_traces = stack_traces(local_windows, env_params)
    traces = multihost.global_traces(
        mesh, jax.tree.map(np.asarray, local_traces), n_envs)

    net = make_policy("flat", env_params.n_actions)
    apply_fn = lambda p, o, m: net.apply(p, o, m)
    cfg = PPOConfig(n_steps=8, n_epochs=1, n_minibatches=2)
    key = jax.random.PRNGKey(0)
    # carry init needs a local-shape trace: init on the local shard, then
    # assemble the global carry the same way the traces were assembled
    local_carry = init_carry(env_params, local_traces, key)
    carry = dp.RolloutCarry(
        env_state=multihost.global_traces(
            mesh, jax.tree.map(np.asarray, local_carry.env_state), n_envs),
        obs=multihost.global_traces(
            mesh, np.asarray(local_carry.obs), n_envs),
        mask=multihost.global_traces(
            mesh, np.asarray(local_carry.mask), n_envs),
        key=key)
    params = net.init(key, np.asarray(local_carry.obs[:1]),
                      np.asarray(local_carry.mask[:1]))
    state = TrainState.create(apply_fn=net.apply, params=params,
                              tx=make_optimizer(cfg))
    step, state, carry, traces = dp.shard_train(
        mesh, make_ppo_step(apply_fn, env_params, cfg), state, carry, traces)
    for i in range(2):
        state, carry, metrics = step(state, carry, traces,
                                     jax.random.PRNGKey(i))
    jax.block_until_ready(state.params)
    assert all(bool(jnp.isfinite(v)) for v in metrics), metrics
    # replicated-params fingerprint: identical across ranks iff the
    # cross-process gradient psum worked
    fp = float(sum(jnp.sum(jnp.abs(l.astype(jnp.float32)))
                   for l in jax.tree.leaves(state.params)))
    print(f"MULTIHOST_DP_OK proc={args.proc_id} fingerprint={fp:.6f}",
          flush=True)

    # ---- PBT exploit gather across the process boundary ------------------
    pop_mesh = multihost.global_mesh(n_pop=args.num_procs)
    pop_sh = mesh_lib.pop_sharded(pop_mesh)
    vals = np.arange(args.num_procs * 4, dtype=np.float32) \
        .reshape(args.num_procs, 4)
    # each process contributes ONLY its own member row (the member stack
    # lives pop-sharded across hosts; exploit must move weights between
    # them — the DCN-analog transfer)
    w = jax.make_array_from_process_local_data(
        pop_sh, vals[args.proc_id:args.proc_id + 1], vals.shape)
    src = np.full((args.num_procs,), args.num_procs - 1, np.int64)
    gathered = pbt.gather_members({"w": w}, src)  # all copy the LAST member
    # verify THIS process's shards now hold the last member's row — data
    # that lived on the other process before the gather (for every rank
    # but the last)
    for shard in gathered["w"].addressable_shards:
        rows = np.asarray(shard.data)
        np.testing.assert_array_equal(
            rows, np.tile(vals[-1], (rows.shape[0], 1)))
    print(f"MULTIHOST_PBT_OK proc={args.proc_id} "
          f"gathered_row={vals[-1].tolist()}", flush=True)


if __name__ == "__main__":
    main()
