"""Async actor–learner engine (L6): Sebulba-style overlapped
rollout/update (PAPERS.md: arXiv 2104.06272).

The synchronous loop alternates rollout and update on the same devices,
idling each phase's silicon during the other. This engine splits the
device set into an ACTOR group (collects fixed-shape trajectory batches
with the fused rollout scan) and a LEARNER group (runs the fused
minibatch-update engine), overlapped through a bounded device-side
queue:

- **actor thread**: gates on the staleness bound, runs the jitted
  rollout on the actor mesh, ``device_put``s the batch onto the learner
  mesh (an EXPLICIT transfer — the hot path stays clean under
  ``jax.transfer_guard("disallow")``), and blocks when the queue is
  full (backpressure, never drops).
- **learner loop** (the CALLER's thread, so exceptions/logging/ckpt
  hooks behave exactly like ``Experiment.run``): pops batch ``i``,
  enforces the staleness invariant, splits the learner RNG in the same
  per-iteration order as the sync loop, runs the jitted
  ``make_learn_step`` program, and publishes the fresh params back to
  the actor mesh.

**Staleness semantics.** Batches are indexed ``i = 0, 1, ...`` and
batch ``i`` feeds update ``i``; after update ``i`` the published
version is ``i+1``. The actor may not START collecting batch ``i``
until ``published_version >= i - bound``, and always uses the FRESHEST
published params (so ``staleness(i) = i - version_used(i) <= bound`` —
the learner asserts it defensively). ``bound = 0`` is lock-step: every
batch is collected with fully-fresh params, which — because the split
rollout/learn programs compose literally the same functions as the
fused step, and the learner replicates the sync loop's key-split
order — reproduces ``Experiment.run`` BIT-IDENTICALLY
(tests/test_async.py pins this).

**Barriers.** Checkpoints and window resamples need a drained queue
(the carry and traces are shared mutable state). Both loops compute the
same barrier set from the cadences up front; at a barrier iteration the
actor parks after collecting that batch, the learner drains/updates
through it, performs the ckpt/resample, then releases the actor — so
checkpoints always capture a consistent (state, key, carry) triple and
resume is deterministic given the drained queue.

A single-device rig runs both roles on the same device
(``DeviceGroups.shared``): phases overlap only at the host level, but
every queue/staleness/barrier semantic — and the bound-0 bit-identity —
is identical, which is what most in-process tests exercise.

**Bit-identity scope.** The bound-0 guarantee holds when the learner
group has the same device count as the sync baseline's placement (the
update's batch reductions keep their float summation order). A WIDER
learner group shards those reductions — allclose, not bitwise, exactly
like ``parallel.dp`` data-parallel vs single-device.

**Compile-once execution.** Both programs are AOT-compiled at
construction (``jit(...).lower(...).compile()``) on the caller thread:
the loops call execute-only Compiled objects, so no jit dispatch-cache
or persistent compile-cache traffic ever happens on the actor thread
(the compile cache's file IO is not thread-safe against a concurrently
dispatching peer), and a geometry change raises a shape error instead
of silently recompiling mid-run.

**CPU host platform caveat.** XLA:CPU's client is not robust against a
second execute thread: concurrent execute calls intermittently crash
(and collective-bearing multi-device programs deadlock), and buffer
DONATION frees inputs at execute time in a way that races the peer
thread (heap corruption). On the CPU platform the runner therefore
serializes device dispatch behind a lock and disables donation — phase
spans still overlap at the host level (queue/staleness/backpressure
all behave), but compute does not. Real overlap needs separate non-CPU
device groups, where the lock is a no-op and donation is on.
"""
from __future__ import annotations

import bisect
import contextlib
import dataclasses
import threading
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp

from .algos import (init_carry, validate_rollout_geometry,
                    validate_update_geometry)
from .algos.a2c import make_learn_step as make_a2c_learn_step
from .algos.ppo import make_learn_step as make_ppo_learn_step
from .algos.rollout import make_rollout_step
from .analysis.sentinels import no_implicit_transfers
from .obs.telemetry import AsyncGauges, OverlapMeter
from .obs.trace import tracer_of
from .parallel.dp import put_carry
from .parallel.groups import DeviceGroups
from .parallel.sharding import put_global
from .utils.profiling import SectionTimer

# every blocking wait re-checks abort/progress at this period, and gives
# up (a clear RuntimeError instead of a silent hang) after stall_timeout_s
_WAIT_TICK_S = 0.2


class StalenessError(RuntimeError):
    """The learner was handed a batch older than the configured bound —
    an engine invariant violation (the actor gate should make this
    impossible), never a user error."""


class _Aborted(Exception):
    """Internal: unwind a loop after the other loop failed."""


@dataclasses.dataclass
class _QueueItem:
    index: int      # global batch index (== the update that consumes it)
    version: int    # policy version the batch was collected with
    batch: Any      # (transitions, last_value) on the LEARNER mesh


class TrajectoryQueue:
    """Bounded blocking FIFO between the actor and learner loops.

    ``put`` blocks while the queue is at capacity (backpressure — a
    full queue slows the actor down, it never drops a batch); ``get``
    blocks while empty. ``abort(exc)`` wakes every waiter: blocked
    ``put``/``get`` calls raise ``_Aborted`` so a failure in either
    loop unwinds the other instead of deadlocking it. Items hold
    device arrays (the batch already lives on the learner mesh), so
    the queue itself never copies — it is depth bookkeeping plus
    blocking semantics."""

    def __init__(self, capacity: int,
                 clock: Callable[[], float] = time.monotonic,
                 stall_timeout_s: float = 300.0):
        if capacity < 1:
            raise ValueError(f"queue capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._clock = clock
        self._stall_timeout_s = stall_timeout_s
        self._items: list[_QueueItem] = []
        self._cv = threading.Condition()
        self._abort_exc: BaseException | None = None

    def __len__(self) -> int:
        with self._cv:
            return len(self._items)

    def abort(self, exc: BaseException) -> None:
        with self._cv:
            if self._abort_exc is None:
                self._abort_exc = exc
            self._cv.notify_all()

    def _wait(self, ready: Callable[[], bool], what: str) -> float:
        """Wait until ``ready()`` under the held condition; returns the
        seconds spent blocked."""
        t0 = self._clock()
        while not ready():
            if self._abort_exc is not None:
                raise _Aborted() from self._abort_exc
            if self._clock() - t0 > self._stall_timeout_s:
                raise RuntimeError(
                    f"TrajectoryQueue.{what} stalled for more than "
                    f"{self._stall_timeout_s}s (deadlocked peer loop?)")
            self._cv.wait(_WAIT_TICK_S)
        if self._abort_exc is not None:
            raise _Aborted() from self._abort_exc
        return self._clock() - t0

    def put(self, item: _QueueItem) -> float:
        """Blocking append; returns seconds spent in backpressure."""
        with self._cv:
            waited = self._wait(
                lambda: len(self._items) < self.capacity, "put")
            self._items.append(item)
            self._cv.notify_all()
            return waited

    def get(self) -> tuple[_QueueItem, float]:
        """Blocking pop; returns (item, seconds spent waiting)."""
        with self._cv:
            waited = self._wait(lambda: len(self._items) > 0, "get")
            item = self._items.pop(0)
            self._cv.notify_all()
            return item, waited


class _ParamSlot:
    """The published-params mailbox: the learner publishes
    ``(params_on_actor_mesh, version)``; the actor waits for a minimum
    version and always reads the freshest publication."""

    def __init__(self, params: Any, version: int,
                 clock: Callable[[], float] = time.monotonic,
                 stall_timeout_s: float = 300.0):
        self._params = params
        self._version = version
        self._clock = clock
        self._stall_timeout_s = stall_timeout_s
        self._cv = threading.Condition()
        self._abort = False

    @property
    def version(self) -> int:
        with self._cv:
            return self._version

    def abort(self) -> None:
        with self._cv:
            self._abort = True
            self._cv.notify_all()

    def publish(self, params: Any, version: int) -> None:
        with self._cv:
            self._params = params
            self._version = version
            self._cv.notify_all()

    def wait_for(self, min_version: int) -> tuple[Any, int, float]:
        """Block until ``version >= min_version``; returns
        (freshest params, their version, seconds spent gated)."""
        t0 = self._clock()
        with self._cv:
            while self._version < min_version:
                if self._abort:
                    raise _Aborted()
                if self._clock() - t0 > self._stall_timeout_s:
                    raise RuntimeError(
                        f"staleness gate stalled waiting for version "
                        f">= {min_version} (have {self._version})")
                self._cv.wait(_WAIT_TICK_S)
            if self._abort:
                raise _Aborted()
            return self._params, self._version, self._clock() - t0


class AsyncRunner:
    """The assembled async engine over one :class:`~.experiment.Experiment`.

    Construction ADOPTS the experiment onto the group meshes: traces +
    rollout carry move to the actor mesh, train state + learner RNG key
    to the learner mesh (all explicit placements). ``run()`` may be
    called repeatedly — programs stay compiled, version/batch counters
    continue — which is how the no-post-warmup-recompile contract is
    tested.

    ``staleness_bound``: max policy-versions a consumed batch may be
    behind (0 = lock-step sync twin). ``queue_capacity``: bounded
    batches in flight past the gate (backpressure blocks the actor
    when full)."""

    def __init__(self, exp, groups: DeviceGroups | None = None,
                 staleness_bound: int = 1, queue_capacity: int = 2,
                 stall_timeout_s: float = 300.0):
        if staleness_bound < 0:
            raise ValueError(f"staleness_bound must be >= 0, got "
                             f"{staleness_bound}")
        cfg = exp.cfg
        algo_cfg = cfg.ppo if cfg.algo == "ppo" else cfg.a2c
        if groups is None:
            # default split carved from the shared unified mesh (same
            # device walk as every other entry point), so actor/learner
            # groups are submeshes of the ONE Mesh(pop × data × model)
            from .parallel.groups import split_mesh
            from .parallel.mesh import unified_mesh
            groups = split_mesh(unified_mesh())
        # decoupled per-phase geometry validation: each phase against
        # ITS device group (the whole point of splitting the check)
        validate_rollout_geometry(algo_cfg.n_steps, cfg.n_envs,
                                  len(groups.actor))
        validate_update_geometry(algo_cfg.n_epochs, algo_cfg.n_minibatches,
                                 algo_cfg.minibatch_size,
                                 n_steps=algo_cfg.n_steps,
                                 n_envs=cfg.n_envs,
                                 n_devices=len(groups.learner))
        # XLA:CPU's client intermittently segfaults (and, for
        # collective-bearing multi-device programs, deadlocks) when two
        # threads execute concurrently, so serialize device dispatch on
        # the CPU platform. Phase spans still overlap at the host level
        # — the same accounting the shared-group mode reports — but
        # real compute overlap needs a non-CPU platform, where the lock
        # is a no-op.
        on_cpu = groups.actor[0].platform == "cpu"
        self._dispatch_lock: Any = (
            threading.Lock() if on_cpu else contextlib.nullcontext())
        self.exp = exp
        self.groups = groups
        self.staleness_bound = staleness_bound
        self.queue_capacity = queue_capacity
        self._stall_timeout_s = stall_timeout_s
        self._clock = time.monotonic

        make_learn = (make_ppo_learn_step if cfg.algo == "ppo"
                      else make_a2c_learn_step)

        # adopt the experiment's state onto the group meshes (explicit
        # placements; the experiment object stays the canonical holder
        # so save/restore_checkpoint work unchanged)
        self._arep = groups.actor_replicated()
        self._aenv = groups.actor_env()
        self._lrep = groups.learner_replicated()
        self._lenv = groups.learner_env()
        self._ltraj = groups.learner_traj()
        exp.traces = put_global(exp.traces, self._aenv)
        exp.carry = put_carry(groups.actor_mesh, exp.carry)
        exp.train_state = put_global(exp.train_state, self._lrep)
        exp.key = jax.device_put(exp.key, self._lrep)
        self._faults = (put_global(exp.faults, self._aenv)
                        if exp.faults is not None else None)
        exp.faults = self._faults

        # AOT-compile BOTH programs on the construction thread
        # (``jit(...).lower(...).compile()``): the loops call execute-only
        # Compiled objects, so neither the jit dispatch machinery nor the
        # persistent compilation cache — whose file IO is not safe to
        # drive from the actor thread while the caller thread dispatches —
        # is ever touched off this thread, and a geometry change raises a
        # shape error instead of silently recompiling mid-run.
        # axis_name stays None on both programs: GSPMD derives the
        # gradient psum / global advantage moments from the shardings,
        # exactly like parallel.dp.shard_train
        # donation frees the consumed input buffers at execute time, and
        # on XLA:CPU that deallocation races the peer loop's thread
        # (heap corruption — intermittent SIGSEGV/SIGABRT at ~30% per
        # run on the 8-virtual-device rig, clean with donation off), so
        # the engine donates only off-CPU; the lock-step bit-identity
        # does not depend on aliasing
        rollout_donate = () if on_cpu else (1,)   # the carry
        learn_donate = () if on_cpu else (0,)     # the train state
        params_a = jax.device_put(exp.train_state.params, self._arep)
        rollout_jit = jax.jit(
            make_rollout_step(exp.apply_fn, exp.env_params,
                              algo_cfg.n_steps),
            donate_argnums=rollout_donate)
        self._rollout = rollout_jit.lower(
            params_a, exp.carry, exp.traces, self._faults).compile()
        # the learner program needs a trajectory batch to lower against;
        # shape it from the rollout's output avals (zeros, freed after)
        _, tr_s, lv_s = jax.eval_shape(rollout_jit, params_a, exp.carry,
                                       exp.traces, self._faults)
        tr0 = jax.device_put(jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), tr_s), self._ltraj)
        lv0 = jax.device_put(jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), lv_s), self._lenv)
        # donate the state only (off-CPU): the trajectory leaves go
        # through a [T, E] -> [B] flatten, so XLA can't alias them
        # anyway (donating them just warns)
        self._learn = jax.jit(
            make_learn(exp.apply_fn, algo_cfg),
            donate_argnums=learn_donate).lower(
                exp.train_state, tr0, lv0, exp.key).compile()
        del tr0, lv0

        # loop state shared across run() calls
        self._iterations_done = 0
        self._slot = _ParamSlot(
            params_a, version=0,
            clock=self._clock, stall_timeout_s=stall_timeout_s)
        self.queue = TrajectoryQueue(queue_capacity, clock=self._clock,
                                     stall_timeout_s=stall_timeout_s)
        self.overlap = OverlapMeter(clock=self._clock)
        self._bar_cv = threading.Condition()
        self._barriers: list[int] = []     # global iteration indices
        self._barriers_done = 0
        self._failure: BaseException | None = None
        # actor-thread-owned accounting, read by the learner at log points
        self._actor_idle_s = 0.0
        self._learner_idle_s = 0.0
        self._staleness_last = 0
        self._staleness_max = 0
        self._staleness_sum = 0
        self._consumed = 0

    # -- barrier plumbing --------------------------------------------------

    def _wait_barriers_before(self, i: int) -> float:
        """Actor side: park until every barrier < global iteration ``i``
        has been completed by the learner. Returns seconds parked."""
        t0 = self._clock()
        with self._bar_cv:
            need = bisect.bisect_left(self._barriers, i)
            while self._barriers_done < need:
                if self._failure is not None:
                    raise _Aborted()
                if self._clock() - t0 > self._stall_timeout_s:
                    raise RuntimeError(
                        f"actor stalled at barrier before iteration {i}")
                self._bar_cv.wait(_WAIT_TICK_S)
        return self._clock() - t0

    def _complete_barrier(self) -> None:
        with self._bar_cv:
            self._barriers_done += 1
            self._bar_cv.notify_all()

    def _abort(self, exc: BaseException) -> None:
        self._failure = exc
        self.queue.abort(exc)
        self._slot.abort()
        with self._bar_cv:
            self._bar_cv.notify_all()

    # -- the actor loop (background thread) --------------------------------

    def _actor_loop(self, base: int, iterations: int,
                    sections: SectionTimer, tracer) -> None:
        exp = self.exp
        carry = exp.carry
        try:
            for k in range(iterations):
                i = base + k
                # the flight recorder's actor track: the two wait spans
                # (barrier park + staleness gate) and the push-side
                # backpressure are the idle gaps the occupancy timeline
                # exists to show; the "actor" span is the busy lane the
                # measured-overlap summary unions against "learner"
                with tracer.span("actor_barrier_wait"):
                    self._actor_idle_s += self._wait_barriers_before(i)
                # staleness gate: may not collect batch i until the
                # learner is within `bound` versions; always take the
                # freshest publication (ISSUE: "refresh actor params
                # from the learner at each publish")
                with tracer.span("actor_gate_wait"):
                    params, version, gated = self._slot.wait_for(
                        i - self.staleness_bound)
                self._actor_idle_s += gated
                # barrier-park may have replaced the carry (resample)
                carry = exp.carry
                with tracer.span("actor", iteration=i), \
                        self.overlap.span("actor"), sections("actor"), \
                        no_implicit_transfers(), self._dispatch_lock:
                    carry, tr, last_value = self._rollout(
                        params, carry, exp.traces, self._faults)
                    # explicit hop onto the learner mesh: the queue is
                    # device-side, the learner pops ready-to-consume
                    # buffers
                    batch = (jax.device_put(tr, self._ltraj),
                             jax.device_put(last_value, self._lenv))
                    jax.block_until_ready(batch)
                exp.carry = carry
                with tracer.span("queue_push_wait"):
                    self._actor_idle_s += self.queue.put(
                        _QueueItem(index=i, version=version, batch=batch))
        except _Aborted:
            pass
        except BaseException as e:  # surface in the learner thread
            self._abort(e)

    # -- the learner loop (caller thread) -----------------------------------

    def run(self, iterations: int | None = None, log_every: int = 0,
            logger: Callable[[int, dict], None] | None = None,
            ckpt=None, ckpt_every: int = 0,
            eval_every: int = 0,
            eval_fn: "Callable[[int], dict] | None" = None,
            eval_logger: Callable[[int, dict], None] | None = None,
            telemetry=None) -> dict:
        """Run ``iterations`` overlapped actor/learner iterations; the
        hook surface (log/ckpt/eval cadences, telemetry protocol,
        summary dict) mirrors :meth:`Experiment.run`. Window streaming
        (``cfg.resample_every``) and checkpoints run at drained-queue
        barriers."""
        exp = self.exp
        cfg = exp.cfg
        iterations = iterations or cfg.iterations
        base = self._iterations_done
        history: list[dict] = []
        eval_history: list[dict] = []
        sections = (telemetry.sections if telemetry is not None
                    else SectionTimer())
        gauges = (AsyncGauges(telemetry.registry)
                  if telemetry is not None else None)
        tracer = tracer_of(telemetry)

        def is_ckpt(b: int) -> bool:
            return bool(ckpt is not None and ckpt_every
                        and ((b + 1) % ckpt_every == 0
                             or b == iterations - 1))

        def is_resample(b: int) -> bool:
            return bool(cfg.resample_every
                        and (b + 1) % cfg.resample_every == 0
                        and b != iterations - 1)

        local_barriers = sorted(b for b in range(iterations)
                                if is_ckpt(b) or is_resample(b))
        with self._bar_cv:
            self._barriers = [base + b for b in local_barriers]
            self._barriers_done = 0
        self._failure = None

        if telemetry is not None:
            telemetry.run_start(
                loop="async-experiment", config=cfg.name, algo=cfg.algo,
                iterations=iterations, n_envs=cfg.n_envs,
                steps_per_iteration=exp.steps_per_iteration,
                staleness_bound=self.staleness_bound,
                queue_capacity=self.queue_capacity,
                actor_devices=[d.id for d in self.groups.actor],
                learner_devices=[d.id for d in self.groups.learner],
                shared_group=self.groups.shared)

        t0 = time.monotonic()
        actor = threading.Thread(
            target=self._actor_loop,
            args=(base, iterations, sections, tracer),
            name="async-actor", daemon=True)
        actor.start()
        try:
            for k in range(iterations):
                b = k  # hook-facing iteration index, as in Experiment.run
                i = base + k
                if telemetry is not None:
                    telemetry.begin_iteration(b)
                with sections("queue_wait"), \
                        tracer.span("queue_pop_wait"):
                    item, waited = self.queue.get()
                self._learner_idle_s += waited
                if item.index != i:
                    raise RuntimeError(
                        f"queue order violation: expected batch {i}, "
                        f"got {item.index}")
                staleness = item.index - item.version
                if staleness > self.staleness_bound:
                    raise StalenessError(
                        f"batch {item.index} was collected at policy "
                        f"version {item.version} — {staleness} versions "
                        f"behind, bound is {self.staleness_bound}")
                self._staleness_last = staleness
                self._staleness_max = max(self._staleness_max, staleness)
                self._staleness_sum += staleness
                self._consumed += 1
                guard = (telemetry.dispatch(b) if telemetry is not None
                         else contextlib.nullcontext())
                tr, last_value = item.batch
                with tracer.span("learner", iteration=b), \
                        self.overlap.span("learner"), \
                        sections("learner"), guard, self._dispatch_lock:
                    # the sync loop's per-iteration split, in the same order
                    exp.key, sub = jax.random.split(exp.key)
                    state, metrics = self._learn(exp.train_state, tr,
                                                 last_value, sub)
                    params_a = jax.device_put(state.params, self._arep)
                    jax.block_until_ready(params_a)
                exp.train_state = state
                self._slot.publish(params_a, i + 1)

                want_log = bool(log_every) and (b % log_every == 0
                                                or b == iterations - 1)
                m = None
                if want_log:
                    with sections("sync"), tracer.span("sync"), \
                            self._dispatch_lock:
                        m = {k2: float(v) for k2, v in
                             jax.device_get(metrics)._asdict().items()}
                    history.append({"iteration": b, **m})
                    if logger is not None:
                        logger(b, m)
                    if gauges is not None:
                        gauges.publish(
                            queue_depth=len(self.queue),
                            staleness=self._staleness_last,
                            actor_idle_s=self._actor_idle_s,
                            learner_idle_s=self._learner_idle_s,
                            overlap_s=self.overlap.overlap_s)
                if eval_fn is not None and eval_every and \
                        ((b + 1) % eval_every == 0 or b == iterations - 1):
                    with sections("eval"), tracer.span("eval"), \
                            self._dispatch_lock:
                        em = dict(eval_fn(b))
                    eval_history.append({"iteration": b, **em})
                    if eval_logger is not None:
                        eval_logger(b, em)
                # drained-queue barrier work (actor is parked past i)
                if is_ckpt(b):
                    with sections("ckpt"), tracer.span("ckpt"):
                        exp.save_checkpoint(
                            ckpt, meta={"iteration": b,
                                        "async_iteration": i,
                                        "staleness_bound":
                                            self.staleness_bound})
                if is_resample(b):
                    with sections("resample"), tracer.span("resample"):
                        self._resample()
                if is_ckpt(b) or is_resample(b):
                    self._complete_barrier()
                if telemetry is not None:
                    telemetry.end_iteration(
                        b, m if want_log else None,
                        exp.steps_per_iteration)
                if self._failure is not None:
                    raise self._failure
        except BaseException as e:
            self._abort(e)
            actor.join(timeout=30)
            raise
        actor.join(timeout=self._stall_timeout_s)
        if actor.is_alive():
            exc = RuntimeError("actor thread failed to drain")
            self._abort(exc)
            raise exc
        if self._failure is not None:
            raise self._failure
        jax.block_until_ready(exp.train_state.params)
        self._iterations_done = base + iterations
        wall = time.monotonic() - t0
        total_env_steps = iterations * exp.steps_per_iteration
        async_info = self.async_info()
        out = {"wall_s": wall, "iterations": iterations,
               "env_steps": total_env_steps,
               "env_steps_per_sec": total_env_steps / wall,
               "window_cursor": exp.window_cursor,
               "history": history,
               "phase_seconds": {k: round(v, 6)
                                 for k, v in sections.report().items()},
               "async": async_info}
        if eval_history:
            out["eval_history"] = eval_history
        if telemetry is not None:
            if gauges is not None:
                gauges.publish(queue_depth=len(self.queue),
                               staleness=self._staleness_last,
                               actor_idle_s=self._actor_idle_s,
                               learner_idle_s=self._learner_idle_s,
                               overlap_s=self.overlap.overlap_s)
            telemetry.run_end(
                iterations=iterations, wall_s=round(wall, 6),
                env_steps=total_env_steps,
                env_steps_per_sec=round(out["env_steps_per_sec"], 3),
                **{f"async_{k2}": v for k2, v in async_info.items()
                   if not isinstance(v, (list, dict))})
        return out

    def async_info(self) -> dict:
        """The engine's overlap/staleness accounting so far."""
        snap = self.overlap.snapshot()
        return {
            "staleness_bound": self.staleness_bound,
            "queue_capacity": self.queue_capacity,
            "actor_devices": [d.id for d in self.groups.actor],
            "learner_devices": [d.id for d in self.groups.learner],
            "shared_group": self.groups.shared,
            "overlap_s": snap["overlap_s"],
            "actor_busy_s": snap.get("busy_actor_s", 0.0),
            "learner_busy_s": snap.get("busy_learner_s", 0.0),
            "actor_idle_s": round(self._actor_idle_s, 6),
            "learner_idle_s": round(self._learner_idle_s, 6),
            "staleness_max": self._staleness_max,
            "staleness_mean": (self._staleness_sum / self._consumed
                               if self._consumed else 0.0),
        }

    def _resample(self) -> None:
        """Window streaming at a drained-queue barrier: re-cut the env
        windows and re-init the carry, keeping every placement on its
        group mesh (the sync twin is ``Experiment.advance_windows``,
        which assumes a single placement domain)."""
        exp = self.exp
        exp._cut_windows(exp.window_cursor + exp.cfg.n_envs)
        exp.key, carry_key = jax.random.split(exp.key)
        carry_key = jax.device_put(carry_key, self._arep)
        carry = init_carry(exp.env_params, exp.traces, carry_key,
                           self._faults)
        exp.carry = jax.tree.map(
            lambda new, old: jax.device_put(new, old.sharding),
            carry, exp.carry)
