"""Async actor–learner engine (L6): Sebulba-style overlapped
rollout/update (PAPERS.md: arXiv 2104.06272).

The synchronous loop alternates rollout and update on the same devices,
idling each phase's silicon during the other. This engine splits the
device set into an ACTOR group (collects fixed-shape trajectory batches
with the fused rollout scan) and a LEARNER group (runs the fused
minibatch-update engine), overlapped through a bounded device-side
queue:

- **actor thread**: gates on the staleness bound, runs the jitted
  rollout on the actor mesh, ``device_put``s the batch onto the learner
  mesh (an EXPLICIT transfer — the hot path stays clean under
  ``jax.transfer_guard("disallow")``), and blocks when the queue is
  full (backpressure, never drops).
- **learner loop** (the CALLER's thread, so exceptions/logging/ckpt
  hooks behave exactly like ``Experiment.run``): pops batch ``i``,
  enforces the staleness invariant, splits the learner RNG in the same
  per-iteration order as the sync loop, runs the jitted
  ``make_learn_step`` program, and publishes the fresh params back to
  the actor mesh.

**Staleness semantics.** Batches are indexed ``i = 0, 1, ...`` and
batch ``i`` feeds update ``i``; after update ``i`` the published
version is ``i+1``. The actor may not START collecting batch ``i``
until ``published_version >= i - bound``, and always uses the FRESHEST
published params (so ``staleness(i) = i - version_used(i) <= bound`` —
the learner asserts it defensively). ``bound = 0`` is lock-step: every
batch is collected with fully-fresh params, which — because the split
rollout/learn programs compose literally the same functions as the
fused step, and the learner replicates the sync loop's key-split
order — reproduces ``Experiment.run`` BIT-IDENTICALLY
(tests/test_async.py pins this).

**Barriers.** Checkpoints and window resamples need a drained queue
(the carry and traces are shared mutable state). Both loops compute the
same barrier set from the cadences up front; at a barrier iteration the
actor parks after collecting that batch, the learner drains/updates
through it, performs the ckpt/resample, then releases the actor — so
checkpoints always capture a consistent (state, key, carry) triple and
resume is deterministic given the drained queue.

A single-device rig runs both roles on the same device
(``DeviceGroups.shared``): phases overlap only at the host level, but
every queue/staleness/barrier semantic — and the bound-0 bit-identity —
is identical, which is what most in-process tests exercise.

**Bit-identity scope.** The bound-0 guarantee holds when the learner
group has the same device count as the sync baseline's placement (the
update's batch reductions keep their float summation order). A WIDER
learner group shards those reductions — allclose, not bitwise, exactly
like ``parallel.dp`` data-parallel vs single-device.

**Compile-once execution.** Both programs are AOT-compiled at
construction (``jit(...).lower(...).compile()``) on the caller thread:
the loops call execute-only Compiled objects, so no jit dispatch-cache
or persistent compile-cache traffic ever happens on the actor thread
(the compile cache's file IO is not thread-safe against a concurrently
dispatching peer), and a geometry change raises a shape error instead
of silently recompiling mid-run.

**CPU host platform caveat.** XLA:CPU's client is not robust against a
second execute thread: concurrent execute calls intermittently crash
(and collective-bearing multi-device programs deadlock), and buffer
DONATION frees inputs at execute time in a way that races the peer
thread (heap corruption). On the CPU platform the runner therefore
serializes device dispatch behind a lock and disables donation — phase
spans still overlap at the host level (queue/staleness/backpressure
all behave), but compute does not. Real overlap needs separate non-CPU
device groups, where the lock is a no-op and donation is on.

**Deep staleness.** The queue depth worth running is bounded by the
learner's tolerance for off-policy data, not by the engine: with the
default clip-only PPO loss, bounds past ~1 visibly bias the surrogate.
``cfg.ppo.correction = "vtrace"`` (``algos.vtrace``) re-weights the
advantage targets by clipped importance ratios so bounds >= 4 train
without that bias — the per-batch mean/max ratios surface on the
``rlsched_async_importance_ratio_*`` gauges and in ``async_info()`` so
a drifting ratio is visible before it is a reward regression.

:class:`AsyncPopulationRunner` extends the same engine to the PBT
population: the vmapped member rollout/learn halves run on the group
meshes, PBT exploit/explore fires at drained-queue barriers predicted
from the controller window, and staleness is tracked per member.
"""
from __future__ import annotations

import bisect
import contextlib
import dataclasses
import threading
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp

from .algos import (init_carry, validate_rollout_geometry,
                    validate_update_geometry)
from .algos.a2c import make_learn_step as make_a2c_learn_step
from .algos.ppo import make_learn_step as make_ppo_learn_step
from .algos.rollout import make_rollout_step
from .analysis.sentinels import no_implicit_transfers
from .obs.telemetry import AsyncGauges, OverlapMeter
from .obs.trace import tracer_of
from .parallel.dp import put_carry
from .parallel.groups import DeviceGroups
from .parallel.sharding import put_global
from .utils.profiling import SectionTimer

# every blocking wait re-checks abort/progress at this period, and gives
# up (a clear RuntimeError instead of a silent hang) after stall_timeout_s
_WAIT_TICK_S = 0.2


class StalenessError(RuntimeError):
    """The learner was handed a batch older than the configured bound —
    an engine invariant violation (the actor gate should make this
    impossible), never a user error."""


class _Aborted(Exception):
    """Internal: unwind a loop after the other loop failed."""


@dataclasses.dataclass
class _QueueItem:
    index: int      # global batch index (== the update that consumes it)
    version: int    # policy version the batch was collected with
    batch: Any      # (transitions, last_value) on the LEARNER mesh


class TrajectoryQueue:
    """Bounded blocking FIFO between the actor and learner loops.

    ``put`` blocks while the queue is at capacity (backpressure — a
    full queue slows the actor down, it never drops a batch); ``get``
    blocks while empty. ``abort(exc)`` wakes every waiter: blocked
    ``put``/``get`` calls raise ``_Aborted`` so a failure in either
    loop unwinds the other instead of deadlocking it. Items hold
    device arrays (the batch already lives on the learner mesh), so
    the queue itself never copies — it is depth bookkeeping plus
    blocking semantics."""

    def __init__(self, capacity: int,
                 clock: Callable[[], float] = time.monotonic,
                 stall_timeout_s: float = 300.0):
        if capacity < 1:
            raise ValueError(f"queue capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._clock = clock
        self._stall_timeout_s = stall_timeout_s
        self._items: list[_QueueItem] = []
        self._cv = threading.Condition()
        self._abort_exc: BaseException | None = None

    def __len__(self) -> int:
        with self._cv:
            return len(self._items)

    def abort(self, exc: BaseException) -> None:
        with self._cv:
            if self._abort_exc is None:
                self._abort_exc = exc
            self._cv.notify_all()

    def _wait(self, ready: Callable[[], bool], what: str) -> float:
        """Wait until ``ready()`` under the held condition; returns the
        seconds spent blocked."""
        t0 = self._clock()
        while not ready():
            if self._abort_exc is not None:
                raise _Aborted() from self._abort_exc
            if self._clock() - t0 > self._stall_timeout_s:
                raise RuntimeError(
                    f"TrajectoryQueue.{what} stalled for more than "
                    f"{self._stall_timeout_s}s (deadlocked peer loop?)")
            self._cv.wait(_WAIT_TICK_S)
        if self._abort_exc is not None:
            raise _Aborted() from self._abort_exc
        return self._clock() - t0

    def put(self, item: _QueueItem) -> float:
        """Blocking append; returns seconds spent in backpressure."""
        with self._cv:
            waited = self._wait(
                lambda: len(self._items) < self.capacity, "put")
            self._items.append(item)
            self._cv.notify_all()
            return waited

    def get(self) -> tuple[_QueueItem, float]:
        """Blocking pop; returns (item, seconds spent waiting)."""
        with self._cv:
            waited = self._wait(lambda: len(self._items) > 0, "get")
            item = self._items.pop(0)
            self._cv.notify_all()
            return item, waited


class _ParamSlot:
    """The published-params mailbox: the learner publishes
    ``(params_on_actor_mesh, version)``; the actor waits for a minimum
    version and always reads the freshest publication."""

    def __init__(self, params: Any, version: int,
                 clock: Callable[[], float] = time.monotonic,
                 stall_timeout_s: float = 300.0):
        self._params = params
        self._version = version
        self._clock = clock
        self._stall_timeout_s = stall_timeout_s
        self._cv = threading.Condition()
        self._abort = False

    @property
    def version(self) -> int:
        with self._cv:
            return self._version

    def abort(self) -> None:
        with self._cv:
            self._abort = True
            self._cv.notify_all()

    def publish(self, params: Any, version: int) -> None:
        with self._cv:
            self._params = params
            self._version = version
            self._cv.notify_all()

    def wait_for(self, min_version: int) -> tuple[Any, int, float]:
        """Block until ``version >= min_version``; returns
        (freshest params, their version, seconds spent gated)."""
        t0 = self._clock()
        with self._cv:
            while self._version < min_version:
                if self._abort:
                    raise _Aborted()
                if self._clock() - t0 > self._stall_timeout_s:
                    raise RuntimeError(
                        f"staleness gate stalled waiting for version "
                        f">= {min_version} (have {self._version})")
                self._cv.wait(_WAIT_TICK_S)
            if self._abort:
                raise _Aborted()
            return self._params, self._version, self._clock() - t0


class AsyncRunner:
    """The assembled async engine over one :class:`~.experiment.Experiment`.

    Construction ADOPTS the experiment onto the group meshes: traces +
    rollout carry move to the actor mesh, train state + learner RNG key
    to the learner mesh (all explicit placements). ``run()`` may be
    called repeatedly — programs stay compiled, version/batch counters
    continue — which is how the no-post-warmup-recompile contract is
    tested.

    ``staleness_bound``: max policy-versions a consumed batch may be
    behind (0 = lock-step sync twin). ``queue_capacity``: bounded
    batches in flight past the gate (backpressure blocks the actor
    when full)."""

    def __init__(self, exp, groups: DeviceGroups | None = None,
                 staleness_bound: int = 1, queue_capacity: int = 2,
                 stall_timeout_s: float = 300.0):
        if staleness_bound < 0:
            raise ValueError(f"staleness_bound must be >= 0, got "
                             f"{staleness_bound}")
        cfg = exp.cfg
        algo_cfg = cfg.ppo if cfg.algo == "ppo" else cfg.a2c
        if groups is None:
            # default split carved from the shared unified mesh (same
            # device walk as every other entry point), so actor/learner
            # groups are submeshes of the ONE Mesh(pop × data × model)
            from .parallel.groups import split_mesh
            from .parallel.mesh import unified_mesh
            groups = split_mesh(unified_mesh())
        # decoupled per-phase geometry validation: each phase against
        # ITS device group (the whole point of splitting the check)
        validate_rollout_geometry(algo_cfg.n_steps, cfg.n_envs,
                                  len(groups.actor))
        validate_update_geometry(algo_cfg.n_epochs, algo_cfg.n_minibatches,
                                 algo_cfg.minibatch_size,
                                 n_steps=algo_cfg.n_steps,
                                 n_envs=cfg.n_envs,
                                 n_devices=len(groups.learner))
        # XLA:CPU's client intermittently segfaults (and, for
        # collective-bearing multi-device programs, deadlocks) when two
        # threads execute concurrently, so serialize device dispatch on
        # the CPU platform. Phase spans still overlap at the host level
        # — the same accounting the shared-group mode reports — but
        # real compute overlap needs a non-CPU platform, where the lock
        # is a no-op.
        on_cpu = groups.actor[0].platform == "cpu"
        self._dispatch_lock: Any = (
            threading.Lock() if on_cpu else contextlib.nullcontext())
        self.exp = exp
        self.groups = groups
        self.staleness_bound = staleness_bound
        self.queue_capacity = queue_capacity
        self._stall_timeout_s = stall_timeout_s
        self._clock = time.monotonic

        make_learn = (make_ppo_learn_step if cfg.algo == "ppo"
                      else make_a2c_learn_step)

        # adopt the experiment's state onto the group meshes (explicit
        # placements; the experiment object stays the canonical holder
        # so save/restore_checkpoint work unchanged)
        self._arep = groups.actor_replicated()
        self._aenv = groups.actor_env()
        self._lrep = groups.learner_replicated()
        self._lenv = groups.learner_env()
        self._ltraj = groups.learner_traj()
        exp.traces = put_global(exp.traces, self._aenv)
        exp.carry = put_carry(groups.actor_mesh, exp.carry)
        exp.train_state = put_global(exp.train_state, self._lrep)
        exp.key = jax.device_put(exp.key, self._lrep)
        self._faults = (put_global(exp.faults, self._aenv)
                        if exp.faults is not None else None)
        exp.faults = self._faults

        # AOT-compile BOTH programs on the construction thread
        # (``jit(...).lower(...).compile()``): the loops call execute-only
        # Compiled objects, so neither the jit dispatch machinery nor the
        # persistent compilation cache — whose file IO is not safe to
        # drive from the actor thread while the caller thread dispatches —
        # is ever touched off this thread, and a geometry change raises a
        # shape error instead of silently recompiling mid-run.
        # axis_name stays None on both programs: GSPMD derives the
        # gradient psum / global advantage moments from the shardings,
        # exactly like parallel.dp.shard_train
        # donation frees the consumed input buffers at execute time, and
        # on XLA:CPU that deallocation races the peer loop's thread
        # (heap corruption — intermittent SIGSEGV/SIGABRT at ~30% per
        # run on the 8-virtual-device rig, clean with donation off), so
        # the engine donates only off-CPU; the lock-step bit-identity
        # does not depend on aliasing
        rollout_donate = () if on_cpu else (1,)   # the carry
        learn_donate = () if on_cpu else (0,)     # the train state
        params_a = jax.device_put(exp.train_state.params, self._arep)
        rollout_jit = jax.jit(
            make_rollout_step(exp.apply_fn, exp.env_params,
                              algo_cfg.n_steps),
            donate_argnums=rollout_donate)
        self._rollout = rollout_jit.lower(
            params_a, exp.carry, exp.traces, self._faults).compile()
        # the learner program needs a trajectory batch to lower against;
        # shape it from the rollout's output avals (zeros, freed after)
        _, tr_s, lv_s = jax.eval_shape(rollout_jit, params_a, exp.carry,
                                       exp.traces, self._faults)
        tr0 = jax.device_put(jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), tr_s), self._ltraj)
        lv0 = jax.device_put(jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), lv_s), self._lenv)
        # donate the state only (off-CPU): the trajectory leaves go
        # through a [T, E] -> [B] flatten, so XLA can't alias them
        # anyway (donating them just warns)
        self._learn = jax.jit(
            make_learn(exp.apply_fn, algo_cfg),
            donate_argnums=learn_donate).lower(
                exp.train_state, tr0, lv0, exp.key).compile()
        del tr0, lv0

        # loop state shared across run() calls
        self._iterations_done = 0
        self._slot = _ParamSlot(
            params_a, version=0,
            clock=self._clock, stall_timeout_s=stall_timeout_s)
        self.queue = TrajectoryQueue(queue_capacity, clock=self._clock,
                                     stall_timeout_s=stall_timeout_s)
        self.overlap = OverlapMeter(clock=self._clock)
        self._bar_cv = threading.Condition()
        self._barriers: list[int] = []     # global iteration indices
        self._barriers_done = 0
        self._failure: BaseException | None = None
        # actor-thread-owned accounting, read by the learner at log points
        self._actor_idle_s = 0.0
        self._learner_idle_s = 0.0
        self._staleness_last = 0
        self._staleness_max = 0
        self._staleness_sum = 0
        self._consumed = 0
        # importance-ratio monitor, fed from the metrics already fetched
        # at log points (ZERO extra host syncs): 1.0 is the on-policy
        # neutral value the GAE path reports
        self._rho_last = 1.0
        self._rho_max_seen = 1.0

    # -- barrier plumbing --------------------------------------------------

    def _wait_barriers_before(self, i: int) -> float:
        """Actor side: park until every barrier < global iteration ``i``
        has been completed by the learner. Returns seconds parked."""
        t0 = self._clock()
        with self._bar_cv:
            need = bisect.bisect_left(self._barriers, i)
            while self._barriers_done < need:
                if self._failure is not None:
                    raise _Aborted()
                if self._clock() - t0 > self._stall_timeout_s:
                    raise RuntimeError(
                        f"actor stalled at barrier before iteration {i}")
                self._bar_cv.wait(_WAIT_TICK_S)
        return self._clock() - t0

    def _complete_barrier(self) -> None:
        with self._bar_cv:
            self._barriers_done += 1
            self._bar_cv.notify_all()

    def _abort(self, exc: BaseException) -> None:
        # publish the failure under the barrier Condition: the learner
        # reads it there, and an unlocked write could be seen torn
        # against the notify
        with self._bar_cv:
            self._failure = exc
            self._bar_cv.notify_all()
        self.queue.abort(exc)
        self._slot.abort()

    # -- the actor loop (background thread) --------------------------------

    def _actor_loop(self, base: int, iterations: int,
                    sections: SectionTimer, tracer) -> None:
        exp = self.exp
        carry = exp.carry
        try:
            for k in range(iterations):
                i = base + k
                # the flight recorder's actor track: the two wait spans
                # (barrier park + staleness gate) and the push-side
                # backpressure are the idle gaps the occupancy timeline
                # exists to show; the "actor" span is the busy lane the
                # measured-overlap summary unions against "learner"
                with tracer.span("actor_barrier_wait"):
                    self._actor_idle_s += self._wait_barriers_before(i)
                # staleness gate: may not collect batch i until the
                # learner is within `bound` versions; always take the
                # freshest publication (ISSUE: "refresh actor params
                # from the learner at each publish")
                with tracer.span("actor_gate_wait"):
                    params, version, gated = self._slot.wait_for(
                        i - self.staleness_bound)
                self._actor_idle_s += gated
                # barrier-park may have replaced the carry (resample)
                carry = exp.carry
                with tracer.span("actor", iteration=i), \
                        self.overlap.span("actor"), sections("actor"), \
                        no_implicit_transfers(), self._dispatch_lock:
                    carry, tr, last_value = self._rollout(
                        params, carry, exp.traces, self._faults)
                    # explicit hop onto the learner mesh: the queue is
                    # device-side, the learner pops ready-to-consume
                    # buffers
                    batch = (jax.device_put(tr, self._ltraj),
                             jax.device_put(last_value, self._lenv))
                    jax.block_until_ready(batch)
                exp.carry = carry
                with tracer.span("queue_push_wait"):
                    self._actor_idle_s += self.queue.put(
                        _QueueItem(index=i, version=version, batch=batch))
        except _Aborted:
            pass
        except BaseException as e:  # surface in the learner thread
            self._abort(e)

    # -- the learner loop (caller thread) -----------------------------------

    def run(self, iterations: int | None = None, log_every: int = 0,
            logger: Callable[[int, dict], None] | None = None,
            ckpt=None, ckpt_every: int = 0,
            eval_every: int = 0,
            eval_fn: "Callable[[int], dict] | None" = None,
            eval_logger: Callable[[int, dict], None] | None = None,
            telemetry=None) -> dict:
        """Run ``iterations`` overlapped actor/learner iterations; the
        hook surface (log/ckpt/eval cadences, telemetry protocol,
        summary dict) mirrors :meth:`Experiment.run`. Window streaming
        (``cfg.resample_every``) and checkpoints run at drained-queue
        barriers."""
        exp = self.exp
        cfg = exp.cfg
        iterations = iterations or cfg.iterations
        base = self._iterations_done
        history: list[dict] = []
        eval_history: list[dict] = []
        sections = (telemetry.sections if telemetry is not None
                    else SectionTimer())
        gauges = (AsyncGauges(telemetry.registry)
                  if telemetry is not None else None)
        tracer = tracer_of(telemetry)

        def is_ckpt(b: int) -> bool:
            return bool(ckpt is not None and ckpt_every
                        and ((b + 1) % ckpt_every == 0
                             or b == iterations - 1))

        def is_resample(b: int) -> bool:
            return bool(cfg.resample_every
                        and (b + 1) % cfg.resample_every == 0
                        and b != iterations - 1)

        local_barriers = sorted(b for b in range(iterations)
                                if is_ckpt(b) or is_resample(b))
        with self._bar_cv:
            self._barriers = [base + b for b in local_barriers]
            self._barriers_done = 0
            self._failure = None

        if telemetry is not None:
            telemetry.run_start(
                loop="async-experiment", config=cfg.name, algo=cfg.algo,
                iterations=iterations, n_envs=cfg.n_envs,
                steps_per_iteration=exp.steps_per_iteration,
                staleness_bound=self.staleness_bound,
                queue_capacity=self.queue_capacity,
                actor_devices=[d.id for d in self.groups.actor],
                learner_devices=[d.id for d in self.groups.learner],
                shared_group=self.groups.shared)

        t0 = time.monotonic()
        actor = threading.Thread(
            target=self._actor_loop,
            args=(base, iterations, sections, tracer),
            name="async-actor", daemon=True)
        actor.start()
        try:
            for k in range(iterations):
                b = k  # hook-facing iteration index, as in Experiment.run
                i = base + k
                if telemetry is not None:
                    telemetry.begin_iteration(b)
                with sections("queue_wait"), \
                        tracer.span("queue_pop_wait"):
                    item, waited = self.queue.get()  # jsan: disable=hung-future -- TrajectoryQueue.get is bounded by construction (stall timeout + abort wakes every waiter)
                self._learner_idle_s += waited
                if item.index != i:
                    raise RuntimeError(
                        f"queue order violation: expected batch {i}, "
                        f"got {item.index}")
                staleness = item.index - item.version
                if staleness > self.staleness_bound:
                    raise StalenessError(
                        f"batch {item.index} was collected at policy "
                        f"version {item.version} — {staleness} versions "
                        f"behind, bound is {self.staleness_bound}")
                self._staleness_last = staleness
                self._staleness_max = max(self._staleness_max, staleness)
                self._staleness_sum += staleness
                self._consumed += 1
                guard = (telemetry.dispatch(b) if telemetry is not None
                         else contextlib.nullcontext())
                tr, last_value = item.batch
                with tracer.span("learner", iteration=b), \
                        self.overlap.span("learner"), \
                        sections("learner"), guard, self._dispatch_lock:
                    # the sync loop's per-iteration split, in the same order
                    exp.key, sub = jax.random.split(exp.key)
                    state, metrics = self._learn(exp.train_state, tr,
                                                 last_value, sub)
                    params_a = jax.device_put(state.params, self._arep)
                    jax.block_until_ready(params_a)
                exp.train_state = state
                self._slot.publish(params_a, i + 1)

                want_log = bool(log_every) and (b % log_every == 0
                                                or b == iterations - 1)
                m = None
                if want_log:
                    with sections("sync"), tracer.span("sync"), \
                            self._dispatch_lock:
                        m = {k2: float(v) for k2, v in
                             jax.device_get(metrics)._asdict().items()}
                    if "rho_mean" in m:
                        self._rho_last = m["rho_mean"]
                        self._rho_max_seen = max(self._rho_max_seen,
                                                 m["rho_max"])
                    history.append({"iteration": b, **m})
                    if logger is not None:
                        logger(b, m)
                    if gauges is not None:
                        gauges.publish(
                            queue_depth=len(self.queue),
                            staleness=self._staleness_last,
                            actor_idle_s=self._actor_idle_s,
                            learner_idle_s=self._learner_idle_s,
                            overlap_s=self.overlap.overlap_s,
                            importance_ratio_mean=self._rho_last,
                            importance_ratio_max=self._rho_max_seen)
                if eval_fn is not None and eval_every and \
                        ((b + 1) % eval_every == 0 or b == iterations - 1):
                    with sections("eval"), tracer.span("eval"), \
                            self._dispatch_lock:
                        em = dict(eval_fn(b))
                    eval_history.append({"iteration": b, **em})
                    if eval_logger is not None:
                        eval_logger(b, em)
                # drained-queue barrier work (actor is parked past i)
                if is_ckpt(b):
                    with sections("ckpt"), tracer.span("ckpt"):
                        exp.save_checkpoint(
                            ckpt, meta={"iteration": b,
                                        "async_iteration": i,
                                        "staleness_bound":
                                            self.staleness_bound})
                if is_resample(b):
                    with sections("resample"), tracer.span("resample"):
                        self._resample()
                if is_ckpt(b) or is_resample(b):
                    self._complete_barrier()
                if telemetry is not None:
                    telemetry.end_iteration(
                        b, m if want_log else None,
                        exp.steps_per_iteration)
                if self._failure is not None:
                    raise self._failure
        except BaseException as e:
            self._abort(e)
            actor.join(timeout=30)
            raise
        actor.join(timeout=self._stall_timeout_s)
        if actor.is_alive():
            exc = RuntimeError("actor thread failed to drain")
            self._abort(exc)
            raise exc
        if self._failure is not None:
            raise self._failure
        jax.block_until_ready(exp.train_state.params)
        self._iterations_done = base + iterations
        wall = time.monotonic() - t0
        total_env_steps = iterations * exp.steps_per_iteration
        async_info = self.async_info()
        out = {"wall_s": wall, "iterations": iterations,
               "env_steps": total_env_steps,
               "env_steps_per_sec": total_env_steps / wall,
               "window_cursor": exp.window_cursor,
               "history": history,
               "phase_seconds": {k: round(v, 6)
                                 for k, v in sections.report().items()},
               "async": async_info}
        if eval_history:
            out["eval_history"] = eval_history
        if telemetry is not None:
            if gauges is not None:
                gauges.publish(queue_depth=len(self.queue),
                               staleness=self._staleness_last,
                               actor_idle_s=self._actor_idle_s,
                               learner_idle_s=self._learner_idle_s,
                               overlap_s=self.overlap.overlap_s,
                               importance_ratio_mean=self._rho_last,
                               importance_ratio_max=self._rho_max_seen)
            telemetry.run_end(
                iterations=iterations, wall_s=round(wall, 6),
                env_steps=total_env_steps,
                env_steps_per_sec=round(out["env_steps_per_sec"], 3),
                **{f"async_{k2}": v for k2, v in async_info.items()
                   if not isinstance(v, (list, dict))})
        return out

    def async_info(self) -> dict:
        """The engine's overlap/staleness accounting so far."""
        snap = self.overlap.snapshot()
        return {
            "staleness_bound": self.staleness_bound,
            "queue_capacity": self.queue_capacity,
            "actor_devices": [d.id for d in self.groups.actor],
            "learner_devices": [d.id for d in self.groups.learner],
            "shared_group": self.groups.shared,
            "overlap_s": snap["overlap_s"],
            "actor_busy_s": snap.get("busy_actor_s", 0.0),
            "learner_busy_s": snap.get("busy_learner_s", 0.0),
            "actor_idle_s": round(self._actor_idle_s, 6),
            "learner_idle_s": round(self._learner_idle_s, 6),
            "staleness_max": self._staleness_max,
            "staleness_mean": (self._staleness_sum / self._consumed
                               if self._consumed else 0.0),
            "importance_ratio_mean": self._rho_last,
            "importance_ratio_max": self._rho_max_seen,
        }

    def _resample(self) -> None:
        """Window streaming at a drained-queue barrier: re-cut the env
        windows and re-init the carry, keeping every placement on its
        group mesh (the sync twin is ``Experiment.advance_windows``,
        which assumes a single placement domain)."""
        exp = self.exp
        exp._cut_windows(exp.window_cursor + exp.cfg.n_envs)
        exp.key, carry_key = jax.random.split(exp.key)
        carry_key = jax.device_put(carry_key, self._arep)
        carry = init_carry(exp.env_params, exp.traces, carry_key,
                           self._faults)
        exp.carry = jax.tree.map(
            lambda new, old: jax.device_put(new, old.sharding),
            carry, exp.carry)


def _make_pop_rollout(apply_fn, env_params, n_steps,
                      with_faults: bool = False):
    """The actor half of the population step: vmap the SAME rollout the
    fused ``make_population_step`` vmaps — member params/carries mapped,
    traces broadcast (``in_axes=None``, one shared env-window set for
    fitness comparability). Per-member [P, E] fault-schedule stacks map
    over the member axis like the carries (``with_faults``)."""
    from .algos.rollout import rollout as rollout_fn

    if with_faults:
        def pop_rollout_faulty(params, carries, traces, faults):
            return jax.vmap(
                lambda p, c, t, f: rollout_fn(apply_fn, p, env_params, t,
                                              c, n_steps, f),
                in_axes=(0, 0, None, 0))(params, carries, traces, faults)

        return pop_rollout_faulty

    def pop_rollout(params, carries, traces):
        return jax.vmap(
            lambda p, c, t: rollout_fn(apply_fn, p, env_params, t, c,
                                       n_steps),
            in_axes=(0, 0, None))(params, carries, traces)

    return pop_rollout


class AsyncPopulationRunner:
    """The async engine over a :class:`~.experiment.PopulationExperiment`:
    the vmapped member ROLLOUT half runs on the actor group, the vmapped
    member LEARN half (``parallel.population.make_member_learn_step``,
    traced per-member hyperparameters and all) on the learner group,
    overlapped through the same bounded queue / staleness gate /
    barrier machinery as :class:`AsyncRunner`.

    **Why V-trace makes this row legal.** The refusal this class deletes
    (``MODE_REFUSALS`` ``async x pbt``) existed because PBT's host-side
    exploit/explore interleaves between steps AND because stale batches
    bias each member differently, corrupting the fitness comparison the
    controller ranks on. Both are now handled: exploit rounds fire at
    drained-queue BARRIERS predicted from the controller window (both
    loops agree on the schedule up front, so the actor is parked and the
    weight copy is race-free), and ``correction="vtrace"`` re-weights
    every member's targets by its own importance ratios so staleness
    shifts no member's fitness estimate.

    **Placement (v1).** Member stacks are REPLICATED on their group
    meshes (``actor_replicated`` / ``learner_replicated``); build the
    population with ``mesh=None`` and let the runner own placement.
    Sharding the member stack over a ``pop`` axis *within* each async
    group is an open end (ROADMAP) — it needs per-group meshes with a
    pop dimension plus a sharded exploit gather, and the bound-0
    bit-identity contract below is defined against the unsharded sync
    twin anyway.

    **Bound-0 contract.** ``staleness_bound=0`` reproduces the non-mesh
    ``PopulationExperiment.run`` loop bit-identically: same key-split
    program and order, same member program composition (the split
    rollout/learn halves vmap the same functions the fused
    ``make_population_step`` vmaps), same exploit schedule (the barrier
    prediction is exact, and the runner raises if the controller ever
    fires off-schedule).

    **Per-member staleness.** Batches are stacked, so every member in
    queue item ``i`` shares the item's version lag; the bookkeeping is
    still tracked per member because exploit RESETS the exploited
    members' effective lag (they restart from just-published donor
    weights). ``async_info()`` reports both the scalar aggregates and
    the per-member last/max vectors."""

    def __init__(self, pexp, groups: DeviceGroups | None = None,
                 staleness_bound: int = 1, queue_capacity: int = 2,
                 stall_timeout_s: float = 300.0):
        from .parallel.population import make_member_learn_step
        if staleness_bound < 0:
            raise ValueError(f"staleness_bound must be >= 0, got "
                             f"{staleness_bound}")
        cfg = pexp.cfg
        if pexp.mesh is not None:
            raise ValueError(
                "AsyncPopulationRunner owns device placement (member "
                "stacks replicated on the actor/learner group meshes); "
                "build the population with mesh=None. Sharding the pop "
                "axis within async groups is an open end (ROADMAP)")
        if groups is None:
            from .parallel.groups import split_mesh
            from .parallel.mesh import unified_mesh
            groups = split_mesh(unified_mesh())
        # v1 replicates both member stacks on their group meshes, so the
        # per-phase geometry checks run against a single placement domain
        validate_rollout_geometry(cfg.ppo.n_steps, cfg.n_envs, 1)
        validate_update_geometry(cfg.ppo.n_epochs, cfg.ppo.n_minibatches,
                                 cfg.ppo.minibatch_size,
                                 n_steps=cfg.ppo.n_steps,
                                 n_envs=cfg.n_envs, n_devices=1)
        on_cpu = groups.actor[0].platform == "cpu"
        self._dispatch_lock: Any = (
            threading.Lock() if on_cpu else contextlib.nullcontext())
        self.pexp = pexp
        self.groups = groups
        self.staleness_bound = staleness_bound
        self.queue_capacity = queue_capacity
        self._stall_timeout_s = stall_timeout_s
        self._clock = time.monotonic

        # adopt the population onto the group meshes (explicit placements;
        # the experiment object stays the canonical holder so
        # save/restore_checkpoint and member_eval_view work unchanged)
        self._arep = groups.actor_replicated()
        self._lrep = groups.learner_replicated()
        pexp.traces = put_global(pexp.traces, self._arep)
        pexp.carries = put_global(pexp.carries, self._arep)
        pexp.states = put_global(pexp.states, self._lrep)
        pexp.keys = jax.device_put(pexp.keys, self._lrep)
        pexp.hparams = put_global(pexp.hparams, self._lrep)
        if pexp.faults is not None:
            # the [P, E] member schedule stacks are actor-side data, like
            # the traces
            pexp.faults = put_global(pexp.faults, self._arep)

        apply_fn = pexp.apply_fn
        pop_learn = jax.vmap(make_member_learn_step(apply_fn, cfg.ppo),
                             in_axes=(0, 0, 0, 0, 0))

        # same AOT-compile + CPU donation-off reasoning as AsyncRunner
        rollout_donate = () if on_cpu else (1,)   # the carry stack
        learn_donate = () if on_cpu else (0,)     # the member-state stack
        params_a = jax.device_put(pexp.states.params, self._arep)
        rollout_jit = jax.jit(
            _make_pop_rollout(apply_fn, pexp.env_params, cfg.ppo.n_steps,
                              with_faults=pexp.faults is not None),
            donate_argnums=rollout_donate)
        rollout_args = (params_a, pexp.carries, pexp.traces)
        if pexp.faults is not None:
            rollout_args = rollout_args + (pexp.faults,)
        self._rollout = rollout_jit.lower(*rollout_args).compile()
        _, tr_s, lv_s = jax.eval_shape(rollout_jit, *rollout_args)
        tr0 = jax.device_put(jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), tr_s), self._lrep)
        lv0 = jax.device_put(jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), lv_s), self._lrep)
        subs0 = jax.device_put(
            jnp.zeros(pexp.keys.shape, pexp.keys.dtype), self._lrep)
        self._learn = jax.jit(
            pop_learn, donate_argnums=learn_donate).lower(
                pexp.states, tr0, lv0, subs0, pexp.hparams).compile()
        del tr0, lv0, subs0
        # the sync population loop's per-iteration key split — the SAME
        # jit(vmap(split)) program in the same order, for bound-0 parity
        self._split_all = jax.jit(jax.vmap(lambda k: jax.random.split(k)))

        # loop state shared across run() calls
        self._iterations_done = 0
        self._slot = _ParamSlot(
            params_a, version=0,
            clock=self._clock, stall_timeout_s=stall_timeout_s)
        self.queue = TrajectoryQueue(queue_capacity, clock=self._clock,
                                     stall_timeout_s=stall_timeout_s)
        self.overlap = OverlapMeter(clock=self._clock)
        self._bar_cv = threading.Condition()
        self._barriers: list[int] = []
        self._barriers_done = 0
        self._failure: BaseException | None = None
        self._actor_idle_s = 0.0
        self._learner_idle_s = 0.0
        self._staleness_last = 0
        self._staleness_max = 0
        self._staleness_sum = 0
        self._consumed = 0
        # per-member lag vectors: uniform per stacked item, but exploit
        # resets the exploited members' LAST lag (fresh donor weights)
        self._stale_last_pm = [0] * pexp.n_pop
        self._stale_max_pm = [0] * pexp.n_pop
        self._rho_last = 1.0
        self._rho_max_seen = 1.0

    # -- barrier plumbing (same protocol as AsyncRunner) --------------------

    def _wait_barriers_before(self, i: int) -> float:
        t0 = self._clock()
        with self._bar_cv:
            need = bisect.bisect_left(self._barriers, i)
            while self._barriers_done < need:
                if self._failure is not None:
                    raise _Aborted()
                if self._clock() - t0 > self._stall_timeout_s:
                    raise RuntimeError(
                        f"actor stalled at barrier before iteration {i}")
                self._bar_cv.wait(_WAIT_TICK_S)
        return self._clock() - t0

    def _complete_barrier(self) -> None:
        with self._bar_cv:
            self._barriers_done += 1
            self._bar_cv.notify_all()

    def _abort(self, exc: BaseException) -> None:
        # publish the failure under the barrier Condition: the learner
        # reads it there, and an unlocked write could be seen torn
        # against the notify
        with self._bar_cv:
            self._failure = exc
            self._bar_cv.notify_all()
        self.queue.abort(exc)
        self._slot.abort()

    # -- the actor loop (background thread) ---------------------------------

    def _actor_loop(self, base: int, iterations: int,
                    sections: SectionTimer, tracer) -> None:
        pexp = self.pexp
        carries = pexp.carries
        try:
            for k in range(iterations):
                i = base + k
                with tracer.span("actor_barrier_wait"):
                    self._actor_idle_s += self._wait_barriers_before(i)
                with tracer.span("actor_gate_wait"):
                    params, version, gated = self._slot.wait_for(
                        i - self.staleness_bound)
                self._actor_idle_s += gated
                carries = pexp.carries
                roll_args = (params, carries, pexp.traces)
                if pexp.faults is not None:
                    roll_args = roll_args + (pexp.faults,)
                with tracer.span("actor", iteration=i), \
                        self.overlap.span("actor"), sections("actor"), \
                        no_implicit_transfers(), self._dispatch_lock:
                    carries, tr, last_value = self._rollout(*roll_args)
                    batch = (jax.device_put(tr, self._lrep),
                             jax.device_put(last_value, self._lrep))
                    jax.block_until_ready(batch)
                pexp.carries = carries
                with tracer.span("queue_push_wait"):
                    self._actor_idle_s += self.queue.put(
                        _QueueItem(index=i, version=version, batch=batch))
        except _Aborted:
            pass
        except BaseException as e:
            self._abort(e)

    # -- the learner loop (caller thread) -----------------------------------

    def run(self, iterations: int | None = None, log_every: int = 0,
            logger: Callable[[int, dict], None] | None = None,
            ckpt=None, ckpt_every: int = 0,
            eval_every: int = 0,
            eval_fn: "Callable[[int], dict] | None" = None,
            eval_logger: Callable[[int, dict], None] | None = None,
            telemetry=None) -> dict:
        """Run ``iterations`` overlapped population iterations; the hook
        surface mirrors :meth:`PopulationExperiment.run` minus
        watchdog/injector (chaos drills stay on the sync loop). PBT
        exploit/explore and checkpoints run at drained-queue barriers."""
        pexp = self.pexp
        cfg = pexp.cfg
        ctrl = pexp.controller
        iterations = iterations or cfg.iterations
        base = self._iterations_done
        history: list[dict] = []
        eval_history: list[dict] = []
        sections = (telemetry.sections if telemetry is not None
                    else SectionTimer())
        gauges = (AsyncGauges(telemetry.registry)
                  if telemetry is not None else None)
        tracer = tracer_of(telemetry)

        def is_ckpt(b: int) -> bool:
            return bool(ckpt is not None and ckpt_every
                        and ((b + 1) % ckpt_every == 0
                             or b == iterations - 1))

        # predict the controller's exploit iterations so both loops agree
        # on the barrier set up front: maybe_update consults ONLY its
        # recorded-window count (never the iteration number) and resets
        # the window on fire, so with `window` records carried in from
        # earlier run() calls, local iteration b fires exactly when
        # (window + b + 1) % ready_iters == 0
        window = ctrl._fitness_n + len(ctrl._pending)
        ready = ctrl.cfg.ready_iters

        def is_exploit(b: int) -> bool:
            return (window + b + 1) % ready == 0

        local_barriers = sorted(b for b in range(iterations)
                                if is_ckpt(b) or is_exploit(b))
        with self._bar_cv:
            self._barriers = [base + b for b in local_barriers]
            self._barriers_done = 0
            self._failure = None

        if telemetry is not None:
            telemetry.run_start(
                loop="async-population", config=cfg.name,
                n_pop=pexp.n_pop, iterations=iterations,
                n_envs=cfg.n_envs,
                steps_per_iteration=pexp.steps_per_iteration,
                staleness_bound=self.staleness_bound,
                queue_capacity=self.queue_capacity,
                actor_devices=[d.id for d in self.groups.actor],
                learner_devices=[d.id for d in self.groups.learner],
                shared_group=self.groups.shared)

        t0 = time.monotonic()
        actor = threading.Thread(
            target=self._actor_loop,
            args=(base, iterations, sections, tracer),
            name="async-pop-actor", daemon=True)
        actor.start()
        try:
            for k in range(iterations):
                b = k
                i = base + k
                if telemetry is not None:
                    telemetry.begin_iteration(b)
                with sections("queue_wait"), \
                        tracer.span("queue_pop_wait"):
                    item, waited = self.queue.get()  # jsan: disable=hung-future -- TrajectoryQueue.get is bounded by construction (stall timeout + abort wakes every waiter)
                self._learner_idle_s += waited
                if item.index != i:
                    raise RuntimeError(
                        f"queue order violation: expected batch {i}, "
                        f"got {item.index}")
                staleness = item.index - item.version
                if staleness > self.staleness_bound:
                    raise StalenessError(
                        f"batch {item.index} was collected at policy "
                        f"version {item.version} — {staleness} versions "
                        f"behind, bound is {self.staleness_bound}")
                self._staleness_last = staleness
                self._staleness_max = max(self._staleness_max, staleness)
                self._staleness_sum += staleness
                self._consumed += 1
                for p in range(pexp.n_pop):
                    self._stale_last_pm[p] = staleness
                    self._stale_max_pm[p] = max(self._stale_max_pm[p],
                                                staleness)
                guard = (telemetry.dispatch(b) if telemetry is not None
                         else contextlib.nullcontext())
                tr, last_value = item.batch
                with tracer.span("learner", iteration=b), \
                        self.overlap.span("learner"), \
                        sections("learner"), guard, self._dispatch_lock:
                    # the sync population loop's per-iteration split,
                    # same program and order
                    both = self._split_all(pexp.keys)
                    keys2, subs = both[:, 0], both[:, 1]
                    states, metrics = self._learn(
                        pexp.states, tr, last_value, subs, pexp.hparams)
                    params_a = jax.device_put(states.params, self._arep)
                    jax.block_until_ready(params_a)
                pexp.keys = keys2
                pexp.states = states
                self._slot.publish(params_a, i + 1)

                # PBT bookkeeping every iteration, as in the sync loop:
                # record is a device-array append (no sync), maybe_update
                # fires only at the barrier-predicted iterations — if it
                # ever fires off-schedule the actor is NOT parked, so
                # fail loudly rather than race the weight copy
                ctrl.record(metrics.mean_reward)
                out = ctrl.maybe_update(i, pexp.states, pexp.hparams)
                if (out is not None) != is_exploit(b):
                    raise RuntimeError(
                        f"PBT exploit fired off the predicted barrier "
                        f"schedule at iteration {b} (window={window}, "
                        f"ready_iters={ready}) — controller state was "
                        f"mutated outside the runner")
                if out is not None:
                    states2, hparams2, decision = out
                    with sections("pbt"), tracer.span("pbt_exploit"), \
                            self._dispatch_lock:
                        # the exploit gather pins its outputs to the
                        # input (learner) shardings; the host-side
                        # explore hands back fresh uncommitted arrays
                        pexp.states = states2
                        pexp.hparams = put_global(hparams2, self._lrep)
                        params_a = jax.device_put(pexp.states.params,
                                                  self._arep)
                        jax.block_until_ready(params_a)
                    # re-publish the exploited weights under the SAME
                    # version: the parked actor then collects batch i+1
                    # with post-exploit params, exactly like the sync loop
                    self._slot.publish(params_a, i + 1)
                    exploited = [bool(x) for x in decision.exploited]
                    for p, ex in enumerate(exploited):
                        if ex:
                            self._stale_last_pm[p] = 0
                    if telemetry is not None:
                        telemetry.emit(
                            "pbt_exploit", iteration=b,
                            exploited=int(sum(exploited)),
                            src=[int(s) for s in decision.src])

                want_log = bool(log_every) and (b % log_every == 0
                                                or b == iterations - 1)
                m = None
                if want_log:
                    # ONE batched device_get for the whole [P]-metrics
                    # tuple, flattened to suffixed scalar columns + _mean
                    # (same CSV schema as the sync population loop)
                    m = {}
                    with sections("sync"), tracer.span("sync"), \
                            self._dispatch_lock:
                        got = jax.device_get(metrics)._asdict()
                    for k2, v in got.items():
                        vals = [float(x) for x in v]
                        m.update({f"{k2}_{p}": x
                                  for p, x in enumerate(vals)})
                        m[f"{k2}_mean"] = sum(vals) / len(vals)
                    if "rho_mean_mean" in m:
                        self._rho_last = m["rho_mean_mean"]
                        self._rho_max_seen = max(
                            self._rho_max_seen,
                            max(float(x) for x in got["rho_max"]))
                    history.append({"iteration": b, **m})
                    if logger is not None:
                        logger(b, m)
                    if gauges is not None:
                        gauges.publish(
                            queue_depth=len(self.queue),
                            staleness=self._staleness_last,
                            actor_idle_s=self._actor_idle_s,
                            learner_idle_s=self._learner_idle_s,
                            overlap_s=self.overlap.overlap_s,
                            importance_ratio_mean=self._rho_last,
                            importance_ratio_max=self._rho_max_seen)
                if eval_fn is not None and eval_every and \
                        ((b + 1) % eval_every == 0 or b == iterations - 1):
                    with sections("eval"), tracer.span("eval"), \
                            self._dispatch_lock:
                        em = dict(eval_fn(b))
                    eval_history.append({"iteration": b, **em})
                    if eval_logger is not None:
                        eval_logger(b, em)
                if is_ckpt(b):
                    with sections("ckpt"), tracer.span("ckpt"):
                        pexp.save_checkpoint(
                            ckpt, meta={"iteration": b,
                                        "async_iteration": i,
                                        "staleness_bound":
                                            self.staleness_bound})
                if is_ckpt(b) or is_exploit(b):
                    self._complete_barrier()
                if telemetry is not None:
                    telemetry.end_iteration(
                        b, m if want_log else None,
                        pexp.steps_per_iteration)
                if self._failure is not None:
                    raise self._failure
        except BaseException as e:
            self._abort(e)
            actor.join(timeout=30)
            raise
        actor.join(timeout=self._stall_timeout_s)
        if actor.is_alive():
            exc = RuntimeError("actor thread failed to drain")
            self._abort(exc)
            raise exc
        if self._failure is not None:
            raise self._failure
        jax.block_until_ready(pexp.states.params)
        self._iterations_done = base + iterations
        wall = time.monotonic() - t0
        total_env_steps = iterations * pexp.steps_per_iteration
        async_info = self.async_info()
        out = {"wall_s": wall, "iterations": iterations,
               "env_steps": total_env_steps,
               "env_steps_per_sec": total_env_steps / wall,
               "final_fitness": [float(f) for f in ctrl.mean_fitness],
               "pbt_events": len(ctrl.history),
               "history": history,
               "phase_seconds": {k2: round(v, 6)
                                 for k2, v in sections.report().items()},
               "async": async_info}
        if eval_history:
            out["eval_history"] = eval_history
        if telemetry is not None:
            if gauges is not None:
                gauges.publish(queue_depth=len(self.queue),
                               staleness=self._staleness_last,
                               actor_idle_s=self._actor_idle_s,
                               learner_idle_s=self._learner_idle_s,
                               overlap_s=self.overlap.overlap_s,
                               importance_ratio_mean=self._rho_last,
                               importance_ratio_max=self._rho_max_seen)
            telemetry.run_end(
                iterations=iterations, wall_s=round(wall, 6),
                env_steps=total_env_steps,
                env_steps_per_sec=round(out["env_steps_per_sec"], 3),
                pbt_events=len(ctrl.history),
                **{f"async_{k2}": v for k2, v in async_info.items()
                   if not isinstance(v, (list, dict))})
        return out

    def async_info(self) -> dict:
        """Overlap/staleness accounting, including the per-member lag
        vectors (uniform per stacked batch; exploit resets the exploited
        members' LAST lag)."""
        snap = self.overlap.snapshot()
        return {
            "staleness_bound": self.staleness_bound,
            "queue_capacity": self.queue_capacity,
            "actor_devices": [d.id for d in self.groups.actor],
            "learner_devices": [d.id for d in self.groups.learner],
            "shared_group": self.groups.shared,
            "overlap_s": snap["overlap_s"],
            "actor_busy_s": snap.get("busy_actor_s", 0.0),
            "learner_busy_s": snap.get("busy_learner_s", 0.0),
            "actor_idle_s": round(self._actor_idle_s, 6),
            "learner_idle_s": round(self._learner_idle_s, 6),
            "staleness_max": self._staleness_max,
            "staleness_mean": (self._staleness_sum / self._consumed
                               if self._consumed else 0.0),
            "staleness_last_per_member": list(self._stale_last_pm),
            "staleness_max_per_member": list(self._stale_max_pm),
            "importance_ratio_mean": self._rho_last,
            "importance_ratio_max": self._rho_max_seen,
        }
