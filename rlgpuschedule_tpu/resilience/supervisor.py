"""Elastic gang supervision (SURVEY.md §5 "Failure detection / elastic
recovery"): the detect → decide → relaunch loop, extracted from
``__graft_entry__.dryrun_multihost_supervised`` into a reusable
abstraction.

Podracer-style gang architectures treat the accelerator gang as a
resizable resource; this module makes the recovery path treat it the
same way. The pieces:

- :class:`Launcher` — pluggable "how do I start a gang" interface. The
  subprocess gang of the CPU dryrun (:class:`SubprocessGangLauncher`) is
  one implementation; a GKE/ray pod launcher is another Launcher away
  and changes nothing above it.
- :class:`RestartPolicy` — exponential backoff with deterministic
  jitter, a ``max_restarts`` budget, and a restart-storm guard: a
  failure that lands within the backoff window of the previous one
  (i.e. the gang died ~immediately after relaunch) charges DOUBLE
  against the budget, so a crash-looping gang terminates early instead
  of burning the whole budget at full speed.
- :class:`Supervisor` — owns the loop. Detection is exit codes (the
  fast signal) plus heartbeat staleness (the general one — a dead rank
  leaves its PEERS silently blocked inside the collective, so liveness
  must be observed from outside the gang). Decision: a rank that exits
  with a code in ``permanent_exit_codes`` (``faults.LOSE_RANK_EXIT``)
  is PERMANENTLY lost — the gang relaunches shrunk to the surviving
  world size, each new rank restoring a surviving old rank's
  checkpoint (shrink-to-fit); any other death restarts at the same
  size from the gang-wide minimum completed step. Termination is a
  :class:`SupervisorResult` that always says WHY: ``completed``, or
  ``gave_up`` with the budget/floor reason spelled out.

The supervisor never inspects jax state — it sees processes, exit
codes, heartbeat files and checkpoint sidecars, which is exactly what a
production pod supervisor sees.
"""
from __future__ import annotations

import dataclasses
import os
import random
import socket
import subprocess
import sys
import tempfile
import time
from typing import Callable, Sequence

from .faults import LOSE_RANK_EXIT
from .heartbeat import HeartbeatMonitor


class SupervisorTimeout(RuntimeError):
    """The overall deadline elapsed with a gang still running (a hang the
    heartbeat timeout did not attribute to any single rank)."""


@dataclasses.dataclass(frozen=True)
class LaunchPlan:
    """One (re)launch decision. ``restore_ranks`` maps each NEW rank i to
    the OLD rank whose checkpoint it must restore (shrink-to-fit: new
    rank i resumes from ``restore_ranks[i]``'s files); ``None`` means
    identity (every rank restores its own)."""
    world_size: int
    attempt: int = 0                       # 0 = first launch
    resume_step: int | None = None         # None = fresh start
    restore_ranks: tuple[int, ...] | None = None


@dataclasses.dataclass
class SupervisorEvent:
    """One detected failure and what it cost."""
    attempt: int
    world_size: int
    rank: int
    detected_by: str       # "exit=N" | "heartbeat>Ts"
    permanent: bool
    charge: int            # 1, or 2 when the storm guard doubled it


@dataclasses.dataclass
class SupervisorResult:
    """Terminal state of a supervised run. ``outcome`` is ``"completed"``
    or ``"gave_up"``; ``reason`` spells out why a run gave up (budget
    exhausted, world floor) and is ``None`` on success."""
    outcome: str
    reason: str | None
    restarts: int
    world_size: int
    resume_step: int | None
    detected_by: str | None
    outputs: list[str]
    events: list[SupervisorEvent]
    budget_spent: int
    storm_charges: int

    @property
    def shrunk(self) -> bool:
        return any(e.permanent for e in self.events)


class Gang:
    """A launched gang. ``poll()`` returns one exit code per rank (None =
    still running); ``kill()`` tears every rank down; ``outputs()``
    returns each rank's full captured output (diagnostics +
    report-parsing)."""

    def poll(self) -> list[int | None]:
        raise NotImplementedError

    def kill(self) -> None:
        raise NotImplementedError

    def outputs(self) -> list[str]:
        raise NotImplementedError

    def tails(self, n: int = 500) -> list[str]:
        return [out[-n:] for out in self.outputs()]


class Launcher:
    """How gangs start and where their durable progress lives. The
    supervisor only ever calls these three methods — swapping the
    subprocess gang for a pod launcher is one subclass."""

    world_size: int   # the initial (full) world size

    def launch(self, plan: LaunchPlan) -> Gang:
        raise NotImplementedError

    def completed_steps(self, ranks: Sequence[int]) -> dict[int, int]:
        """{rank: last durably completed step} for the ranks that have
        one. Ranks with no durable checkpoint are simply absent."""
        raise NotImplementedError


class RestartPolicy:
    """Restart budget + exponential backoff + deterministic jitter + the
    restart-storm guard.

    A failure is "stormy" when it lands within ``storm_window_s`` of the
    previous failure (default: the backoff delay just applied plus one
    base backoff — i.e. the gang died about as fast as it came up) and
    charges 2 against ``max_restarts`` instead of 1. ``exhausted()``
    is true once charges EXCEED ``max_restarts`` (a budget of N allows N
    healthy restarts).

    Jitter is drawn from a seeded PRNG so a supervised run is exactly
    reproducible; distinct supervisors should get distinct
    ``jitter_seed``s (that is the point of jitter — decorrelating
    thundering-herd relaunches)."""

    def __init__(self, max_restarts: int, backoff_s: float = 1.0,
                 backoff_max_s: float = 30.0, jitter_frac: float = 0.25,
                 jitter_seed: int = 0,
                 storm_window_s: float | None = None,
                 clock: Callable[[], float] = time.monotonic):
        if max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
        self.max_restarts = max_restarts
        self.backoff_s = backoff_s
        self.backoff_max_s = backoff_max_s
        self.jitter_frac = jitter_frac
        self.storm_window_s = storm_window_s
        self._rng = random.Random(jitter_seed)
        self._clock = clock
        self.failures = 0          # failures observed
        self.spent = 0             # budget charges (>= failures)
        self.storm_charges = 0     # how many failures were double-charged
        self._last_failure_t: float | None = None
        self._last_delay = 0.0

    def record_failure(self) -> int:
        """Account one detected failure; returns the charge (1 or 2)."""
        now = self._clock()
        window = (self.storm_window_s if self.storm_window_s is not None
                  else self._last_delay + self.backoff_s)
        charge = 1
        if (self._last_failure_t is not None
                and now - self._last_failure_t <= window):
            charge = 2
            self.storm_charges += 1
        self.failures += 1
        self.spent += charge
        self._last_failure_t = now
        return charge

    def exhausted(self) -> bool:
        return self.spent > self.max_restarts

    def next_delay(self) -> float:
        """Backoff before the next relaunch: exponential in the failure
        count, capped, jittered upward by up to ``jitter_frac``."""
        base = min(self.backoff_s * 2 ** max(self.failures - 1, 0),
                   self.backoff_max_s)
        self._last_delay = base * (1.0 + self.jitter_frac
                                   * self._rng.random())
        return self._last_delay


@dataclasses.dataclass
class _Failure:
    rank: int
    detected_by: str
    permanent: bool


class Supervisor:
    """Drives one supervised run to a terminal state. See module
    docstring for the loop; ``monitor_factory(world_size)`` builds the
    heartbeat monitor for each (re)launch (fresh monitor = fresh
    missing-file grace window at the CURRENT world size), or ``None``
    for exit-code-only detection (unit tests with fake launchers)."""

    def __init__(self, launcher: Launcher, policy: RestartPolicy, *,
                 monitor_factory: Callable[[int], HeartbeatMonitor]
                 | None = None,
                 min_world: int = 1,
                 permanent_exit_codes: tuple[int, ...] = (LOSE_RANK_EXIT,),
                 deadline_s: float = 900.0, poll_interval_s: float = 0.2,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic,
                 log: Callable[[str], None] | None = None, bus=None):
        self.launcher = launcher
        self.policy = policy
        self.monitor_factory = monitor_factory
        self.min_world = min_world
        self.permanent_exit_codes = tuple(permanent_exit_codes)
        self.deadline_s = deadline_s
        self.poll_interval_s = poll_interval_s
        self._sleep = sleep
        self._clock = clock
        self._log = log or (lambda msg: print(msg, flush=True))
        # obs.EventBus (or None): every detect->decide->relaunch step
        # lands on the merged run timeline, so the post-mortem shows the
        # same story SupervisorResult summarizes — launch attempts, which
        # rank died how, restart-vs-shrink decisions, the terminal reason
        self._bus = bus

    def _emit(self, kind: str, **fields) -> None:
        if self._bus is not None:
            self._bus.emit(kind, **fields)

    def run(self) -> SupervisorResult:
        deadline = self._clock() + self.deadline_s
        world = self.launcher.world_size
        plan = LaunchPlan(world_size=world)
        events: list[SupervisorEvent] = []

        def result(outcome, reason, outputs, detected_by):
            return SupervisorResult(
                outcome=outcome, reason=reason, restarts=plan.attempt,
                world_size=world, resume_step=plan.resume_step,
                detected_by=detected_by, outputs=outputs, events=events,
                budget_spent=self.policy.spent,
                storm_charges=self.policy.storm_charges)

        while True:
            self._emit("gang_launch", attempt=plan.attempt,
                       world_size=plan.world_size,
                       resume_step=plan.resume_step,
                       restore_ranks=(list(plan.restore_ranks)
                                      if plan.restore_ranks is not None
                                      else None))
            gang = self.launcher.launch(plan)
            monitor = (self.monitor_factory(world)
                       if self.monitor_factory else None)
            failure = self._watch(gang, monitor, deadline)
            if failure is None:
                self._emit("supervisor_done", outcome="completed",
                           reason=None, restarts=plan.attempt,
                           world_size=world,
                           budget_spent=self.policy.spent,
                           storm_charges=self.policy.storm_charges)
                return result("completed", None, gang.outputs(),
                              events[-1].detected_by if events else None)
            gang.kill()
            charge = self.policy.record_failure()
            events.append(SupervisorEvent(
                attempt=plan.attempt, world_size=world, rank=failure.rank,
                detected_by=failure.detected_by,
                permanent=failure.permanent, charge=charge))
            self._emit("rank_failure", attempt=plan.attempt,
                       world_size=world, failed_rank=failure.rank,
                       detected_by=failure.detected_by,
                       permanent=failure.permanent, charge=charge)
            if self.policy.exhausted():
                storm = (f", {self.policy.storm_charges} storm-doubled"
                         if self.policy.storm_charges else "")
                reason = (
                    f"restart budget exhausted: {self.policy.failures} "
                    f"failures charged {self.policy.spent} against "
                    f"max_restarts={self.policy.max_restarts}{storm}; "
                    f"last: rank {failure.rank} ({failure.detected_by})")
                self._log(f"supervisor: giving up — {reason}")
                self._emit("supervisor_done", outcome="gave_up",
                           reason=reason, restarts=plan.attempt,
                           world_size=world,
                           budget_spent=self.policy.spent,
                           storm_charges=self.policy.storm_charges)
                return result("gave_up", reason, gang.outputs(),
                              failure.detected_by)
            if failure.permanent:
                survivors = [r for r in range(world) if r != failure.rank]
                if len(survivors) < self.min_world:
                    reason = (
                        f"rank {failure.rank} permanently lost "
                        f"({failure.detected_by}) at world size {world}; "
                        f"surviving world {len(survivors)} is below "
                        f"min_world={self.min_world}")
                    self._log(f"supervisor: giving up — {reason}")
                    self._emit("supervisor_done", outcome="gave_up",
                               reason=reason, restarts=plan.attempt,
                               world_size=world,
                               budget_spent=self.policy.spent,
                               storm_charges=self.policy.storm_charges)
                    return result("gave_up", reason, gang.outputs(),
                                  failure.detected_by)
                done = self.launcher.completed_steps(survivors)
                if set(done) >= set(survivors):
                    resume = min(done[r] for r in survivors)
                    restore = tuple(survivors)
                else:
                    resume, restore = None, None   # fresh, but smaller
                self._emit("gang_shrink", from_world=world,
                           to_world=len(survivors),
                           lost_rank=failure.rank, resume_step=resume,
                           restore_ranks=(list(restore)
                                          if restore is not None
                                          else None))
                world = len(survivors)
                self._log(
                    f"supervisor: rank {failure.rank} permanently lost "
                    f"({failure.detected_by}); shrinking gang to world "
                    f"size {world}"
                    + (f", resuming from checkpoint step {resume}"
                       if resume is not None else ", restarting fresh"))
            else:
                done = self.launcher.completed_steps(list(range(world)))
                if len(done) == world:
                    resume, restore = min(done.values()), None
                    self._emit("gang_restart", world_size=world,
                               resume_step=resume)
                    self._log(
                        f"supervisor: rank {failure.rank} dead "
                        f"({failure.detected_by}); restarting gang from "
                        f"checkpoint step {resume}")
                else:
                    # a rank died before every rank had a durable
                    # checkpoint: restart FRESH — a resume step would
                    # point ranks at files that do not exist and crash
                    # the restarted gang
                    resume, restore = None, None
                    self._emit("gang_restart", world_size=world,
                               resume_step=None)
                    self._log(
                        f"supervisor: rank {failure.rank} dead "
                        f"({failure.detected_by}) before all ranks "
                        f"checkpointed ({len(done)}/{world}); restarting "
                        f"fresh")
            self._sleep(self.policy.next_delay())
            plan = LaunchPlan(world_size=world, attempt=plan.attempt + 1,
                              resume_step=resume, restore_ranks=restore)

    def _watch(self, gang: Gang, monitor, deadline) -> _Failure | None:
        """Block until the gang completes (``None``) or one failure is
        attributed. Raises :class:`SupervisorTimeout` at the deadline."""
        while True:
            if self._clock() > deadline:
                gang.kill()
                raise SupervisorTimeout(
                    f"supervised run exceeded its {self.deadline_s:.0f}s "
                    f"deadline; rank logs: " + " | ".join(gang.tails()))
            codes = gang.poll()
            if all(c == 0 for c in codes):
                return None
            bad = [(r, c) for r, c in enumerate(codes)
                   if c is not None and c != 0]
            if bad:
                # a permanent-loss exit wins attribution: peers torn down
                # by the death often exit non-zero too, and restarting
                # same-size on a peer's code would miss the shrink
                perm = [(r, c) for r, c in bad
                        if c in self.permanent_exit_codes]
                rank, code = perm[0] if perm else bad[0]
                return _Failure(rank=rank, detected_by=f"exit={code}",
                                permanent=bool(perm))
            if monitor is not None:
                stale = monitor.stale_ranks()
                if stale:
                    return _Failure(
                        rank=stale[0],
                        detected_by=f"heartbeat>{monitor.timeout_s}s",
                        permanent=False)
            self._sleep(self.poll_interval_s)


class SubprocessGang(Gang):
    def __init__(self, procs, logs):
        self._procs = procs
        self._logs = logs

    def poll(self) -> list[int | None]:
        return [p.poll() for p in self._procs]

    def kill(self) -> None:
        for p in self._procs:
            if p.poll() is None:
                p.kill()
                p.wait()

    def outputs(self) -> list[str]:
        outs = []
        for log in self._logs:
            log.flush()
            with open(log.name) as f:
                outs.append(f.read())
        return outs


class SubprocessGangLauncher(Launcher):
    """The CPU dryrun's gang: N fresh ``multihost_worker`` processes on
    localhost, heartbeats + per-rank npz checkpoints under ``base_dir``.
    Caller owns ``base_dir`` (and its cleanup) and supplies the scrubbed
    child environment (the rig-specific hygiene — compile-cache scrub,
    platform pins — stays with the caller; see ``__graft_entry__``).

    Fault flags are armed only on full-world fresh launches: a resumed
    or shrunk gang re-armed with ``kill-rank``/``lose-rank`` would
    re-fire the drill forever (each relaunch is a fresh process with
    fresh ``FaultSpec.fired`` state)."""

    def __init__(self, *, n_processes: int, devices_per_process: int,
                 steps: int, env: dict, base_dir: str,
                 faults: Sequence[str] = (), repo_root: str | None = None,
                 obs_dir: str | None = None):
        self.world_size = n_processes
        self._initial_world = n_processes
        self.devices_per_process = devices_per_process
        self.steps = steps
        self.env = env
        self.base_dir = base_dir
        self.faults = tuple(faults)
        self.repo_root = repo_root or os.getcwd()
        # per-rank event streams land here (workers get --obs-dir); a
        # relaunched rank APPENDS to its stream, so one file tells the
        # rank's whole story across attempts
        self.obs_dir = obs_dir
        self.hb_dir = os.path.join(base_dir, "hb")
        self.ckpt_dir = os.path.join(base_dir, "ckpt")
        os.makedirs(self.hb_dir, exist_ok=True)
        os.makedirs(self.ckpt_dir, exist_ok=True)

    def launch(self, plan: LaunchPlan) -> SubprocessGang:
        # stale heartbeat files from the previous gang instance would be
        # judged against the new monitor's clock; drop them so every
        # launch starts inside the missing-file grace window
        for name in os.listdir(self.hb_dir):
            if name.endswith(".hb"):
                os.unlink(os.path.join(self.hb_dir, name))
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        procs, logs = [], []
        for pid in range(plan.world_size):
            cmd = [sys.executable, "-m",
                   "rlgpuschedule_tpu.parallel.multihost_worker",
                   "--coordinator", f"127.0.0.1:{port}",
                   "--num-procs", str(plan.world_size),
                   "--proc-id", str(pid),
                   "--devices-per-proc", str(self.devices_per_process),
                   "--steps", str(self.steps),
                   "--heartbeat-dir", self.hb_dir,
                   "--ckpt-dir", self.ckpt_dir, "--no-pbt-check"]
            if self.obs_dir is not None:
                cmd += ["--obs-dir", self.obs_dir]
            if plan.resume_step is not None:
                cmd += ["--resume-step", str(plan.resume_step)]
                if plan.restore_ranks is not None:
                    cmd += ["--restore-rank",
                            str(plan.restore_ranks[pid])]
            if (plan.resume_step is None
                    and plan.world_size == self._initial_world):
                for f in self.faults:
                    cmd += ["--fault", f]
            log = tempfile.NamedTemporaryFile(
                "w+", suffix=f".a{plan.attempt}.rank{pid}.log",
                delete=False, dir=self.base_dir)
            logs.append(log)
            procs.append(subprocess.Popen(
                cmd, stdout=log, stderr=subprocess.STDOUT, text=True,
                env=self.env, cwd=self.repo_root))
        return SubprocessGang(procs, logs)

    def completed_steps(self, ranks: Sequence[int]) -> dict[int, int]:
        out = {}
        for r in ranks:
            try:
                path = os.path.join(self.ckpt_dir, f"rank{r}.step")
                with open(path) as f:
                    out[r] = int(f.read().strip())
            except (FileNotFoundError, ValueError):
                pass
        return out
