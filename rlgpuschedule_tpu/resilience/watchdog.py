"""Divergence watchdog (SURVEY.md §5 "Failure detection").

A NaN in one PPO update silently poisons every later iteration — the run
keeps "training" on garbage until someone reads the curves. The watchdog
checks each iteration's materialized metrics (the one host sync the
per-iteration loop already pays when logging) and, on divergence, rolls
the experiment back to the last good Orbax checkpoint with a
deterministically decayed learning rate; after ``max_rollbacks`` it gives
up with a clean :class:`DivergenceError` instead of looping forever.

Determinism: the decay schedule is ``lr_decay ** n_rollbacks`` and the
retry's RNG stream is ``fold_in(restored_key, n_rollbacks)`` — a faulted
run recovers the same way every time it is replayed.
"""
from __future__ import annotations

import dataclasses
import math
import sys
from typing import Any

import numpy as np


class DivergenceError(RuntimeError):
    """The run diverged more times than ``max_rollbacks`` allows."""


@dataclasses.dataclass
class RollbackEvent:
    """One recovery action, as it appears in the run summary/log."""
    iteration: int           # iteration whose metrics tripped the check
    restored_step: int | None  # checkpoint step actually restored
    resume_iteration: int    # loop index training resumes from
    n_rollback: int          # 1-based rollback counter
    lr_scale: float          # LR multiplier now in effect
    reason: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class DivergenceWatchdog:
    """Per-iteration divergence detection + checkpoint rollback.

    >>> wd = DivergenceWatchdog(max_rollbacks=3)
    >>> out = exp.run(..., ckpt=ckpt, ckpt_every=10, watchdog=wd)

    ``check`` flags (a) any non-finite metric and (b) a total_loss whose
    magnitude exceeds ``blowup_factor`` × the running loss EMA — the
    "finite but exploding" precursor a plain NaN check misses. The EMA
    resets on rollback so the retried trajectory is judged afresh.
    """

    def __init__(self, max_rollbacks: int = 3, lr_decay: float = 0.5,
                 blowup_factor: float = 1e4, ema_decay: float = 0.9,
                 bus=None):
        if max_rollbacks < 0:
            raise ValueError(f"max_rollbacks must be >= 0, "
                             f"got {max_rollbacks}")
        self.max_rollbacks = max_rollbacks
        self.lr_decay = lr_decay
        self.blowup_factor = blowup_factor
        self.ema_decay = ema_decay
        self.n_rollbacks = 0
        self.events: list[RollbackEvent] = []
        self._loss_ema: float | None = None
        self._bus = bus   # obs.EventBus (or None): rollback timeline

    def check(self, metrics: dict[str, float]) -> str | None:
        """Reason string if this iteration's metrics look divergent, else
        None (and the loss EMA advances)."""
        for k, v in metrics.items():
            if not math.isfinite(v):
                return f"non-finite {k}={v}"
        loss = metrics.get("total_loss")
        if loss is not None:
            if self._loss_ema is not None and \
                    abs(loss) > self.blowup_factor * max(
                        abs(self._loss_ema), 1.0):
                return (f"loss blow-up: |total_loss|={abs(loss):.3g} > "
                        f"{self.blowup_factor:g} x ema "
                        f"{abs(self._loss_ema):.3g}")
            self._loss_ema = (loss if self._loss_ema is None else
                              self.ema_decay * self._loss_ema
                              + (1 - self.ema_decay) * loss)
        return None

    def check_population(self, fitness: Any) -> str | None:
        """Population variant: a SINGLE dead member is PBT's job (exploit
        re-seeds it from the best member); the watchdog only rolls back
        the catastrophic case where NO member has finite fitness — there
        is nobody left to re-seed from."""
        # ONE batched device read: per-element float() on a device array
        # issues a separate blocking transfer per member, every iteration
        # (jsan host-sync review, PR 3)
        vals = [float(v) for v in np.asarray(fitness)]
        if vals and not any(math.isfinite(v) for v in vals):
            return f"all {len(vals)} members non-finite (fitness={vals})"
        return None

    def rollback(self, exp: Any, ckpt: Any, iteration: int,
                 reason: str) -> RollbackEvent:
        """Roll ``exp`` back to the last good checkpoint (integrity
        fallback included — a corrupted latest step falls through to the
        previous retained one), decay the LR, fold the rollback count
        into the RNG key, and return the event. Raises
        :class:`DivergenceError` once ``max_rollbacks`` is exhausted."""
        if self.n_rollbacks >= self.max_rollbacks:
            raise DivergenceError(
                f"diverged at iteration {iteration} ({reason}) after "
                f"{self.n_rollbacks} rollback(s); max_rollbacks="
                f"{self.max_rollbacks} exhausted — giving up cleanly")
        self.n_rollbacks += 1
        # settle async saves first: the most recent periodic save may
        # still be in flight, and rolling back past it would silently
        # lose good iterations
        ckpt.wait()
        meta = exp.restore_checkpoint(ckpt)
        scale = self.lr_decay ** self.n_rollbacks
        exp.scale_lr(scale)
        exp.fold_key(self.n_rollbacks)
        resume = int((meta or {}).get("iteration", -1)) + 1
        self._loss_ema = None
        event = RollbackEvent(
            iteration=iteration, restored_step=ckpt.last_restored_step,
            resume_iteration=resume, n_rollback=self.n_rollbacks,
            lr_scale=scale, reason=reason)
        self.events.append(event)
        if self._bus is not None:
            self._bus.emit("rollback", **event.as_dict())
        print(f"watchdog: {reason} at iteration {iteration} -> rolled "
              f"back to checkpoint step {event.restored_step} (resume "
              f"iteration {resume}, lr x{scale:g}, rollback "
              f"{self.n_rollbacks}/{self.max_rollbacks})",
              file=sys.stderr, flush=True)
        return event
