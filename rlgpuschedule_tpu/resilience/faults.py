"""Deterministic fault injection (SURVEY.md §5 "fault injection").

Faults are fully specified by their spec string — no RNG — so a faulted
run is exactly reproducible and the recovery paths can be asserted in
tier-1 CPU tests. Three kinds, one per recovery path:

- ``nan-grad@K`` — at training iteration K, poison the train state's
  params with NaN and flag the iteration's metrics non-finite, as if one
  PPO update had applied a NaN gradient. Recovery: the
  :class:`~.watchdog.DivergenceWatchdog` rolls back to the last good
  checkpoint. Against a PBT population, ``:rank=M`` selects WHICH member
  is poisoned (default 0); recovery is then the exploit re-seed of the
  dead member (``parallel.pbt.exploit_explore``).
- ``corrupt-ckpt@K`` — truncate the data files of the checkpoint written
  at iteration K, right after its save. Recovery:
  ``Checkpointer.restore``'s integrity fallback to the previous retained
  step.
- ``kill-rank@T[:rank=R]`` — multihost: rank R calls ``os._exit`` right
  before train step T (before entering the step's collective, so every
  rank's last durable checkpoint is step T-1), exit code
  :data:`KILL_RANK_EXIT` — a RESTARTABLE death. Recovery: the
  :class:`~.supervisor.Supervisor` restarts the gang at the same world
  size from the minimum completed checkpoint step. Refused by the
  single-process train CLI.
- ``lose-rank@T[:rank=R]`` — multihost: same kill-before-the-collective
  semantics, but exit code :data:`LOSE_RANK_EXIT` marks the rank
  PERMANENTLY lost (the hardware-gone signature: a host that will not
  come back). Recovery: the supervisor shrinks the gang to the surviving
  world size and resumes from the survivors' checkpoints
  (shrink-to-fit). Refused by the single-process train CLI.

Each fault fires exactly once (a rollback that replays iteration K must
not re-trip the same injected fault, or no retry could ever succeed).
"""
from __future__ import annotations

import dataclasses
import glob
import os
import sys
from typing import Any

FAULT_KINDS = ("nan-grad", "corrupt-ckpt", "kill-rank", "lose-rank")

# exit codes the supervised dryrun's ranks die with; the supervisor keys
# its restart decision on them (same-size restart vs shrink-to-fit)
KILL_RANK_EXIT = 17   # restartable death: respawn at the same world size
LOSE_RANK_EXIT = 23   # permanent loss: relaunch at the surviving world size


@dataclasses.dataclass
class FaultSpec:
    kind: str       # one of FAULT_KINDS
    at: int         # iteration (nan-grad/corrupt-ckpt) or train step (kill)
    rank: int = 0   # kill-rank: process rank; nan-grad vs PBT: member index
    fired: bool = False


def parse_fault(spec: str) -> FaultSpec:
    """Parse ``kind@N[:rank=R]`` (e.g. ``nan-grad@3``,
    ``kill-rank@2:rank=1``). Raises ValueError with the offending spec."""
    body = spec.strip()
    rank = 0
    if ":" in body:
        body, _, opt = body.partition(":")
        key, _, val = opt.partition("=")
        if key.strip() != "rank" or not val.strip().lstrip("-").isdigit():
            raise ValueError(f"bad fault option {opt!r} in {spec!r} "
                             f"(expected rank=R)")
        rank = int(val)
    kind, sep, at = body.partition("@")
    kind = kind.strip()
    if kind not in FAULT_KINDS or not sep or not at.strip().isdigit():
        raise ValueError(
            f"bad fault spec {spec!r}; expected kind@N[:rank=R] with kind "
            f"in {FAULT_KINDS}")
    return FaultSpec(kind=kind, at=int(at), rank=rank)


def corrupt_checkpoint(directory: str, step: int,
                       fix_checksums: bool = False) -> int:
    """Truncate every data blob of checkpoint ``step`` under ``directory``
    to half its size (the truncated-save / partial-write failure mode).
    Returns the number of files corrupted; raises if the step dir has no
    data files (corrupting nothing would silently test nothing).

    ``fix_checksums=True`` re-writes the step's crc32 sidecar AFTER the
    corruption, so the cheap checksum pre-check passes and the deep
    failed-load fallback path is the one exercised (an adversarial
    corruption that keeps the sidecar consistent — e.g. a buggy writer
    that checksummed what it actually wrote)."""
    step_dir = os.path.join(directory, str(step))
    targets = [f for pat in ("state/d/*", "state/ocdbt.process_*/d/*")
               for f in glob.glob(os.path.join(step_dir, pat))
               if os.path.isfile(f)]
    if not targets:
        raise FileNotFoundError(
            f"no checkpoint data files under {step_dir} to corrupt")
    for f in targets:
        with open(f, "r+b") as fh:
            fh.truncate(max(os.path.getsize(f) // 2, 1))
    if fix_checksums:
        from rlgpuschedule_tpu.checkpoint import write_checksum_sidecar
        write_checksum_sidecar(directory, step)
    return len(targets)


class FaultInjector:
    """Host-side injection hooks called from the training loops. Holds the
    parsed specs; every hook is a no-op unless a not-yet-fired spec
    matches the current iteration/step, so an attached injector costs
    nothing on the healthy path."""

    def __init__(self, specs: list[FaultSpec], bus=None):
        self.specs = list(specs)
        self._bus = bus   # obs.EventBus (or None): fault firings

    def _emit(self, spec: FaultSpec, **fields: Any) -> None:
        if self._bus is not None:
            self._bus.emit("fault", fault=spec.kind, at=spec.at,
                           target_rank=spec.rank, **fields)

    def _take(self, kind: str, at: int) -> FaultSpec | None:
        for s in self.specs:
            if s.kind == kind and s.at == at and not s.fired:
                s.fired = True
                return s
        return None

    def poison_nan(self, exp: Any, iteration: int, metrics: Any) -> Any:
        """``nan-grad`` hook (single-run ``Experiment``): poison the whole
        param tree + the iteration's metrics. Returns the (possibly
        poisoned) metrics NamedTuple."""
        import jax
        import jax.numpy as jnp
        spec = self._take("nan-grad", iteration)
        if spec is None:
            return metrics
        self._emit(spec, iteration=iteration)
        print(f"fault-injection: nan-grad at iteration {iteration} "
              f"(params poisoned)", file=sys.stderr, flush=True)
        exp.train_state = exp.train_state.replace(
            params=jax.tree.map(lambda x: x * jnp.nan,
                                exp.train_state.params))
        return metrics._replace(
            total_loss=metrics.total_loss * jnp.nan)

    def poison_nan_member(self, pop: Any, iteration: int,
                          metrics: Any) -> Any:
        """``nan-grad`` hook (``PopulationExperiment``): poison ONE
        member's param rows (spec ``rank`` = member index) and its metric
        column — the dead-member input to the PBT exploit re-seed."""
        import jax
        import jax.numpy as jnp
        spec = self._take("nan-grad", iteration)
        if spec is None:
            return metrics
        m = spec.rank
        self._emit(spec, iteration=iteration, member=m)
        print(f"fault-injection: nan-grad at iteration {iteration} "
              f"member {m}", file=sys.stderr, flush=True)
        pop.states = pop.states._replace(
            params=jax.tree.map(
                lambda x: x.at[m].set(jnp.nan), pop.states.params))
        return metrics._replace(
            mean_reward=metrics.mean_reward.at[m].set(jnp.nan))

    def corrupt_after_save(self, ckpt: Any, iteration: int) -> None:
        """``corrupt-ckpt`` hook: right after the periodic save at
        ``iteration``, corrupt the just-saved (latest) step's files."""
        spec = self._take("corrupt-ckpt", iteration)
        if spec is None:
            return
        ckpt.wait()          # the async save must be on disk to corrupt
        step = ckpt.latest_step()
        n = corrupt_checkpoint(ckpt.directory, step)
        self._emit(spec, iteration=iteration, step=step, files=n)
        print(f"fault-injection: corrupted checkpoint step {step} "
              f"({n} files) after iteration {iteration}",
              file=sys.stderr, flush=True)

    def maybe_exit_rank(self, rank: int, step: int) -> None:
        """``kill-rank`` / ``lose-rank`` hook (multihost worker): rank
        ``rank`` dies un-gracefully right before train step ``step``.
        ``kill-rank`` exits :data:`KILL_RANK_EXIT` (restartable);
        ``lose-rank`` exits :data:`LOSE_RANK_EXIT` (permanent loss — the
        supervisor must shrink the gang instead of respawning rank R)."""
        for s in self.specs:
            if s.kind in ("kill-rank", "lose-rank") and s.at == step \
                    and s.rank == rank and not s.fired:
                s.fired = True
                code = (KILL_RANK_EXIT if s.kind == "kill-rank"
                        else LOSE_RANK_EXIT)
                # the bus appends+flushes per emit, so the event is
                # durable before the un-graceful exit below
                self._emit(s, step=step, exit_code=code)
                print(f"fault-injection: rank {rank} dying before step "
                      f"{step} ({s.kind}, exit {code})",
                      file=sys.stderr, flush=True)
                os._exit(code)

    # back-compat alias (pre-elastic name; same hook, kill-rank only kept
    # firing through it because old callers only armed kill-rank specs)
    maybe_kill_rank = maybe_exit_rank
