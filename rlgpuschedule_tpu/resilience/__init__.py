"""Fault tolerance (L6 aux): divergence watchdog, checkpoint recovery,
heartbeats, and deterministic fault injection.

Capability parity: SURVEY.md §5 "Failure detection / elastic recovery /
fault injection" — the one `[?]` capability left open by the seed.
Checkpoint-restart is the rebuild's recovery story (Podracer treats
preemption + restart-from-checkpoint as a first-class design constraint);
this package adds the pieces that make it an actual recovery story:

- :class:`DivergenceWatchdog` — per-iteration non-finite / loss-blow-up
  detection, rollback to the last good Orbax checkpoint with a
  deterministically decayed LR, clean give-up after ``max_rollbacks``;
- :class:`FaultInjector` / :func:`parse_fault` — the deterministic
  fault-injection harness (``nan-grad@K``, ``corrupt-ckpt@K``,
  ``kill-rank@T[:rank=R]``, ``lose-rank@T[:rank=R]``) that drives every
  recovery path on CPU in tier-1 tests and from the train CLI
  (``--fault``);
- :class:`HeartbeatWriter` / :class:`HeartbeatMonitor` — per-rank
  heartbeat files (monotonic-clock stamps, atomic writes) + timeout
  watchdog;
- :class:`Supervisor` / :class:`RestartPolicy` / :class:`Launcher` —
  the elastic gang supervisor: detect (exit code / stale heartbeat) →
  decide (same-size restart from the minimum completed step, or
  shrink-to-fit relaunch at the surviving world size on permanent rank
  loss) → relaunch (exponential backoff + jitter, ``max_restarts``
  budget with a restart-storm guard), terminating in a
  :class:`SupervisorResult` that reports why. The subprocess gang of
  the CPU dryrun is one :class:`Launcher`
  (:class:`SubprocessGangLauncher`); a pod launcher is another.

Checkpoint integrity verification itself (crc32 sidecar pre-check, then
restore-the-latest-step with fallback to the previous retained step)
lives in ``checkpoint.Checkpointer`` — every restore path gets it for
free; shrink-to-fit re-sharding is ``checkpoint.Checkpointer.
elastic_restore``.
"""
from .faults import (KILL_RANK_EXIT, LOSE_RANK_EXIT, FaultInjector,
                     FaultSpec, corrupt_checkpoint, parse_fault)
from .heartbeat import HeartbeatMonitor, HeartbeatWriter
from .supervisor import (Gang, Launcher, LaunchPlan, RestartPolicy,
                         SubprocessGangLauncher, Supervisor,
                         SupervisorEvent, SupervisorResult,
                         SupervisorTimeout)
from .watchdog import DivergenceError, DivergenceWatchdog, RollbackEvent

__all__ = [
    "DivergenceError", "DivergenceWatchdog", "RollbackEvent",
    "FaultInjector", "FaultSpec", "corrupt_checkpoint", "parse_fault",
    "KILL_RANK_EXIT", "LOSE_RANK_EXIT",
    "HeartbeatMonitor", "HeartbeatWriter",
    "Gang", "Launcher", "LaunchPlan", "RestartPolicy",
    "SubprocessGangLauncher", "Supervisor", "SupervisorEvent",
    "SupervisorResult", "SupervisorTimeout",
]
