"""Fault tolerance (L6 aux): divergence watchdog, checkpoint recovery,
heartbeats, and deterministic fault injection.

Capability parity: SURVEY.md §5 "Failure detection / elastic recovery /
fault injection" — the one `[?]` capability left open by the seed.
Checkpoint-restart is the rebuild's recovery story (Podracer treats
preemption + restart-from-checkpoint as a first-class design constraint);
this package adds the pieces that make it an actual recovery story:

- :class:`DivergenceWatchdog` — per-iteration non-finite / loss-blow-up
  detection, rollback to the last good Orbax checkpoint with a
  deterministically decayed LR, clean give-up after ``max_rollbacks``;
- :class:`FaultInjector` / :func:`parse_fault` — the deterministic
  fault-injection harness (``nan-grad@K``, ``corrupt-ckpt@K``,
  ``kill-rank@T[:rank=R]``) that drives every recovery path on CPU in
  tier-1 tests and from the train CLI (``--fault``);
- :class:`HeartbeatWriter` / :class:`HeartbeatMonitor` — per-rank
  heartbeat files + timeout watchdog for the supervised multihost dryrun
  (``__graft_entry__.dryrun_multihost_supervised``).

Checkpoint integrity verification itself (restore the latest step, fall
back to the previous retained step when it is truncated/corrupt) lives in
``checkpoint.Checkpointer.restore`` — every restore path gets it for free.
"""
from .faults import FaultInjector, FaultSpec, corrupt_checkpoint, parse_fault
from .heartbeat import HeartbeatMonitor, HeartbeatWriter
from .watchdog import DivergenceError, DivergenceWatchdog, RollbackEvent

__all__ = [
    "DivergenceError", "DivergenceWatchdog", "RollbackEvent",
    "FaultInjector", "FaultSpec", "corrupt_checkpoint", "parse_fault",
    "HeartbeatMonitor", "HeartbeatWriter",
]
