"""Per-rank heartbeat files + timeout watchdog (SURVEY.md §5 "Failure
detection" — the multihost half).

A dead rank leaves its peers silently blocked inside a collective; no
exception ever surfaces on the survivors. Liveness therefore has to be
observed from OUTSIDE the gang: each rank atomically rewrites a tiny
``rank<r>.hb`` file before every train step, and the supervisor
(``__graft_entry__.dryrun_multihost_supervised``) declares a rank dead
when its file goes stale past the timeout (or its process exits
non-zero, the fast path) and restarts the gang from checkpoint.

Files, not sockets: the supervisor and workers already share a
filesystem, an atomic rename is crash-consistent, and a stale file is
exactly the failure signature we need — a hung rank stops renaming.
"""
from __future__ import annotations

import os
import time


class HeartbeatWriter:
    """One rank's side: ``beat(step)`` atomically rewrites the rank file
    with the current step and wall time."""

    def __init__(self, directory: str, rank: int):
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, f"rank{rank}.hb")
        self._tmp = self.path + ".tmp"

    def beat(self, step: int) -> None:
        with open(self._tmp, "w") as f:
            f.write(f"{step} {time.time()}")
        os.replace(self._tmp, self.path)   # atomic on POSIX


class HeartbeatMonitor:
    """Supervisor's side: which ranks have not beaten within
    ``timeout_s``? A rank with no file yet is judged against the
    monitor's start time (grace for slow jax/XLA startup)."""

    def __init__(self, directory: str, n_ranks: int, timeout_s: float):
        self.directory = directory
        self.n_ranks = n_ranks
        self.timeout_s = timeout_s
        self._t0 = time.time()

    def restart(self) -> None:
        """Re-arm the missing-file grace window (call when the gang is
        (re)spawned)."""
        self._t0 = time.time()

    def read(self) -> dict[int, tuple[int, float]]:
        """{rank: (last step, beat wall time)} for ranks that have beaten."""
        out = {}
        for r in range(self.n_ranks):
            path = os.path.join(self.directory, f"rank{r}.hb")
            try:
                with open(path) as f:
                    step_s, ts_s = f.read().split()
                out[r] = (int(step_s), float(ts_s))
            except (FileNotFoundError, ValueError):
                continue   # not yet written, or mid-rename torn read
        return out

    def stale_ranks(self, now: float | None = None) -> list[int]:
        now = time.time() if now is None else now
        beats = self.read()
        stale = []
        for r in range(self.n_ranks):
            last = beats.get(r, (None, self._t0))[1]
            if now - last > self.timeout_s:
                stale.append(r)
        return stale
