"""Per-rank heartbeat files + timeout watchdog (SURVEY.md §5 "Failure
detection" — the multihost half).

A dead rank leaves its peers silently blocked inside a collective; no
exception ever surfaces on the survivors. Liveness therefore has to be
observed from OUTSIDE the gang: each rank atomically rewrites a tiny
``rank<r>.hb`` file before every train step, and the supervisor
(``resilience.supervisor.Supervisor``) declares a rank dead when its
file goes stale past the timeout (or its process exits non-zero, the
fast path) and restarts the gang from checkpoint.

Files, not sockets: the supervisor and workers already share a
filesystem, an atomic rename is crash-consistent, and a stale file is
exactly the failure signature we need — a hung rank stops renaming.

Clock discipline: beats carry ``time.monotonic()`` stamps, NOT wall
time. Wall clocks jump (NTP slew/step, manual adjustment); a backward
jump makes a dead rank's file look fresh (false-alive) and a forward
jump makes a live rank look stale (false-stale) — both were possible
with the original ``time.time()`` stamps. CLOCK_MONOTONIC is shared by
every process on one host, which is exactly the supervised dryrun's
topology (supervisor + ranks on one machine). Cross-HOST supervision
needs stamps the reader generates itself (e.g. file mtimes under the
reader's clock) and belongs to the pod-launcher integration.

Writes are torn-proof: content goes to a writer-private tmp file
(pid-suffixed, so a not-yet-reaped predecessor rank can't interleave
with its replacement) and lands via ``os.replace`` — a reader sees the
old beat or the new one, never half a line.
"""
from __future__ import annotations

import os
import time
from typing import Callable


class HeartbeatWriter:
    """One rank's side: ``beat(step)`` atomically rewrites the rank file
    with the current step and a monotonic timestamp. ``clock`` is
    injectable for tests; the default (``time.monotonic``) must match the
    monitor's."""

    def __init__(self, directory: str, rank: int,
                 clock: Callable[[], float] = time.monotonic):
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, f"rank{rank}.hb")
        # pid-unique tmp: after a gang restart the old rank process may
        # not be fully reaped yet; a shared tmp name would let its last
        # in-flight write race the new rank's
        self._tmp = f"{self.path}.tmp.{os.getpid()}"
        self._clock = clock

    def beat(self, step: int) -> None:
        with open(self._tmp, "w") as f:
            f.write(f"{step} {self._clock()}")
        os.replace(self._tmp, self.path)   # atomic on POSIX


class HeartbeatMonitor:
    """Supervisor's side: which ranks have not beaten within
    ``timeout_s``? The staleness threshold is a constructor argument —
    it must scale with the deployment's longest legitimate beat-free
    stretch (XLA compile of the step program), which no constant can
    know. A rank with no file yet is judged against the monitor's start
    time (grace for slow jax/XLA startup)."""

    def __init__(self, directory: str, n_ranks: int, timeout_s: float,
                 clock: Callable[[], float] = time.monotonic):
        self.directory = directory
        self.n_ranks = n_ranks
        self.timeout_s = timeout_s
        self._clock = clock
        self._t0 = clock()

    def restart(self) -> None:
        """Re-arm the missing-file grace window (call when the gang is
        (re)spawned)."""
        self._t0 = self._clock()

    def read(self) -> dict[int, tuple[int, float]]:
        """{rank: (last step, beat monotonic time)} for ranks that have
        beaten."""
        out = {}
        for r in range(self.n_ranks):
            path = os.path.join(self.directory, f"rank{r}.hb")
            try:
                with open(path) as f:
                    step_s, ts_s = f.read().split()
                out[r] = (int(step_s), float(ts_s))
            except (FileNotFoundError, ValueError):
                continue   # not yet written, or mid-rename torn read
        return out

    def stale_ranks(self, now: float | None = None) -> list[int]:
        now = self._clock() if now is None else now
        beats = self.read()
        stale = []
        for r in range(self.n_ranks):
            last = beats.get(r, (None, self._t0))[1]
            if now - last > self.timeout_s:
                stale.append(r)
        return stale
