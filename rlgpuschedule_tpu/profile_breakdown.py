"""Where-the-time-goes profiling of the headline bench workload (L6 aux).

Capability parity: SURVEY.md §5 "Tracing / profiling" and §7 hard part (d)
("keeping per-step host↔device sync at zero"); VERDICT r2 missing #4 /
next-round #6 — one steps/s number says nothing about WHERE the time goes,
so this CLI decomposes the fused PPO train step into its three stages and
measures the host gap:

- **rollout**: the fused policy+env ``lax.scan`` (HOT LOOP #1),
- **gae**: the bare reverse-scan advantage computation (reference row),
- **advantage**: the production fused advantage pipeline
  (``algos.ppo.compute_advantages``: optional streaming reward
  standardization → GAE or V-trace → global normalization → optional
  bf16 storage). With default flags this is the gae row plus
  normalization; ``--correction vtrace`` prices the batched
  target-policy recompute the off-policy path adds on top,
- **update**: epoch × minibatch clipped-surrogate updates (HOT LOOP #2),
- **fused_loop**: the production one-jit step (rollout+gae+update
  together — XLA may fuse across stages, so fused ≤ sum(parts) is
  expected) timed as a pipelined driver loop (block only at the end),
- **fused_step_blocked**: the same step with a device sync after EVERY
  call — the un-pipelined latency,
- **pipeline_overlap**: blocked − pipelined = how much host work (Python
  dispatch, PRNG splits) async dispatch hides. True device time needs the
  profiler trace (``--trace-dir``); wall-minus-parts is NOT it, because
  cross-stage fusion makes sum(parts) an overestimate of the fused step.

Each stage is jitted separately, warmed, then timed as median-of-N
(the same noise discipline as bench.py). Optionally captures a
``jax.profiler`` trace (Perfetto/TensorBoard) of the fused loop.

Usage::

    python -m rlgpuschedule_tpu.profile_breakdown [--cpu] [--repeats 5]
        [--trace-dir /tmp/jax-trace] [--n-envs 512] [--n-steps 128]
        [--n-epochs 2] [--n-minibatches 8 | --minibatch-size N]
        [--bf16-update] [--correction vtrace] [--reward-norm]
        [--bf16-advantages]
    python -m rlgpuschedule_tpu.profile_breakdown [--cpu] \
        --sweep-minibatch [--sweep-out sweep.json]
    python -m rlgpuschedule_tpu.profile_breakdown [--cpu] \
        --async [--staleness-bound 1] [--async-out async.json]

``--async`` swaps the stage breakdown for a sync-vs-async PHASE table:
the same workload is run through the per-iteration sync loop and through
the overlapped actor-learner engine (``async_engine.AsyncRunner``), and
the artifact reports seconds/iteration for both plus the engine's own
phase accounting — actor / learner busy seconds, queue-wait (the actor's
staleness-gate stall + the learner's pop stall), and the overlap-ceiling
projection ``(actor + learner) / max(actor, learner)`` that bounds the
achievable speedup on hardware with enough cores to truly overlap. With
``--cpu`` this mode pins TWO virtual CPU devices (the split needs
disjoint actor/learner groups; the plain breakdown pins one).

Prints one JSON object with per-stage seconds/iteration, the stage shares,
an env-steps/s figure, and a model-FLOPs/s estimate (policy fwd+bwd FLOPs
from param count — the MXU utilization proxy; the env scan does almost no
matmul work, so "MFU" here is meaningful for the update stage only).

``--sweep-minibatch`` is the automated minibatch-geometry lever sweep
(BASELINE.md named it "the first lever the next TPU session should
profile"): one rollout+GAE is materialized, then the update stage alone is
timed at every power-of-two minibatch count that tiles the batch, on
whatever backend jax picked — the same artifact schema on CPU and TPU
(``mfu_update`` is null off-chip where no bf16 peak is known). The output
is a RANKED JSON artifact (fastest geometry first, ``best`` duplicated at
the top level); feed it to ``bench.py --sweep`` so the headline number
reflects the lever. The update step is timed exactly as production runs
it: optimizer/param buffers donated and threaded call-to-call
(``algos.update.make_update_step``), so no per-call state reallocation
pollutes the measurement.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import statistics
import time


def _median_time(fn, repeats: int) -> float:
    samples = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        samples.append(time.perf_counter() - t0)
    return statistics.median(samples)


# MFU pricing: the chip's bf16 matmul peak (the networks run bf16
# compute), keyed on device_kind — platform == "tpu" alone would price
# every generation at the v5e's peak. This is the measured replacement
# for the "dispatch/HBM-bound" assertion (VERDICT r4 missing #4):
# mfu_total over the whole fused step, and mfu_update over the update
# stage alone (the only stage whose matmuls could fill the MXU — the
# env scan does no matmul work). Public bf16 peaks per chip.
BF16_PEAK = {"v4": 275e12, "v5 lite": 197e12, "v5e": 197e12,
             "v5p": 459e12, "v5": 459e12, "v6 lite": 918e12,
             "v6e": 918e12}


def _sweep_minibatch(args, ppo, platform, kind, peak, B, n_params,
                     timed_update, state, tr, adv, ret, key, n,
                     t_adv) -> dict:
    """Time the update stage over the geometry grid — epochs in
    ``{1, configured}`` × every power-of-two minibatch count that tiles
    the batch (plus the configured default) — and rank the geometries
    fastest-first. All three axes of the ``n_epochs × n_minibatches ×
    minibatch_size`` triple are covered (minibatch_size is the derived
    ``B / n_minibatches``); ``n_epochs`` scales the update's FLOPs
    linearly, so same-epoch rows compare pure geometry overhead/MXU fill
    while the 1-epoch rows price the fused single-pass recipe. Same
    artifact on CPU and TPU; ``mfu_update`` is null where no bf16 peak is
    known (non-TPU backends)."""
    import dataclasses as _dc

    from rlgpuschedule_tpu.algos import resolve_geometry

    _, default_mb, _sz = resolve_geometry(ppo.n_epochs, ppo.n_minibatches,
                                          ppo.minibatch_size, B)
    mbs = sorted({m for m in (2 ** p for p in range(0, 8))
                  if m <= B and B % m == 0} | {default_mb})
    results = []
    for e in sorted({1, ppo.n_epochs}):
        upd_evals = e * B                        # fwd+bwd per sample
        upd_flops = 2 * n_params * 3 * upd_evals
        for m in mbs:
            geom = _dc.replace(ppo, n_epochs=e, n_minibatches=m,
                               minibatch_size=None)
            t = timed_update(geom, state, tr, adv, ret, key, n)
            results.append({
                "n_epochs": e, "n_minibatches": m,
                "minibatch_size": B // m,
                "update_s_per_iteration": round(t, 5),
                "update_env_steps_per_sec": round(B / t, 1),
                "model_flops_per_sec": round(upd_flops / t, 1),
                "mfu_update": round(upd_flops / t / peak, 6)
                if peak is not None else None,
            })
    default = next(r for r in results
                   if r["n_epochs"] == ppo.n_epochs
                   and r["n_minibatches"] == default_mb)
    t_default = default["update_s_per_iteration"]
    for r in results:
        r["speedup_vs_default"] = round(
            t_default / r["update_s_per_iteration"], 3)
    results.sort(key=lambda r: r["update_s_per_iteration"])
    out = {
        "sweep": "minibatch-geometry",
        "platform": platform,
        "device_kind": kind or None,
        "n_envs": tr.reward.shape[1], "n_steps": ppo.n_steps,
        "batch_per_iteration": B,
        "bf16_update": ppo.bf16_update,
        "advantage_pipeline": {"correction": ppo.correction,
                               "reward_norm": ppo.reward_norm,
                               "bf16_advantages": ppo.bf16_advantages},
        # the advantage phase is geometry-invariant (it runs once per
        # iteration, before the epoch×minibatch grid) — one row
        # contextualizes every geometry's update time against it
        "advantage_s_per_iteration": round(t_adv, 5),
        "policy_params": int(n_params),
        "assumed_bf16_peak_flops": peak,
        "default_geometry": {"n_epochs": ppo.n_epochs,
                             "n_minibatches": default_mb},
        "results": results,            # ranked fastest-first
        "best": results[0],
    }
    return out


def _profile_async(args, cfg, platform) -> dict:
    """Sync-vs-async phase table on one workload.

    Times the per-iteration sync loop and the overlapped engine
    (median-of-N, same noise discipline as the stage breakdown), then
    folds in the engine's own accounting: per-phase host seconds from the
    run's SectionTimer (``actor``/``learner``/``queue_wait``/``sync``)
    and the cumulative overlap/staleness counters from ``async_info()``.
    ``projected_overlap_speedup`` is the phase-time ceiling
    ``(actor + learner) / max(actor, learner)`` — what perfect overlap
    would buy on hardware with spare host cores; the measured ``speedup``
    is what THIS host delivers (≈1.0 or below on a single core, where the
    CPU dispatch lock serializes the two loops by design)."""
    import os

    from rlgpuschedule_tpu.async_engine import AsyncRunner
    from rlgpuschedule_tpu.experiment import Experiment

    n = args.iters_per_repeat
    sync_exp = Experiment.build(cfg)
    sync_exp.run(iterations=1)                     # compile + warm
    t_sync = _median_time(lambda: sync_exp.run(iterations=n),
                          args.repeats) / n

    async_exp = Experiment.build(cfg)
    runner = AsyncRunner(async_exp, staleness_bound=args.staleness_bound)
    runner.run(iterations=1)                       # warm the engine path
    last: dict = {}

    def timed():
        last.update(runner.run(iterations=n))

    t_async = _median_time(timed, args.repeats) / n
    phases = last["phase_seconds"]                 # last timed run only
    info = last["async"]                           # cumulative counters
    busy_a = phases.get("actor", 0.0)
    busy_l = phases.get("learner", 0.0)
    parts = busy_a + busy_l
    return {
        "profile": "async-phase-table",
        "platform": platform,
        "cores": os.cpu_count(),
        "n_envs": cfg.n_envs, "n_steps": cfg.ppo.n_steps,
        "iters_per_repeat": n, "repeats": args.repeats,
        "staleness_bound": args.staleness_bound,
        "groups": runner.groups.describe(),
        "seconds_per_iteration": {
            "sync_loop": round(t_sync, 5),
            "async_loop": round(t_async, 5)},
        "speedup": round(t_sync / t_async, 3),
        "async_phase_seconds_per_iteration": {
            k: round(v / n, 5) for k, v in sorted(phases.items())},
        "async_phase_share_of_busy": {
            "actor": round(busy_a / parts, 3) if parts else None,
            "learner": round(busy_l / parts, 3) if parts else None},
        "projected_overlap_speedup": round(
            parts / max(busy_a, busy_l), 3) if parts else None,
        "queue_wait_s_cumulative": {
            "actor_idle": info["actor_idle_s"],
            "learner_idle": info["learner_idle_s"]},
        "staleness": {"max": info["staleness_max"],
                      "mean": info["staleness_mean"]},
        "overlap_s_cumulative": info["overlap_s"],
        "note": "phase seconds are the last timed run's SectionTimer; "
                "queue_wait/overlap/staleness counters are cumulative "
                "over warmup + all repeats",
    }


def main(argv: list[str] | None = None) -> dict:
    ap = argparse.ArgumentParser(prog="rlgpuschedule_tpu.profile_breakdown")
    ap.add_argument("--cpu", action="store_true",
                    help="pin the CPU platform (default: whatever jax picks)")
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--iters-per-repeat", type=int, default=3)
    ap.add_argument("--n-envs", type=int, default=None,
                    help="default: 512 on TPU, 32 on CPU")
    ap.add_argument("--n-steps", type=int, default=None,
                    help="default: 128 on TPU, 64 on CPU")
    ap.add_argument("--n-epochs", type=int, default=2,
                    help="update geometry: PPO epochs over the batch")
    ap.add_argument("--n-minibatches", type=int, default=8,
                    help="update geometry: minibatch count per epoch "
                         "(profile the swept-best with e.g. 1)")
    ap.add_argument("--minibatch-size", type=int, default=None,
                    help="update geometry: explicit minibatch size; "
                         "overrides --n-minibatches (algos.update "
                         "resolve_geometry contract)")
    ap.add_argument("--bf16-update", action="store_true",
                    help="profile the bf16-compute / fp32-optimizer "
                         "update path")
    ap.add_argument("--correction", choices=["none", "vtrace"],
                    default="none",
                    help="advantage pipeline: V-trace importance-corrected "
                         "targets instead of plain GAE — the advantage row "
                         "then prices the batched target-policy recompute "
                         "the off-policy path adds")
    ap.add_argument("--reward-norm", action="store_true",
                    help="advantage pipeline: streaming Welford reward "
                         "standardization before the target scan")
    ap.add_argument("--bf16-advantages", action="store_true",
                    help="advantage pipeline: store advantages/returns in "
                         "bf16 (halves the tensors' HBM traffic; the "
                         "update still computes fp32)")
    ap.add_argument("--sweep-minibatch", action="store_true",
                    help="time the update stage over a grid of minibatch "
                         "geometries and emit a ranked JSON artifact "
                         "(steps/s + mfu_update) instead of the stage "
                         "breakdown")
    ap.add_argument("--sweep-out", default=None,
                    help="with --sweep-minibatch: also write the ranked "
                         "artifact to this path (bench.py --sweep reads "
                         "it)")
    ap.add_argument("--trace-dir", default=None,
                    help="also capture a jax.profiler trace of the fused "
                         "loop here")
    ap.add_argument("--async", dest="async_run", action="store_true",
                    help="profile the overlapped actor-learner engine "
                         "against the sync loop (phase table) instead of "
                         "the stage breakdown")
    ap.add_argument("--staleness-bound", type=int, default=1,
                    help="with --async: the engine's staleness bound")
    ap.add_argument("--async-out", default=None,
                    help="with --async: also write the phase-table "
                         "artifact to this path")
    args = ap.parse_args(argv)
    if args.sweep_out and not args.sweep_minibatch:
        ap.error("--sweep-out only applies with --sweep-minibatch")
    if args.async_out and not args.async_run:
        ap.error("--async-out only applies with --async")
    if args.async_run and (args.sweep_minibatch or args.trace_dir):
        ap.error("--async is exclusive with --sweep-minibatch/--trace-dir")

    if args.cpu:
        from rlgpuschedule_tpu.utils.platform import force_cpu
        # the async split needs disjoint actor/learner device groups
        force_cpu(2 if args.async_run else 1)
    from rlgpuschedule_tpu.utils.platform import enable_compile_cache

    enable_compile_cache()

    import jax
    import jax.numpy as jnp

    from rlgpuschedule_tpu.algos import PPOConfig, resolve_geometry
    from rlgpuschedule_tpu.algos.ppo import (compute_advantages,
                                             normalize_advantages,
                                             run_ppo_epochs)
    from rlgpuschedule_tpu.algos.rollout import rollout
    from rlgpuschedule_tpu.algos.update import make_update_step
    from rlgpuschedule_tpu.configs import PPO_MLP_SYNTH64
    from rlgpuschedule_tpu.experiment import Experiment
    from rlgpuschedule_tpu.ops.gae import compute_gae
    from rlgpuschedule_tpu.utils import profiling

    platform = jax.devices()[0].platform
    on_cpu = platform == "cpu"
    n_envs = args.n_envs or (32 if on_cpu else 512)
    n_steps = args.n_steps or (64 if on_cpu else 128)
    ppo = PPOConfig(n_steps=n_steps, n_epochs=args.n_epochs,
                    n_minibatches=args.n_minibatches,
                    minibatch_size=args.minibatch_size,
                    bf16_update=args.bf16_update,
                    correction=args.correction,
                    reward_norm=args.reward_norm,
                    bf16_advantages=args.bf16_advantages)
    cfg = dataclasses.replace(PPO_MLP_SYNTH64, n_envs=n_envs, ppo=ppo)
    if args.async_run:
        out = _profile_async(args, cfg, platform)
        print(json.dumps(out))
        if args.async_out:
            with open(args.async_out, "w") as f:
                json.dump(out, f, indent=1)
        return out
    exp = Experiment.build(cfg)
    env_params, apply_fn = exp.env_params, exp.apply_fn
    state, carry, traces = exp.train_state, exp.carry, exp.traces
    # one key per consumer: the sweep, the standalone update timing, and
    # the fused-step warmup each get their own stream (jsan
    # prng-key-reuse: handing two consumers the same key makes their
    # draws bit-identical); the fused timing loop splits `key` itself
    key = jax.random.PRNGKey(0)
    key, k_sweep, k_upd, k_warm = jax.random.split(key, 4)
    B = n_steps * n_envs
    n_params = sum(x.size for x in jax.tree.leaves(state.params))
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    peak = next((v for k, v in BF16_PEAK.items()
                 if f"tpu {k}" in kind or kind == k), None) \
        if platform == "tpu" else None

    # ---- stage jits (batch inputs are reused across repeats, so only the
    # update's state — the buffers production donates — is donated and
    # threaded call-to-call) ----------------------------------------------
    @jax.jit
    def rollout_only(params, carry):
        return rollout(apply_fn, params, env_params, traces, carry, n_steps)

    @jax.jit
    def gae_only(tr, last_value):
        adv, ret = compute_gae(tr.reward, tr.value, tr.done, last_value,
                               ppo.gamma, ppo.gae_lambda)
        return normalize_advantages(adv), ret

    @jax.jit
    def advantage_only(state, tr, last_value):
        # the production pipeline (reward-norm → GAE/V-trace → normalize
        # → bf16 store); with default flags it lowers to gae_only's ops
        _st, a, r, _rho = compute_advantages(apply_fn, ppo, state, tr,
                                             last_value)
        return a, r

    # ONE jitted copy program shared by every _timed_update call: the
    # sweep times a dozen geometries, and a fresh jax.jit(lambda) per
    # call would recompile the copy once per geometry (jsan
    # recompile-hazard, PR 3 first-run finding). Can't live at module
    # scope — jax is imported lazily so --cpu can pin the platform first.
    copy_state = jax.jit(  # jsan: disable=recompile-hazard -- built once per process; jax import is deferred
        lambda t: jax.tree.map(jnp.copy, t))

    def _timed_update(ppo_g, state0, tr, adv, ret, key, n):
        """Median seconds/iteration of the donated update step at geometry
        ``ppo_g``, threading the donated state like the production loop."""
        upd = make_update_step(
            lambda s, t, a, r, k: run_ppo_epochs(
                apply_fn, ppo_g, s, t, a, r, k,
                lambda st, g: st.apply_gradients(grads=g)))
        cell = {"s": copy_state(state0)}
        cell["s"], _ = jax.block_until_ready(
            upd(cell["s"], tr, adv, ret, key))         # compile + warm

        def run_n():
            for _ in range(n):
                cell["s"], _m = upd(cell["s"], tr, adv, ret, key)
            jax.block_until_ready(cell["s"].params)

        return _median_time(run_n, args.repeats) / n

    _, tr, last_value = jax.block_until_ready(
        rollout_only(state.params, carry))
    jax.block_until_ready(gae_only(tr, last_value))        # compile + warm
    # the update/sweep timings consume the PRODUCTION pipeline's outputs
    # (bf16 storage changes the tensors the update reads)
    adv, ret = jax.block_until_ready(advantage_only(state, tr, last_value))

    n = args.iters_per_repeat
    t_adv = _median_time(
        lambda: jax.block_until_ready(
            [advantage_only(state, tr, last_value) for _ in range(n)]),
        args.repeats) / n
    if args.sweep_minibatch:
        out = _sweep_minibatch(args, ppo, platform, kind, peak, B, n_params,
                               _timed_update, state, tr, adv, ret, k_sweep,
                               n, t_adv)
        print(json.dumps(out))
        if args.sweep_out:
            with open(args.sweep_out, "w") as f:
                json.dump(out, f, indent=1)
        return out

    t_upd = _timed_update(ppo, state, tr, adv, ret, k_upd, n)

    fused = exp.train_step     # the production jit (donates; returns fresh)
    state2, carry2, _ = fused(state, carry, traces, k_warm)
    jax.block_until_ready(state2.params)
    state, carry = state2, carry2   # donated originals are dead now

    t_roll = _median_time(
        lambda: jax.block_until_ready(
            [rollout_only(state.params, carry) for _ in range(n)]),
        args.repeats) / n
    t_gae = _median_time(
        lambda: jax.block_until_ready(
            [gae_only(tr, last_value) for _ in range(n)]),
        args.repeats) / n

    def fused_loop(block_every: bool = False):
        nonlocal state, carry, key
        for _ in range(n):
            key, sub = jax.random.split(key)
            state, carry, _m = fused(state, carry, traces, sub)
            if block_every:
                jax.block_until_ready(state.params)
        jax.block_until_ready(state.params)

    t_loop = _median_time(fused_loop, args.repeats) / n
    t_blocked = _median_time(lambda: fused_loop(True), args.repeats) / n

    if args.trace_dir:
        with profiling.trace(args.trace_dir):
            fused_loop()

    # parts = the production decomposition (rollout → advantage pipeline
    # → update); the bare gae row stays as the pre-fusion reference
    t_parts = t_roll + t_adv + t_upd
    pipeline_overlap = max(t_blocked - t_loop, 0.0)

    # model-FLOPs proxy: 2*params per fwd MAC, 3x for fwd+bwd, over every
    # policy evaluation (T rollout steps + 1 bootstrap + epochs*B updates)
    fwd_evals = B + n_envs                      # rollout + bootstrap value
    upd_evals = ppo.n_epochs * B                # fwd+bwd per sample
    flops = 2 * n_params * (fwd_evals + 3 * upd_evals)
    upd_flops = 2 * n_params * 3 * upd_evals
    _, n_mb, mb = resolve_geometry(ppo.n_epochs, ppo.n_minibatches,
                                   ppo.minibatch_size, B)
    out = {
        "platform": platform,
        "n_envs": n_envs, "n_steps": n_steps,
        "geometry": {"n_epochs": ppo.n_epochs, "n_minibatches": n_mb,
                     "minibatch_size": mb,
                     "bf16_update": ppo.bf16_update},
        "advantage_pipeline": {"correction": ppo.correction,
                               "reward_norm": ppo.reward_norm,
                               "bf16_advantages": ppo.bf16_advantages},
        "seconds_per_iteration": {
            "rollout": round(t_roll, 5), "gae": round(t_gae, 5),
            "advantage": round(t_adv, 5),
            "update": round(t_upd, 5), "fused_loop": round(t_loop, 5),
            "fused_step_blocked": round(t_blocked, 5),
            "pipeline_overlap": round(pipeline_overlap, 5)},
        "stage_share_of_parts": {
            "rollout": round(t_roll / t_parts, 3),
            "advantage": round(t_adv / t_parts, 3),
            "update": round(t_upd / t_parts, 3)},
        "env_steps_per_sec": round(B / t_loop, 1),
        "policy_params": int(n_params),
        "model_flops_per_sec": round(flops / t_loop, 1),
    }
    if peak is not None:
        out["assumed_bf16_peak_flops"] = peak
        out["device_kind"] = kind
        out["mfu_total"] = round(flops / t_loop / peak, 6)
        out["mfu_update"] = round(upd_flops / t_upd / peak, 6)
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    main()
