"""Where-the-time-goes profiling of the headline bench workload (L6 aux).

Capability parity: SURVEY.md §5 "Tracing / profiling" and §7 hard part (d)
("keeping per-step host↔device sync at zero"); VERDICT r2 missing #4 /
next-round #6 — one steps/s number says nothing about WHERE the time goes,
so this CLI decomposes the fused PPO train step into its three stages and
measures the host gap:

- **rollout**: the fused policy+env ``lax.scan`` (HOT LOOP #1),
- **gae**: the reverse-scan advantage computation,
- **update**: epoch × minibatch clipped-surrogate updates (HOT LOOP #2),
- **fused_loop**: the production one-jit step (rollout+gae+update
  together — XLA may fuse across stages, so fused ≤ sum(parts) is
  expected) timed as a pipelined driver loop (block only at the end),
- **fused_step_blocked**: the same step with a device sync after EVERY
  call — the un-pipelined latency,
- **pipeline_overlap**: blocked − pipelined = how much host work (Python
  dispatch, PRNG splits) async dispatch hides. True device time needs the
  profiler trace (``--trace-dir``); wall-minus-parts is NOT it, because
  cross-stage fusion makes sum(parts) an overestimate of the fused step.

Each stage is jitted separately, warmed, then timed as median-of-N
(the same noise discipline as bench.py). Optionally captures a
``jax.profiler`` trace (Perfetto/TensorBoard) of the fused loop.

Usage::

    python -m rlgpuschedule_tpu.profile_breakdown [--cpu] [--repeats 5]
        [--trace-dir /tmp/jax-trace] [--n-envs 512] [--n-steps 128]

Prints one JSON object with per-stage seconds/iteration, the stage shares,
an env-steps/s figure, and a model-FLOPs/s estimate (policy fwd+bwd FLOPs
from param count — the MXU utilization proxy; the env scan does almost no
matmul work, so "MFU" here is meaningful for the update stage only).
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import statistics
import time


def _median_time(fn, repeats: int) -> float:
    samples = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        samples.append(time.perf_counter() - t0)
    return statistics.median(samples)


def main(argv: list[str] | None = None) -> dict:
    ap = argparse.ArgumentParser(prog="rlgpuschedule_tpu.profile_breakdown")
    ap.add_argument("--cpu", action="store_true",
                    help="pin the CPU platform (default: whatever jax picks)")
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--iters-per-repeat", type=int, default=3)
    ap.add_argument("--n-envs", type=int, default=None,
                    help="default: 512 on TPU, 32 on CPU")
    ap.add_argument("--n-steps", type=int, default=None,
                    help="default: 128 on TPU, 64 on CPU")
    ap.add_argument("--trace-dir", default=None,
                    help="also capture a jax.profiler trace of the fused "
                         "loop here")
    args = ap.parse_args(argv)

    if args.cpu:
        from rlgpuschedule_tpu.utils.platform import force_cpu
        force_cpu(1)
    from rlgpuschedule_tpu.utils.platform import enable_compile_cache

    enable_compile_cache()

    import jax
    import jax.numpy as jnp

    from rlgpuschedule_tpu.algos import PPOConfig
    from rlgpuschedule_tpu.algos.ppo import (normalize_advantages,
                                             run_ppo_epochs)
    from rlgpuschedule_tpu.algos.rollout import rollout
    from rlgpuschedule_tpu.configs import PPO_MLP_SYNTH64
    from rlgpuschedule_tpu.experiment import Experiment
    from rlgpuschedule_tpu.ops.gae import compute_gae
    from rlgpuschedule_tpu.utils import profiling

    platform = jax.devices()[0].platform
    on_cpu = platform == "cpu"
    n_envs = args.n_envs or (32 if on_cpu else 512)
    n_steps = args.n_steps or (64 if on_cpu else 128)
    ppo = PPOConfig(n_steps=n_steps, n_epochs=2, n_minibatches=8)
    cfg = dataclasses.replace(PPO_MLP_SYNTH64, n_envs=n_envs, ppo=ppo)
    exp = Experiment.build(cfg)
    env_params, apply_fn = exp.env_params, exp.apply_fn
    state, carry, traces = exp.train_state, exp.carry, exp.traces
    key = jax.random.PRNGKey(0)

    # ---- stage jits (no donation: inputs are reused across repeats) ------
    @jax.jit
    def rollout_only(params, carry):
        return rollout(apply_fn, params, env_params, traces, carry, n_steps)

    @jax.jit
    def gae_only(tr, last_value):
        adv, ret = compute_gae(tr.reward, tr.value, tr.done, last_value,
                               ppo.gamma, ppo.gae_lambda)
        return normalize_advantages(adv), ret

    @jax.jit
    def update_only(state, tr, adv, ret, key):
        return run_ppo_epochs(
            apply_fn, ppo, state, tr, adv, ret, key,
            lambda s, g: s.apply_gradients(grads=g))

    _, tr, last_value = jax.block_until_ready(
        rollout_only(state.params, carry))
    adv, ret = jax.block_until_ready(gae_only(tr, last_value))
    jax.block_until_ready(update_only(state, tr, adv, ret, key))

    fused = exp.train_step     # the production jit (donates; returns fresh)
    state2, carry2, _ = fused(state, carry, traces, key)
    jax.block_until_ready(state2.params)
    state, carry = state2, carry2   # donated originals are dead now

    n = args.iters_per_repeat
    t_roll = _median_time(
        lambda: jax.block_until_ready(
            [rollout_only(state.params, carry) for _ in range(n)]),
        args.repeats) / n
    t_gae = _median_time(
        lambda: jax.block_until_ready(
            [gae_only(tr, last_value) for _ in range(n)]),
        args.repeats) / n
    t_upd = _median_time(
        lambda: jax.block_until_ready(
            [update_only(state, tr, adv, ret, key) for _ in range(n)]),
        args.repeats) / n

    def fused_loop(block_every: bool = False):
        nonlocal state, carry, key
        for _ in range(n):
            key, sub = jax.random.split(key)
            state, carry, _m = fused(state, carry, traces, sub)
            if block_every:
                jax.block_until_ready(state.params)
        jax.block_until_ready(state.params)

    t_loop = _median_time(fused_loop, args.repeats) / n
    t_blocked = _median_time(lambda: fused_loop(True), args.repeats) / n

    if args.trace_dir:
        with profiling.trace(args.trace_dir):
            fused_loop()

    t_parts = t_roll + t_gae + t_upd
    pipeline_overlap = max(t_blocked - t_loop, 0.0)

    # model-FLOPs proxy: 2*params per fwd MAC, 3x for fwd+bwd, over every
    # policy evaluation (T rollout steps + 1 bootstrap + epochs*B updates)
    n_params = sum(x.size for x in jax.tree.leaves(state.params))
    B = n_steps * n_envs
    fwd_evals = B + n_envs                      # rollout + bootstrap value
    upd_evals = ppo.n_epochs * B                # fwd+bwd per sample
    flops = 2 * n_params * (fwd_evals + 3 * upd_evals)
    # MFU vs the chip's bf16 matmul peak (the networks run bf16 compute),
    # keyed on device_kind — platform == "tpu" alone would price every
    # generation at the v5e's peak. This is the measured replacement for
    # the "dispatch/HBM-bound" assertion (VERDICT r4 missing #4):
    # mfu_total over the whole fused step, and mfu_update over the update
    # stage alone (the only stage whose matmuls could fill the MXU — the
    # env scan does no matmul work). Public bf16 peaks per chip.
    BF16_PEAK = {"v4": 275e12, "v5 lite": 197e12, "v5e": 197e12,
                 "v5p": 459e12, "v5": 459e12, "v6 lite": 918e12,
                 "v6e": 918e12}
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    peak = next((v for k, v in BF16_PEAK.items()
                 if f"tpu {k}" in kind or kind == k), None) \
        if platform == "tpu" else None
    upd_flops = 2 * n_params * 3 * upd_evals
    out = {
        "platform": platform,
        "n_envs": n_envs, "n_steps": n_steps,
        "seconds_per_iteration": {
            "rollout": round(t_roll, 5), "gae": round(t_gae, 5),
            "update": round(t_upd, 5), "fused_loop": round(t_loop, 5),
            "fused_step_blocked": round(t_blocked, 5),
            "pipeline_overlap": round(pipeline_overlap, 5)},
        "stage_share_of_parts": {
            "rollout": round(t_roll / t_parts, 3),
            "gae": round(t_gae / t_parts, 3),
            "update": round(t_upd / t_parts, 3)},
        "env_steps_per_sec": round(B / t_loop, 1),
        "policy_params": int(n_params),
        "model_flops_per_sec": round(flops / t_loop, 1),
    }
    if peak is not None:
        out["assumed_bf16_peak_flops"] = peak
        out["device_kind"] = kind
        out["mfu_total"] = round(flops / t_loop / peak, 6)
        out["mfu_update"] = round(upd_flops / t_upd / peak, 6)
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    main()
