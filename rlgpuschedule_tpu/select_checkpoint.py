"""Post-hoc checkpoint selection on a held-out VALIDATION stream (L6).

``python -m rlgpuschedule_tpu.select_checkpoint --ckpt-dir out/run ...``

Round-5 measurement: neither the drain probe nor the streaming probe
reliably ranks stitched full-trace quality (drain-probe best read 1.08 vs
Tiresias on the test stream, streaming-probe best 1.28, while an
unselected mid-series checkpoint read 0.96 on validation) — per-window
probe JCT and full-trace JCT are different functionals of the same
policy. The honest selector is therefore the DELIVERABLE's own metric
(full-trace stitched replay) on a validation stream that is neither the
training trace nor the test stream: sweep every retained checkpoint
(``train --ckpt-keep N`` retains a series), score each, emit the argmin.
The test stream is then run ONCE with the chosen step
(``evaluate --ckpt-step``), keeping selection and measurement disjoint.

Prints one JSON line: {"dir", "step", "val_ratio", "val_tiresias",
"ranking": [[ratio, step], ...]}.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="rlgpuschedule_tpu.select_checkpoint",
        description="Rank retained checkpoints by full-trace JCT on a "
                    "held-out validation stream.")
    p.add_argument("--ckpt-dir", required=True)
    p.add_argument("--config", default="ppo-mlp-synth64")
    p.add_argument("--seed", type=int, default=None,
                   help="the TRAINING seed the checkpointed run used "
                        "(train --seed); the val-seed guard checks "
                        "against this, not just the preset default")
    p.add_argument("--val-seed", type=int, default=2000,
                   help="seed of the VALIDATION stream (must differ from "
                        "the training seed, from training seed + 1000 — "
                        "the --eval-every probe's default held-out "
                        "stream — and from the test seed)")
    p.add_argument("--test-seed", type=int, default=None,
                   help="seed of the TEST stream the chosen step will be "
                        "measured on (evaluate's stream); pass it so the "
                        "validation/test disjointness this selector "
                        "promises is actually enforced, not assumed")
    p.add_argument("--val-jobs", type=int, default=1024,
                   help="validation stream length in jobs")
    p.add_argument("--stitch-drain-jobs", type=int, default=8,
                   help="deep-backlog batching for the sweep (selection "
                        "only ranks checkpoints, so a coarse fast stitch "
                        "is fine; the test run chooses its own)")
    # the same shape overrides the training run used (must match the
    # checkpoints' shapes)
    p.add_argument("--n-envs", type=int, default=None)
    p.add_argument("--n-nodes", type=int, default=None)
    p.add_argument("--gpus-per-node", type=int, default=None)
    p.add_argument("--window-jobs", type=int, default=None)
    p.add_argument("--queue-len", type=int, default=None)
    p.add_argument("--horizon", type=int, default=None)
    p.add_argument("--obs-kind", default=None,
                   choices=["flat", "grid", "graph"])
    p.add_argument("--trace-load", type=float, default=None,
                   help="proxy traces: offered load of the validation "
                        "stream — match the TEST stream's load (round-5 "
                        "measurement: a load-1.1-trained policy reads "
                        "7.4x Tiresias on a 1.6x-overload 100k stream; "
                        "selection must happen in the deliverable's "
                        "regime)")
    return p


def main(argv: list[str] | None = None) -> dict:
    args = build_parser().parse_args(argv)
    from .configs import CONFIGS
    if args.config not in CONFIGS:
        sys.exit(f"unknown config {args.config!r}")
    over = {k: v for k, v in
            {"seed": args.seed, "n_envs": args.n_envs,
             "n_nodes": args.n_nodes,
             "gpus_per_node": args.gpus_per_node,
             "window_jobs": args.window_jobs, "queue_len": args.queue_len,
             "horizon": args.horizon, "obs_kind": args.obs_kind,
             "trace_load": args.trace_load}.items()
            if v is not None}
    cfg = dataclasses.replace(CONFIGS[args.config], **over)
    if cfg.trace in ("philly", "pai"):
        sys.exit("csv traces have no seeded held-out stream (the loader "
                 "would silently re-read the training csv — the same "
                 "no-op train.py refuses for --eval-seed); select "
                 "against a generated validation stream or split the "
                 "csv yourself")
    if args.val_seed == cfg.seed:
        sys.exit("--val-seed equals the config's training seed; selection "
                 "on the training distribution is not validation")
    if args.val_seed == cfg.seed + 1000:
        sys.exit("--val-seed equals training seed + 1000, the in-training "
                 "--eval-every probe's default held-out seed; a --keep-best "
                 "run already optimized checkpoint choice against that "
                 "stream, so selecting on it is not validation either")
    if args.test_seed is not None:
        if args.test_seed == args.val_seed:
            sys.exit("--test-seed equals --val-seed; selection and "
                     "measurement must run on disjoint streams")
        if args.test_seed == cfg.seed:
            sys.exit("--test-seed equals the config's training seed; "
                     "measuring on the training distribution is not a "
                     "test")

    import os

    from . import eval as eval_lib
    from .checkpoint import Checkpointer
    from .experiment import Experiment, load_source_trace
    from .sim.core import validate_trace
    from .sim.schedulers import run_baseline
    from .utils.platform import enable_compile_cache

    enable_compile_cache()
    exp = Experiment.build(cfg)
    val = validate_trace(
        exp.env_params.sim,
        load_source_trace(cfg, n_jobs=args.val_jobs, seed=args.val_seed),
        clamp=True)
    tiresias = run_baseline(val, cfg.n_nodes, cfg.gpus_per_node,
                            "tiresias").avg_jct()
    rows = []
    with Checkpointer(os.path.abspath(args.ckpt_dir)) as ck:
        steps = ck.all_steps()
        if not steps:
            sys.exit(f"no checkpoints under {args.ckpt_dir}")
        for step in sorted(steps):
            exp.restore_checkpoint(ck, step=step)
            out = eval_lib.full_trace_replay(
                exp.apply_fn, exp.train_state.params, exp.env_params, val,
                drain_completions=args.stitch_drain_jobs)
            ratio = out["avg_jct"] / tiresias
            rows.append((round(ratio, 4), step))
            print(f"step {step}: {out['avg_jct']:.1f} ratio {ratio:.4f}",
                  file=sys.stderr, flush=True)
    best = min(rows)
    result = {"dir": args.ckpt_dir, "step": best[1], "val_ratio": best[0],
              "val_tiresias": round(tiresias, 1), "ranking": sorted(rows)}
    print(json.dumps(result))
    return result


if __name__ == "__main__":
    main()
