"""Tracing / profiling (L6 aux): XLA/TPU profiler integration.

Capability parity: SURVEY.md §5 "Tracing / profiling" — the reference's
ad-hoc timers become first-class ``jax.profiler`` traces (viewable in
Perfetto / TensorBoard-profile) plus a lightweight host-side section
timer for the driver loop. Debug invariant checking (SURVEY.md §5 "Race
detection / sanitizers": JAX's purity removes data races by construction;
NaN debugging is a flag flip) is exposed via :func:`debug_checks`.
"""
from __future__ import annotations

import contextlib
import time
from typing import Iterator

import jax


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a device trace for the enclosed block::

        with profiling.trace("/tmp/jax-trace"):
            exp.run(iterations=5)

    Open the resulting directory with TensorBoard's profile plugin or
    Perfetto. On TPU this records per-op device timelines (MXU/HBM
    utilization); on CPU it still records XLA host ops."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def debug_checks(nans: bool = True) -> Iterator[None]:
    """Enable jax_debug_nans for the enclosed block (CI hook — SURVEY.md §4
    determinism/regression + §5 sanitizers)."""
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", nans)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


class SectionTimer:
    """Cumulative host-side wall-clock per named section.

    >>> t = SectionTimer()
    >>> with t("rollout"): ...
    >>> t.report()  # {'rollout': 1.23}
    """

    def __init__(self):
        self._acc: dict[str, float] = {}

    @contextlib.contextmanager
    def __call__(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._acc[name] = (self._acc.get(name, 0.0)
                               + time.perf_counter() - t0)

    def report(self) -> dict[str, float]:
        return dict(self._acc)
