"""Metrics logging (L6 aux): scalar curves to CSV + console.

Capability parity: SURVEY.md §2 "Metrics/logging" and §5 "Metrics /
logging / observability" — reward curves, env-steps/sec, avg/percentile
JCT, cluster utilization. The reference's TensorBoard-style scalar stream
becomes an append-only CSV (one row per logged iteration, stable header)
that pandas/TensorBoard ingest trivially; the JCT comparison table is
produced by ``eval.jct_report``/``format_report``.
"""
from __future__ import annotations

import csv
import os
import sys
import time
from typing import IO, Any, Mapping


class MetricsLogger:
    """Append scalar rows keyed by iteration; writes CSV and optionally
    mirrors a compact line to a stream.

    >>> log = MetricsLogger("out/metrics.csv", echo=True)
    >>> log(10, {"mean_reward": -0.5, "total_loss": 0.1})
    >>> log.close()

    The header is fixed by the first row (stable schema for the whole
    run); any later row whose keys differ from the first row's raises, so
    schema drift is caught at the call site rather than producing ragged
    CSVs.
    """

    def __init__(self, csv_path: str | None = None, echo: bool = False,
                 stream: IO[str] | None = None):
        self._csv_path = csv_path
        self._echo = echo
        self._stream = stream or sys.stderr
        self._writer: csv.DictWriter | None = None
        self._file: IO[str] | None = None
        self._fields: list[str] | None = None
        self._t0 = time.time()

    def __call__(self, iteration: int, metrics: Mapping[str, Any]) -> None:
        row = {"iteration": iteration,
               "wall_s": round(time.time() - self._t0, 3)}
        for k, v in metrics.items():
            row[k] = float(v) if hasattr(v, "__float__") else v
        if self._csv_path is not None:
            if self._writer is None:
                os.makedirs(os.path.dirname(self._csv_path) or ".",
                            exist_ok=True)
                self._file = open(self._csv_path, "w", newline="")
                self._fields = list(row)
                self._writer = csv.DictWriter(self._file, self._fields)
                self._writer.writeheader()
            elif set(row) != set(self._fields):
                raise ValueError(
                    f"metrics schema drift: first row had "
                    f"{sorted(self._fields)}, this row has {sorted(row)}")
            self._writer.writerow(row)
            self._file.flush()
        if self._echo:
            body = " ".join(f"{k}={v:.4g}" if isinstance(v, float)
                            else f"{k}={v}" for k, v in row.items()
                            if k != "iteration")
            print(f"[iter {iteration}] {body}", file=self._stream)

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class ThroughputMeter:
    """env-steps/sec tracker for the north-star throughput metric
    (SURVEY.md §6 metric #1). Call ``tick(n_steps)`` once per iteration."""

    def __init__(self):
        self._t0 = time.time()
        self._steps = 0

    def tick(self, n_steps: int) -> None:
        self._steps += int(n_steps)

    @property
    def steps_per_sec(self) -> float:
        dt = time.time() - self._t0
        return self._steps / dt if dt > 0 else 0.0
