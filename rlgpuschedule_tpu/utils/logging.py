"""Metrics logging (L6 aux): scalar curves to CSV + console.

Capability parity: SURVEY.md §2 "Metrics/logging" and §5 "Metrics /
logging / observability" — reward curves, env-steps/sec, avg/percentile
JCT, cluster utilization. The reference's TensorBoard-style scalar stream
becomes an append-only CSV (one row per logged iteration, stable header)
that pandas/TensorBoard ingest trivially; the JCT comparison table is
produced by ``eval.jct_report``/``format_report``.
"""
from __future__ import annotations

import csv
import os
import sys
import time
from typing import IO, Any, Mapping


class MetricsLogger:
    """Append scalar rows keyed by iteration; writes CSV and optionally
    mirrors a compact line to a stream.

    >>> log = MetricsLogger("out/metrics.csv", echo=True)
    >>> log(10, {"mean_reward": -0.5, "total_loss": 0.1})
    >>> log.close()

    The header is fixed by the first row (stable schema for the whole
    run); any later row whose keys differ from the first row's raises, so
    schema drift is caught at the call site rather than producing ragged
    CSVs.

    ``append=True`` is the supervisor-relaunch / ``--resume`` mode: an
    existing CSV's header is re-read and becomes the pinned schema, new
    rows are APPENDED after the history instead of truncating it (mode
    ``"w"`` silently wiped every pre-restart row — the metrics history a
    relaunch exists to continue), and a resumed run whose row keys drift
    from the original header raises the same schema error as in-run
    drift. An ``append=True`` open of a missing/empty file degrades to
    the fresh-file path.

    ``wall_s`` is a DURATION (seconds since this logger was built) and
    is therefore measured on ``time.monotonic()`` — a wall-clock step
    (NTP) mid-run would otherwise bend every downstream steps/s
    computation; event timestamps (wall time proper) belong to the obs
    event bus, not this column.
    """

    def __init__(self, csv_path: str | None = None, echo: bool = False,
                 stream: IO[str] | None = None, append: bool = False):
        self._csv_path = csv_path
        self._echo = echo
        self._append = append
        self._stream = stream or sys.stderr
        self._writer: csv.DictWriter | None = None
        self._file: IO[str] | None = None
        self._fields: list[str] | None = None
        self._t0 = time.monotonic()

    def _open(self, first_row: Mapping[str, Any]) -> None:
        os.makedirs(os.path.dirname(self._csv_path) or ".", exist_ok=True)
        header: list[str] | None = None
        if self._append and os.path.exists(self._csv_path):
            with open(self._csv_path, newline="") as f:
                header = next(csv.reader(f), None)
        if header:
            if set(first_row) != set(header):
                raise ValueError(
                    f"metrics schema drift across resume: existing CSV "
                    f"header has {sorted(header)}, this run logs "
                    f"{sorted(first_row)}")
            self._file = open(self._csv_path, "a", newline="")
            self._fields = list(header)   # keep the original column order
            self._writer = csv.DictWriter(self._file, self._fields)
        else:
            self._file = open(self._csv_path, "w", newline="")
            self._fields = list(first_row)
            self._writer = csv.DictWriter(self._file, self._fields)
            self._writer.writeheader()

    def __call__(self, iteration: int, metrics: Mapping[str, Any]) -> None:
        row = {"iteration": iteration,
               "wall_s": round(time.monotonic() - self._t0, 3)}
        for k, v in metrics.items():
            row[k] = float(v) if hasattr(v, "__float__") else v
        if self._csv_path is not None:
            if self._writer is None:
                self._open(row)
            elif set(row) != set(self._fields):
                raise ValueError(
                    f"metrics schema drift: first row had "
                    f"{sorted(self._fields)}, this row has {sorted(row)}")
            self._writer.writerow(row)
            self._file.flush()
        if self._echo:
            body = " ".join(f"{k}={v:.4g}" if isinstance(v, float)
                            else f"{k}={v}" for k, v in row.items()
                            if k != "iteration")
            print(f"[iter {iteration}] {body}", file=self._stream)

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _crc32c(data: bytes) -> int:
    """CRC-32C (Castagnoli), table-driven — the checksum TFRecord framing
    requires. Pure Python: the write cadence is one small record per logged
    iteration, so speed is irrelevant and we avoid a tensorflow import."""
    table = _crc32c_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


_CRC_TABLE: list[int] | None = None


def _crc32c_table() -> list[int]:
    global _CRC_TABLE
    if _CRC_TABLE is None:
        poly = 0x82F63B78
        table = []
        for n in range(256):
            crc = n
            for _ in range(8):
                crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
            table.append(crc)
        _CRC_TABLE = table
    return _CRC_TABLE


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


def _varint(n: int) -> bytes:
    if n < 0:   # proto int64: 10-byte two's-complement encoding
        n &= 0xFFFFFFFFFFFFFFFF
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _tb_event(wall_time: float, step: int,
              scalars: Mapping[str, float] | None = None,
              file_version: str | None = None) -> bytes:
    """Hand-encoded ``tensorflow.Event`` proto: wall_time (field 1,
    double), step (field 2, int64), file_version (3, string) or summary
    (5, message of Value{tag=1 string, simple_value=2 float})."""
    import struct
    ev = bytearray()
    ev += b"\x09" + struct.pack("<d", wall_time)
    ev += b"\x10" + _varint(step)
    if file_version is not None:
        fv = file_version.encode()
        ev += b"\x1a" + _varint(len(fv)) + fv
    if scalars:
        summary = bytearray()
        for tag, val in scalars.items():
            t = tag.encode()
            value = (b"\x0a" + _varint(len(t)) + t +
                     b"\x15" + struct.pack("<f", float(val)))
            summary += b"\x0a" + _varint(len(value)) + value
        ev += b"\x2a" + _varint(len(summary)) + bytes(summary)
    return bytes(ev)


class TensorBoardWriter:
    """Scalar curves as a TensorBoard event file — the reference family's
    usual dashboard (SURVEY.md §5 "Metrics / logging: TensorBoard [?]").

    Dependency-free by design: encodes the ``Event`` protobuf and TFRecord
    framing (length + masked-crc32c) by hand, ~40 lines instead of a
    tensorflow/tensorboard import on the training host. Files read back
    with any stock TensorBoard (round-trip pinned in tests/test_cli.py).

    >>> with TensorBoardWriter("out/tb") as tb:
    ...     tb(10, {"mean_reward": -0.5})
    """

    def __init__(self, logdir: str):
        import socket
        os.makedirs(logdir, exist_ok=True)
        name = (f"events.out.tfevents.{int(time.time())}."
                f"{socket.gethostname()}.{os.getpid()}")
        self.path = os.path.join(logdir, name)
        self._file: IO[bytes] = open(self.path, "wb")
        self._record(_tb_event(time.time(), 0,
                               file_version="brain.Event:2"))

    def _record(self, payload: bytes) -> None:
        import struct
        header = struct.pack("<Q", len(payload))
        self._file.write(header)
        self._file.write(struct.pack("<I", _masked_crc(header)))
        self._file.write(payload)
        self._file.write(struct.pack("<I", _masked_crc(payload)))
        self._file.flush()

    def __call__(self, step: int, metrics: Mapping[str, Any]) -> None:
        scalars = {k: float(v) for k, v in metrics.items()
                   if hasattr(v, "__float__")}
        if scalars:
            self._record(_tb_event(time.time(), int(step), scalars))

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None

    def __enter__(self) -> "TensorBoardWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class ThroughputMeter:
    """env-steps/sec tracker for the north-star throughput metric
    (SURVEY.md §6 metric #1). Call ``tick(n_steps)`` once per iteration.

    Durations come from ``time.monotonic()`` — the same wall-clock-jump
    bug class the heartbeat stamps fixed (PR 4): an NTP step mid-run
    would otherwise dent (or inflate) the headline steps/s. ``clock`` is
    injectable for deterministic tests."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._t0 = clock()
        self._steps = 0

    def tick(self, n_steps: int) -> None:
        self._steps += int(n_steps)

    @property
    def steps_per_sec(self) -> float:
        dt = self._clock() - self._t0
        return self._steps / dt if dt > 0 else 0.0
