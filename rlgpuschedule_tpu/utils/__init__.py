"""L6 auxiliary utilities: metrics logging, profiling/tracing."""
from .logging import MetricsLogger, TensorBoardWriter, ThroughputMeter
from .profiling import trace, debug_checks, SectionTimer

__all__ = ["MetricsLogger", "TensorBoardWriter", "ThroughputMeter",
           "trace", "debug_checks", "SectionTimer"]
