"""CPU-platform pinning shared by the test rig and the driver dryrun.

This machine's ``sitecustomize`` registers a real-TPU tunnel backend
("axon") in every Python process and pins ``jax_platforms`` to it; when
the tunnel is unhealthy, initializing that backend hangs forever. Both the
test suite (tests/conftest.py) and ``__graft_entry__.dryrun_multichip``
need the opposite: N virtual CPU devices, pinned before ANY jax backend
initializes (SURVEY.md §4 "Distributed without a real cluster"). One
helper so a jax upgrade that moves the private
``backends_are_initialized`` probe breaks exactly one place.
"""
from __future__ import annotations

import os


def enable_compile_cache(directory: str | None = None) -> str:
    """Point jax's persistent compilation cache at a stable directory and
    cache every compile (floor 0). The CLIs call this at startup: without
    it each training/eval PROCESS re-pays its XLA compiles — measured
    round 5, the config-1 grid-CNN program build alone is ~10 minutes on
    the 1-core host, re-paid per run, while the second process with a
    warm cache skips it. Honors an explicit ``JAX_COMPILATION_CACHE_DIR``
    (the test conftest routes through this helper too). The default is
    PER-USER (``~/.cache/rlgpuschedule/jax``), not a world-shared /tmp
    path: on a multi-user host a shared fixed path is both unwritable for
    the second user (jax silently disables caching) and poisonable (cache
    entries deserialize into executables). Returns the directory."""
    directory = (directory or os.environ.get("JAX_COMPILATION_CACHE_DIR")
                 or os.path.expanduser("~/.cache/rlgpuschedule/jax"))
    # the env var covers subprocesses (multihost workers, CLI re-execs)
    os.environ["JAX_COMPILATION_CACHE_DIR"] = directory
    import jax

    jax.config.update("jax_compilation_cache_dir", directory)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    try:
        # floor-0 caching from every CLI run accumulates; cap with LRU
        # eviction so the per-user dir stays bounded (~2 GiB)
        jax.config.update("jax_compilation_cache_max_size", 2 * 1024 ** 3)
    except AttributeError:
        pass  # older jax: no size knob; the floor-0 policy still applies
    return directory


def force_cpu(n_devices: int = 8) -> list:
    """Pin jax to the CPU platform with ``n_devices`` virtual devices and
    return them.

    jax may already be imported (sitecustomize imports it), but as long as
    its backends are still lazy the pin works: flip ``jax_platforms`` to
    cpu and set ``--xla_force_host_platform_device_count`` before first
    device access. If backends already initialized as CPU this is a no-op
    that returns the existing devices; if they initialized as anything
    else, raises with an actionable message (the fix is a fresh process)
    instead of the opaque backend errors that follow otherwise.

    The env-var mutations are reverted before returning: in-process the
    pin lives in the initialized backend, and leaking ``JAX_PLATFORMS=cpu``
    into the environment would silently force later-spawned subprocesses
    (e.g. a real-TPU bench) onto CPU.
    """
    prev = {k: os.environ.get(k) for k in ("JAX_PLATFORMS", "XLA_FLAGS")}
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    try:
        import jax

        if not jax._src.xla_bridge.backends_are_initialized():
            jax.config.update("jax_platforms", "cpu")
        try:
            devices = jax.devices("cpu")
        except RuntimeError as e:
            raise RuntimeError(
                f"cannot obtain CPU devices: jax backends were already "
                f"initialized (default backend "
                f"{jax.default_backend()!r}) before force_cpu could pin "
                f"the platform — run the CPU-mesh program in a fresh "
                f"process") from e
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    if not devices or any(d.platform != "cpu" for d in devices):
        raise RuntimeError(f"force_cpu got non-CPU devices: {devices}")
    if len(devices) < n_devices:
        raise RuntimeError(
            f"force_cpu({n_devices}) got only {len(devices)} CPU devices — "
            f"either the CPU backend initialized before this call, or "
            f"XLA_FLAGS already pins a smaller "
            f"xla_force_host_platform_device_count; a multichip program "
            f"must not silently degrade to {len(devices)} device(s)")
    return devices
