"""Evaluation CLI (L6): ``python -m rlgpuschedule_tpu.evaluate``.

Capability parity: SURVEY.md §3.4 — "run trained policy (or baseline) over
full trace, report JCT table" (the eval/replay script of §2 "Eval / trace
replay"). Loads a config (+ optional checkpoint), replays the trace windows
under the greedy policy and the oracle baselines, and prints the avg-JCT
comparison table — north-star metric #2's harness.

Examples::

    python -m rlgpuschedule_tpu.evaluate --config ppo-mlp-synth64
    python -m rlgpuschedule_tpu.evaluate --config ppo-cnn-philly512 \
        --trace-path philly.csv --ckpt-dir out/ckpt
    python -m rlgpuschedule_tpu.evaluate --config ppo-mlp-synth64 \
        --baselines-only
"""
from __future__ import annotations

import argparse
import contextlib
import dataclasses
import json
import sys

# tail-latency columns --percentiles adds (keep the flag's help in sync)
PERCENTILES = (50, 90, 99)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="rlgpuschedule_tpu.evaluate",
        description="JCT evaluation: trained policy vs baseline schedulers.")
    p.add_argument("--config", default="ppo-mlp-synth64")
    p.add_argument("--trace", default=None,
                   choices=["synthetic", "philly", "pai", "philly-proxy",
                            "pai-proxy"],
                   help="trace source override (same contract as train)")
    p.add_argument("--trace-path", default=None)
    p.add_argument("--trace-load", type=float, default=None,
                   help="proxy traces: offered-load target of the "
                        "EVALUATION stream (a replay-time knob, not part "
                        "of the checkpointed policy — e.g. evaluate a "
                        "load-1.1-trained policy on a load-1.6 overload "
                        "stream)")
    p.add_argument("--source-jobs", type=int, default=None,
                   help="generated traces: pin the evaluation source "
                        "trace size in jobs (e.g. a 100k-job held-out "
                        "stream for --full-trace)")
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--n-envs", type=int, default=None)
    # cluster-shape overrides — MUST match the training run when restoring
    # a checkpoint (shapes are part of the saved state)
    p.add_argument("--n-nodes", type=int, default=None)
    p.add_argument("--gpus-per-node", type=int, default=None)
    p.add_argument("--window-jobs", type=int, default=None)
    p.add_argument("--queue-len", type=int, default=None)
    p.add_argument("--horizon", type=int, default=None)
    p.add_argument("--obs-kind", default=None,
                   choices=["flat", "grid", "graph"],
                   help="must match the training run when restoring a "
                        "checkpoint (same contract as the cluster-shape "
                        "overrides)")
    p.add_argument("--drain-frac", type=float, default=None,
                   help="evaluate on backlog-drain copies of this fraction "
                        "of the windows (all jobs at t=0) — the regime the "
                        "drain curriculum trains on; use 1.0 to reproduce "
                        "the BASELINE.md drain tables")
    p.add_argument("--faults", default=None, metavar="REGIME",
                   help="config override matching a --faults TRAINING run "
                        "(the health channel is part of the checkpointed "
                        "observation space — same contract as the "
                        "cluster-shape overrides). Evaluation itself "
                        "stays clean unless --chaos is passed")
    p.add_argument("--domains", default=None, metavar="REGIME",
                   help="config override matching a --domains TRAINING "
                        "run (the geometry/health channels are part of "
                        "the checkpointed observation space — same "
                        "contract as --faults). Evaluation itself stays "
                        "on the fixed cluster unless --matrix is passed")
    p.add_argument("--matrix", action="store_true",
                   help="generalization matrix: replay the policy (plus "
                        "any --matrix-ckpt rows) AND the oracle "
                        "baselines under identical seeded DOMAIN draws "
                        "— randomized geometry, heterogeneous speeds, "
                        "arrival regimes up to 1.6× overload — and "
                        "report per-cell avg JCT, completion, and "
                        "DEGRADATION vs the fixed-cluster control — "
                        "flat configs")
    p.add_argument("--matrix-regimes", default=None, metavar="A,B,...",
                   help="with --matrix: comma-separated eval-regime "
                        "subset (domains.DOMAIN_REGIMES); the "
                        "fixed-cluster 'none' control is always included")
    p.add_argument("--matrix-baselines", default="sjf,tiresias",
                   metavar="A,B,...",
                   help="with --matrix: baseline scheduler rows next to "
                        "the policy (sim.schedulers.BASELINES)")
    p.add_argument("--matrix-seed", type=int, default=0,
                   help="with --matrix: base seed of the domain draws "
                        "and generated windows (env e draws (seed, e)); "
                        "recorded in the JSON repro tuple")
    p.add_argument("--matrix-ckpt", action="append", default=None,
                   metavar="REGIME=DIR",
                   help="with --matrix: add a policy row restored from "
                        "DIR, trained under --domains REGIME (use "
                        "'clean' for a checkpoint trained without "
                        "domains). Repeatable — the train-regime × "
                        "eval-regime cross table. Cluster shape must "
                        "match the --config")
    p.add_argument("--alarms", action="store_true",
                   help="with --matrix --obs-dir: production alarm scope "
                        "over the jitted matrix cells — a post-warmup "
                        "recompile or implicit transfer becomes an alarm "
                        "event (obs.report --strict-alarms gates on "
                        "them); the zero-retrace-across-domains contract, "
                        "enforced in CI")
    p.add_argument("--stitch-faults", default=None, metavar="REGIME",
                   help="with --full-trace: run the WHOLE stitched table "
                        "(policy rows and baselines) under one seeded "
                        "global-time fault schedule of this regime "
                        "(sim.faults.FAULT_REGIMES)")
    p.add_argument("--stitch-domain", default=None, metavar="REGIME",
                   help="with --full-trace: run the whole stitched table "
                        "on one seeded domain draw of this regime "
                        "(domains.DOMAIN_REGIMES) — heterogeneous "
                        "speeds / shrunken geometry; composes with "
                        "--stitch-faults (worst slowdown wins per node)")
    p.add_argument("--stitch-seed", type=int, default=0,
                   help="with --stitch-faults/--stitch-domain: seed of "
                        "the schedule draw; recorded in the repro tuple")
    p.add_argument("--chaos", action="store_true",
                   help="chaos evaluation matrix: replay the policy AND "
                        "the oracle baselines under identical seeded "
                        "fault schedules across regimes (none/sporadic "
                        "drains/drain storms/stragglers) and report "
                        "per-regime avg JCT, completion, and DEGRADATION "
                        "vs the clean regime — flat configs")
    p.add_argument("--chaos-regimes", default=None, metavar="A,B,...",
                   help="with --chaos: comma-separated regime subset "
                        "(sim.faults.FAULT_REGIMES); the clean 'none' "
                        "control is always included")
    p.add_argument("--chaos-baselines", default="sjf,tiresias",
                   metavar="A,B,...",
                   help="with --chaos: baseline scheduler columns next "
                        "to the policy (sim.schedulers.BASELINES)")
    p.add_argument("--chaos-seed", type=int, default=0,
                   help="with --chaos: base seed of the fault-schedule "
                        "draws (env e draws (seed, e)); recorded in the "
                        "JSON repro tuple")
    p.add_argument("--obs-dir", default=None,
                   help="with --chaos/--matrix: emit per-cell events "
                        "(env_fault / domain_cell, JSONL event bus) and "
                        "chaos_*/matrix_* gauges (metrics.prom) under "
                        "this directory so obs.report can tell the "
                        "story")
    p.add_argument("--trace-spans", action="store_true",
                   help="with --chaos --obs-dir: flight recorder — "
                        "record each regime row as nested "
                        "chaos_regime/policy_replay/baseline spans on "
                        "the event bus (export via obs.report "
                        "--trace-out). NOT --trace, which would be the "
                        "workload trace source")
    p.add_argument("--ckpt-dir", default=None,
                   help="restore the trained policy from this checkpoint "
                        "dir (omit = untrained init weights)")
    p.add_argument("--ckpt-step", type=int, default=None)
    p.add_argument("--max-steps", type=int, default=None)
    p.add_argument("--eval-windows", type=int, default=None,
                   help="evaluate on this many windows instead of --n-envs. "
                        "--n-envs must still match the TRAINING run (the "
                        "checkpoint's rollout carry restores into it), but "
                        "the replay itself has no batch-size constraint — "
                        "use a small value to evaluate a large-batch TPU "
                        "checkpoint on a CPU host")
    p.add_argument("--percentiles", action="store_true",
                   help="add p50/p90/p99 JCT tail-latency columns per "
                        "scheduler to the table (flat configs)")
    p.add_argument("--pbt", action="store_true",
                   help="evaluate a PBT population checkpoint (config 5): "
                        "restores the population from --ckpt-dir and "
                        "replays one member")
    p.add_argument("--n-pop", type=int, default=4,
                   help="with --pbt: population size of the training run")
    p.add_argument("--member", type=int, default=None,
                   help="with --pbt: member index to evaluate (default: "
                        "fittest by the controller's windowed fitness)")
    p.add_argument("--baselines-only", action="store_true")
    p.add_argument("--no-random", action="store_true",
                   help="skip the random-policy column")
    p.add_argument("--fairness", action="store_true",
                   help="multi-tenant fairness table: per-tenant avg JCT "
                        "+ Jain index, policy vs baselines (config 3)")
    p.add_argument("--full-trace", action="store_true",
                   help="evaluate over the ENTIRE source trace: policy via "
                        "sequential windowed replay with residual carry, "
                        "baselines via the native engine on the same trace")
    p.add_argument("--max-jobs", type=int, default=None,
                   help="with --full-trace: cap the source trace at the "
                        "first N jobs")
    p.add_argument("--stitch-window-jobs", type=int, default=None,
                   help="with --full-trace: stitch-replay through a "
                        "job-table of this size instead of the training "
                        "window_jobs — the policy nets are max_jobs-"
                        "independent, so a deeper stitch window widens "
                        "the backlog held between seams")
    p.add_argument("--stitch-drain-jobs", type=int, default=1,
                   help="with --full-trace: in deep-backlog mode, free "
                        "this many job-table rows per stitched window "
                        "instead of 1 before ingesting fresh jobs. The "
                        "default reproduces the recorded tables exactly "
                        "but makes window count linear in the backlog "
                        "excess — set ~max_jobs/8 for sustained-overload "
                        "streams of 10^5 jobs (fewer seams, same carry "
                        "approximation)")
    p.add_argument("--backlog-gate", type=int, default=0,
                   help="evaluate the backlog-gated HYBRID scheduler: "
                        "when fewer than N jobs are pending, play FIFO "
                        "(place the oldest job if it fits) instead of "
                        "the policy. A drain-trained policy adds "
                        "ordering delay on underloaded streams where "
                        "placing immediately is optimal (measured, "
                        "BASELINE.md config 4); the gate recovers the "
                        "FIFO tie there and keeps the learned policy "
                        "where backlogs are deep. Flat configs, policy "
                        "row only")
    p.add_argument("--stall-guard", dest="stall_guard", default=True,
                   action="store_true",
                   help="break eval-time place<->preempt argmax cycles by "
                        "masking preempt actions after the legitimate "
                        "zero-dt activity bound (preemptive configs; "
                        "default ON — the measured config-1p drain "
                        "deadlock, BASELINE.md)")
    p.add_argument("--no-stall-guard", dest="stall_guard",
                   action="store_false",
                   help="disable the guard (A/B the raw argmax replay; a "
                        "preemptive policy may then deadlock at <100% "
                        "completion — the completion guard will flag it)")
    return p


def main(argv: list[str] | None = None) -> dict:
    args = build_parser().parse_args(argv)
    from .configs import CONFIGS
    if args.config not in CONFIGS:
        sys.exit(f"unknown config {args.config!r}")
    cfg = CONFIGS[args.config]
    over = {k: v for k, v in
            {"trace": args.trace, "trace_path": args.trace_path,
             "trace_load": args.trace_load, "seed": args.seed,
             "source_jobs": args.source_jobs,
             "n_envs": args.n_envs, "n_nodes": args.n_nodes,
             "gpus_per_node": args.gpus_per_node,
             "window_jobs": args.window_jobs, "queue_len": args.queue_len,
             "horizon": args.horizon, "obs_kind": args.obs_kind,
             "drain_frac": args.drain_frac,
             "faults": args.faults,
             "domains": args.domains}.items() if v is not None}
    cfg = dataclasses.replace(cfg, **over)

    from .configs import ModeCombinationError, validate_mode_combination
    try:
        validate_mode_combination({
            "pbt": args.pbt,
            "faults": args.faults is not None,
            "domains": args.domains is not None,
        })
    except ModeCombinationError as e:
        sys.exit(str(e))

    if args.source_jobs is not None:
        if args.source_jobs <= 0:
            sys.exit("--source-jobs must be positive")
        if cfg.trace in ("philly", "pai"):
            sys.exit("--source-jobs sizes GENERATED traces; a CSV trace "
                     "is its file's own size (refusing the silent no-op)")

    from .eval import (baseline_jct_table, fairness_report, format_fairness,
                       format_report, full_trace_report, jct_report)
    from .experiment import Experiment, build_stack
    from .utils.platform import enable_compile_cache

    enable_compile_cache()

    if args.chaos:
        if (args.pbt or args.fairness or args.full_trace
                or args.baselines_only or args.percentiles
                or args.backlog_gate or cfg.n_pods > 1):
            sys.exit("--chaos is its own regime × scheduler matrix over "
                     "the window batch (flat configs): no --pbt/"
                     "--fairness/--full-trace/--baselines-only/"
                     "--percentiles/--backlog-gate")
        if args.eval_windows is not None:
            sys.exit("--chaos replays the experiment's window batch; "
                     "size it with --n-envs")
        from .sim.faults import FAULT_REGIMES
        from .sim.schedulers import BASELINES
        regimes = (tuple(s for s in args.chaos_regimes.split(",") if s)
                   if args.chaos_regimes else None)
        chaos_baselines = tuple(
            s for s in args.chaos_baselines.split(",") if s)
        bad = [r for r in (regimes or ()) if r not in FAULT_REGIMES]
        if bad:
            sys.exit(f"unknown --chaos-regimes {bad}; known: "
                     f"{sorted(FAULT_REGIMES)}")
        bad = [b for b in chaos_baselines if b not in BASELINES]
        if bad:
            sys.exit(f"unknown --chaos-baselines {bad}; known: "
                     f"{sorted(BASELINES)}")
    elif args.chaos_regimes is not None:
        sys.exit("--chaos-regimes configures the --chaos matrix; pass "
                 "--chaos with it (refusing the silent no-op)")
    if args.obs_dir and not (args.chaos or args.matrix):
        sys.exit("--obs-dir serves the --chaos and --matrix flows; pass "
                 "one of them with it (refusing the silent no-op)")
    if args.trace_spans and not (args.chaos and args.obs_dir):
        sys.exit("--trace-spans records spans on the chaos event bus; "
                 "pass --chaos and --obs-dir with it (refusing the "
                 "silent no-op)")

    if args.matrix:
        if (args.chaos or args.pbt or args.fairness or args.full_trace
                or args.baselines_only or args.percentiles
                or args.backlog_gate or cfg.n_pods > 1):
            sys.exit("--matrix is its own train-regime × eval-regime "
                     "table over generated domain windows (flat "
                     "configs): no --chaos/--pbt/--fairness/"
                     "--full-trace/--baselines-only/--percentiles/"
                     "--backlog-gate")
        if args.eval_windows is not None:
            sys.exit("--matrix generates its own window batch per "
                     "regime; size it with --n-envs")
        from .domains import DOMAIN_REGIMES
        from .sim.schedulers import BASELINES
        matrix_regimes = (tuple(s for s in args.matrix_regimes.split(",")
                                if s)
                          if args.matrix_regimes else None)
        matrix_baselines = tuple(
            s for s in args.matrix_baselines.split(",") if s)
        bad = [r for r in (matrix_regimes or ()) if r not in
               DOMAIN_REGIMES]
        if bad:
            sys.exit(f"unknown --matrix-regimes {bad}; known: "
                     f"{sorted(DOMAIN_REGIMES)}")
        bad = [b for b in matrix_baselines if b not in BASELINES]
        if bad:
            sys.exit(f"unknown --matrix-baselines {bad}; known: "
                     f"{sorted(BASELINES)}")
        matrix_ckpts = []
        for spec in args.matrix_ckpt or []:
            regime, sep, path = spec.partition("=")
            if not sep or not path or (regime != "clean" and
                                       regime not in DOMAIN_REGIMES):
                sys.exit(f"--matrix-ckpt wants REGIME=DIR with REGIME "
                         f"in {sorted(DOMAIN_REGIMES)} or 'clean' "
                         f"(got {spec!r})")
            matrix_ckpts.append((regime, path))
    elif (args.matrix_regimes is not None or args.matrix_ckpt
          or args.matrix_seed != 0 or args.alarms):
        sys.exit("--matrix-regimes/--matrix-ckpt/--matrix-seed/--alarms "
                 "configure the --matrix table; pass --matrix with them "
                 "(refusing the silent no-op)")
    if args.alarms and not args.obs_dir:
        sys.exit("--alarms raises its events on the --obs-dir bus; pass "
                 "--obs-dir with it")

    if (args.stitch_faults or args.stitch_domain) and not args.full_trace:
        sys.exit("--stitch-faults/--stitch-domain degrade the "
                 "--full-trace stitched replay; pass --full-trace with "
                 "them (refusing the silent no-op)")
    if args.stitch_seed != 0 and not (args.stitch_faults or
                                      args.stitch_domain):
        sys.exit("--stitch-seed seeds the --stitch-faults/--stitch-domain "
                 "draw; pass one of them with it")
    if args.stitch_faults is not None:
        from .sim.faults import FAULT_REGIMES
        if args.stitch_faults not in FAULT_REGIMES:
            sys.exit(f"unknown --stitch-faults {args.stitch_faults!r}; "
                     f"known: {sorted(FAULT_REGIMES)}")
    if args.stitch_domain is not None:
        from .domains import DOMAIN_REGIMES
        if args.stitch_domain not in DOMAIN_REGIMES:
            sys.exit(f"unknown --stitch-domain {args.stitch_domain!r}; "
                     f"known: {sorted(DOMAIN_REGIMES)}")

    # the full reproducibility tuple every evaluate JSON carries: enough
    # to regenerate any row (chaos-matrix rows included) exactly —
    # resolved checkpoint step filled in by restore() below. The tuple's
    # shape is shared with the serve CLI (configs.repro_tuple), so
    # serving numbers reproduce the same way evaluation numbers do
    from .configs import repro_tuple
    repro = repro_tuple(cfg, ckpt_dir=args.ckpt_dir)

    if args.percentiles and (args.fairness or args.baselines_only
                             or args.pbt):
        sys.exit("--percentiles applies to the per-window and --full-trace "
                 "JCT tables (flat configs, no --fairness/"
                 "--baselines-only/--pbt)")
    if args.eval_windows is not None and (args.pbt or args.fairness or
                                          args.full_trace or
                                          args.baselines_only):
        sys.exit("--eval-windows applies to the plain per-window JCT "
                 "table (population views carry no source trace; the "
                 "other modes define their own window batch)")
    if args.stitch_window_jobs is not None and not args.full_trace:
        sys.exit("--stitch-window-jobs applies to --full-trace stitched "
                 "replay only")
    if args.stitch_drain_jobs != 1 and not args.full_trace:
        sys.exit("--stitch-drain-jobs applies to --full-trace stitched "
                 "replay only")
    if args.stitch_drain_jobs < 1:
        sys.exit("--stitch-drain-jobs must be >= 1 (each deep-backlog "
                 "window must free at least one job-table row)")
    if args.backlog_gate < 0:
        sys.exit("--backlog-gate must be >= 0 (a negative gate would "
                 "silently run ungated)")
    if args.backlog_gate and (args.pbt or args.fairness or
                              args.baselines_only or cfg.n_pods > 1):
        sys.exit("--backlog-gate applies to the flat per-window and "
                 "--full-trace policy tables (the hierarchical action "
                 "space has no single FIFO fall-through action; "
                 "--baselines-only has no policy row)")
    if not args.stall_guard and (args.baselines_only or args.fairness
                                 or cfg.n_pods > 1
                                 or cfg.preempt_len == 0):
        sys.exit("--no-stall-guard applies to flat PREEMPTIVE configs' "
                 "policy rows (per-window, --full-trace, and flat --pbt "
                 "members): the guard only ever masks preempt actions, "
                 "so it is a no-op elsewhere, and the fairness path "
                 "does not plumb it; refusing beats silently changing "
                 "nothing)")

    if args.baselines_only:
        _, windows, _, _, _, _, _ = build_stack(cfg)
        report = baseline_jct_table(windows, cfg.n_nodes, cfg.gpus_per_node)
        print(format_report(report), file=sys.stderr)
        print(json.dumps({**report, "repro": repro}))
        return report

    def restore(target, label: str) -> None:
        if args.ckpt_dir:
            from .checkpoint import Checkpointer
            import os
            with Checkpointer(os.path.abspath(args.ckpt_dir)) as ckpt:
                target.restore_checkpoint(ckpt, step=args.ckpt_step)
                # resolved, not requested: the integrity fallback may
                # restore an older retained step than asked for
                repro["ckpt_step"] = ckpt.last_restored_step
            print(f"{label} restored from {args.ckpt_dir}", file=sys.stderr)
        else:
            print("note: no --ckpt-dir; evaluating untrained init weights",
                  file=sys.stderr)

    if args.pbt:
        if args.fairness or args.full_trace:
            sys.exit("--pbt supports the per-window JCT table "
                     "(hierarchical members replay per-window)")
        from .experiment import PopulationExperiment
        pop = PopulationExperiment.build(cfg, n_pop=args.n_pop)
        restore(pop, "population")
        # untrained populations have no fitness record to rank by
        member = args.member if args.member is not None else \
            (None if args.ckpt_dir else 0)
        exp = pop.member_eval_view(member)
        print(f"evaluating member {exp.member} of {args.n_pop}",
              file=sys.stderr)
    else:
        exp = Experiment.build(cfg)
        restore(exp, "policy")
    if args.chaos:
        import os

        from .eval import CHAOS_REGIMES, chaos_report, format_chaos
        bus = registry = tracer = None
        if args.obs_dir:
            from .obs import EventBus, Registry
            bus = EventBus(os.path.abspath(args.obs_dir), rank=0,
                           name="chaos")
            registry = Registry()
            if args.trace_spans:
                from .obs.trace import Tracer
                tracer = Tracer(bus, enabled=True)
        try:
            report = chaos_report(
                exp, regimes=regimes or CHAOS_REGIMES,
                baselines=chaos_baselines, max_steps=args.max_steps,
                seed=args.chaos_seed, bus=bus, registry=registry,
                tracer=tracer)
        finally:
            if bus is not None:
                bus.close()
        if registry is not None:
            registry.write(os.path.join(os.path.abspath(args.obs_dir),
                                        "metrics.prom"))
        print(format_chaos(report), file=sys.stderr)
        report["repro"] = dict(repro, chaos_seed=args.chaos_seed,
                               chaos_regimes=report["chaos_regimes"],
                               chaos_baselines=list(chaos_baselines))
        print(json.dumps(report))
        return report
    if args.matrix:
        import os

        from .eval import MATRIX_REGIMES, format_matrix, matrix_report
        # the experiment's own row, labeled by its training regime
        own = cfg.domains or "clean"
        policies = {own: (exp.apply_fn, exp.train_state.params,
                          exp.env_params)}
        for regime, path in matrix_ckpts:
            label = regime if regime not in policies else \
                f"{regime}@{len(policies)}"
            rcfg = dataclasses.replace(
                cfg, domains=None if regime == "clean" else regime)
            rexp = Experiment.build(rcfg)
            from .checkpoint import Checkpointer
            with Checkpointer(os.path.abspath(path)) as ck:
                rexp.restore_checkpoint(ck, step=None)
            print(f"matrix row {label!r} restored from {path}",
                  file=sys.stderr)
            policies[label] = (rexp.apply_fn, rexp.train_state.params,
                               rexp.env_params)
        bus = registry = alarms = None
        if args.obs_dir:
            from .obs import EventBus, Registry
            bus = EventBus(os.path.abspath(args.obs_dir), rank=0,
                           name="matrix")
            registry = Registry()
            if args.alarms:
                from .obs import Alarms
                alarms = Alarms(bus, registry, warmup_iters=1,
                                transfer_guard=True)
        try:
            with (alarms if alarms is not None
                  else contextlib.nullcontext()):
                report = matrix_report(
                    exp, regimes=matrix_regimes or MATRIX_REGIMES,
                    baselines=matrix_baselines, policies=policies,
                    max_steps=args.max_steps, seed=args.matrix_seed,
                    bus=bus, registry=registry, alarms=alarms)
        finally:
            if bus is not None:
                bus.close()
        if registry is not None:
            registry.write(os.path.join(os.path.abspath(args.obs_dir),
                                        "metrics.prom"))
        print(format_matrix(report), file=sys.stderr)
        report["repro"] = dict(
            repro, matrix_seed=args.matrix_seed,
            matrix_regimes=report["matrix_regimes"],
            matrix_baselines=list(matrix_baselines),
            matrix_ckpts=[f"{r}={p}" for r, p in matrix_ckpts])
        print(json.dumps(report))
        return report
    if args.fairness:
        report = fairness_report(exp, max_steps=args.max_steps)
        print(format_fairness(report), file=sys.stderr)
        import math

        # NaN is the deliberate nothing-completed sentinel, but bare NaN
        # tokens are invalid JSON — emit null so strict parsers (jq etc.)
        # can consume the CLI output
        def _json_safe(v):
            if isinstance(v, float) and not math.isfinite(v):
                return None
            if isinstance(v, dict):
                return {k: _json_safe(x) for k, x in v.items()}
            if isinstance(v, list):
                return [_json_safe(x) for x in v]
            return v
        print(json.dumps(_json_safe({**report, "repro": repro})))
        return report
    if args.full_trace:
        stitch_params = None
        if args.stitch_window_jobs is not None:
            if cfg.n_pods > 1:
                sys.exit("--stitch-window-jobs applies to flat configs "
                         "(full-trace evaluation has no hierarchical "
                         "form)")
            stitch_params = dataclasses.replace(
                exp.env_params, sim=dataclasses.replace(
                    exp.env_params.sim,
                    max_jobs=args.stitch_window_jobs))
        stitch_schedule = None
        if args.stitch_faults or args.stitch_domain:
            from .sim.faults import (fault_horizon, resolve_regime,
                                     sample_fault_schedule)
            if args.stitch_faults:
                stitch_schedule = sample_fault_schedule(
                    cfg.n_nodes, resolve_regime(args.stitch_faults),
                    (args.stitch_seed,), fault_horizon([exp.source]))
            if args.stitch_domain:
                from .domains import (domain_schedule, domain_stats,
                                      resolve_domain, sample_domain)
                draw = sample_domain(resolve_domain(args.stitch_domain),
                                     cfg.n_nodes, cfg.gpus_per_node,
                                     (args.stitch_seed,))
                stitch_schedule = domain_schedule(draw, stitch_schedule)
                repro["stitch_domain_draw"] = domain_stats(draw)
            repro["stitch_faults"] = args.stitch_faults
            repro["stitch_domain"] = args.stitch_domain
            repro["stitch_seed"] = args.stitch_seed
        report = full_trace_report(exp, max_jobs=args.max_jobs,
                                   include_random=not args.no_random,
                                   percentiles=PERCENTILES
                                   if args.percentiles else None,
                                   env_params=stitch_params,
                                   backlog_gate=args.backlog_gate,
                                   stall_guard=args.stall_guard,
                                   drain_completions=args.stitch_drain_jobs,
                                   faults=stitch_schedule)
    else:
        eval_windows = None
        if args.eval_windows is not None and \
                args.eval_windows != cfg.n_envs:
            # re-cut the evaluation window batch at the requested size,
            # keeping the checkpoint's restored tiling cursor so a
            # resized batch replays the same part of the trace the
            # default path would; the restored params have no batch
            # dimension, so only the restore template above needed the
            # training n_envs
            from .experiment import make_env_windows
            eval_windows = make_env_windows(
                dataclasses.replace(cfg, n_envs=args.eval_windows),
                exp.source, start=exp.window_cursor)
        report = jct_report(exp, windows=eval_windows,
                            max_steps=args.max_steps,
                            include_random=not args.no_random,
                            percentiles=PERCENTILES if args.percentiles
                            else None,
                            backlog_gate=args.backlog_gate,
                            stall_guard=args.stall_guard)
    print(format_report(report), file=sys.stderr)
    out = {k: v for k, v in report.items() if isinstance(v, (int, float))}
    if "percentiles" in report:
        out["percentiles"] = report["percentiles"]
    out["repro"] = repro
    print(json.dumps(out))
    return report


if __name__ == "__main__":
    main()
