"""Training CLI (L6): ``python -m rlgpuschedule_tpu.train --config <name>``.

Capability parity: SURVEY.md §2 "Config/flags" and §3.1 "cli main (parse
flags, seed, build trace)" — entry script selecting trace, cluster size,
algorithm, encoder, env count, seeds; checkpointing; metric logging. The
five driver capability configs are the named presets (``--list-configs``);
every preset axis can be overridden from the command line.

Examples::

    python -m rlgpuschedule_tpu.train --config ppo-mlp-synth64
    python -m rlgpuschedule_tpu.train --config ppo-cnn-philly512 \
        --trace philly --trace-path philly.csv --iterations 200 \
        --ckpt-dir out/ckpt --log-csv out/metrics.csv --log-every 10 --report
    python -m rlgpuschedule_tpu.train --config hier-pbt-member \
        --pbt --n-pop 4 --pbt-ready 10            # config 5: PBT population
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import re
import sys

from .configs import (CONFIGS, ExperimentConfig, ModeCombinationError,
                      validate_mode_combination)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="rlgpuschedule_tpu.train",
        description="Train an RL GPU-cluster scheduling policy (TPU-native).")
    p.add_argument("--config", default="ppo-mlp-synth64",
                   help="named preset (see --list-configs)")
    p.add_argument("--list-configs", action="store_true")
    # config overrides (None = keep preset value)
    p.add_argument("--iterations", type=int, default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--n-envs", type=int, default=None)
    p.add_argument("--n-nodes", type=int, default=None)
    p.add_argument("--gpus-per-node", type=int, default=None)
    p.add_argument("--window-jobs", type=int, default=None)
    p.add_argument("--queue-len", type=int, default=None,
                   help="pending-queue slots the agent sees/acts on (the "
                        "policy's visibility into the backlog)")
    p.add_argument("--horizon", type=int, default=None)
    p.add_argument("--obs-kind", default=None,
                   choices=["flat", "grid", "graph"],
                   help="override the preset's observation/encoder family "
                        "(e.g. train config 2's cluster on the flat MLP "
                        "encoder on a CPU host)")
    p.add_argument("--trace", default=None,
                   choices=["synthetic", "philly", "pai", "philly-proxy",
                            "pai-proxy"],
                   help="trace source (e.g. switch a -proxy preset to the "
                        "real CSV loader)")
    p.add_argument("--trace-path", default=None,
                   help="CSV path for philly/pai traces")
    p.add_argument("--trace-load", type=float, default=None,
                   help="proxy traces: offered-load target (default 1.1)")
    p.add_argument("--source-jobs", type=int, default=None,
                   help="generated traces: pin the source trace size in "
                        "jobs (default: one window-streaming pass over "
                        "the env batch). The north-star full-Philly run "
                        "pins 100k+ explicitly")
    p.add_argument("--resample-every", type=int, default=None,
                   help="window streaming: rotate env windows over the "
                        "source trace every N iterations (0 = static)")
    p.add_argument("--drain-frac", type=float, default=None,
                   help="backlog-drain curriculum: fraction of envs that "
                        "train on drained copies of their windows (all "
                        "jobs at t=0)")
    p.add_argument("--faults", default=None, metavar="REGIME",
                   help="cluster chaos: train on a seeded in-simulator "
                        "fault distribution — per-env node-drain/"
                        "straggler schedules (sim.faults.FAULT_REGIMES: "
                        "none/sporadic/storm/straggler) threaded through "
                        "the rollout next to the traces; flat configs "
                        "also expose per-node health in the observation. "
                        "Evaluate the result with evaluate --chaos")
    p.add_argument("--domains", default=None, metavar="REGIME",
                   help="domain randomization: train ONE policy across a "
                        "seeded distribution of clusters — randomized "
                        "geometry (per-node capacity), heterogeneous "
                        "hardware speeds, and arrival regimes up to "
                        "sustained overload (domains.DOMAIN_REGIMES: "
                        "none/baseline/geom/hetero/overload/flash/"
                        "mixed); per-env draws ride the fault-schedule "
                        "slot, windows are GENERATED from the trace's "
                        "fitted job mix against each draw's actual "
                        "capacity. Composes with --faults (worst "
                        "slowdown wins per node). Evaluate the result "
                        "with evaluate --matrix")
    # algorithm hyperparameter overrides (apply to the active algo's
    # config — cfg.ppo or cfg.a2c; None = keep preset value). Large-batch
    # TPU runs typically want a higher --lr than the preset 3e-4, which
    # was tuned at config-1 batch sizes.
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--ent-coef", type=float, default=None)
    p.add_argument("--n-steps", type=int, default=None,
                   help="rollout length T per iteration")
    # update geometry (algos.update): n_epochs x n_minibatches x
    # minibatch_size, validated against n_steps * n_envs at build time.
    # Applies to BOTH algorithms — A2C's default 1x1 is the classic
    # full-batch update; any other geometry runs the same fused engine.
    p.add_argument("--n-epochs", type=int, default=None,
                   help="update epochs per iteration")
    p.add_argument("--n-minibatches", type=int, default=None,
                   help="minibatches per update epoch")
    p.add_argument("--minibatch-size", type=int, default=None,
                   help="explicit minibatch size (overrides "
                        "--n-minibatches; must tile n_steps * n_envs — "
                        "the fewer-larger-minibatch throughput lever, "
                        "sweepable via profile_breakdown "
                        "--sweep-minibatch)")
    p.add_argument("--bf16-update", action="store_true", default=None,
                   help="bf16-compute / fp32-optimizer-state update path "
                        "(NOT bit-identical to the fp32 default)")
    # fused advantage pipeline (ISSUE 12): off-policy correction +
    # streaming reward normalization + compact advantage storage
    p.add_argument("--correction", default=None,
                   choices=["none", "vtrace"],
                   help="off-policy advantage correction (PPO only). "
                        "'vtrace' re-weights the advantage scan by "
                        "rho/c-clipped importance ratios (algos.vtrace) "
                        "so deep --staleness-bound queues train without "
                        "bias; requires --async (on-policy ratios are "
                        "identically 1 and the correction reduces "
                        "bit-identically to the GAE path, so the sync "
                        "combination is refused as a silent no-op)")
    p.add_argument("--reward-norm", action="store_true", default=None,
                   help="streaming reward standardization: scale rewards "
                        "by a running inverse-std (Welford moments "
                        "carried in the train state, scale-only — no "
                        "centering, so sparse-reward signs survive) "
                        "before the advantage scan")
    p.add_argument("--bf16-advantages", action="store_true", default=None,
                   help="store advantage/return targets in bfloat16 "
                        "between the advantage scan and the minibatch "
                        "epochs (halves the target buffer; NOT "
                        "bit-identical — loss math upcasts to fp32)")
    # async actor-learner split (async_engine; opt-in)
    p.add_argument("--async", dest="async_run", action="store_true",
                   help="overlapped actor-learner engine: rollout "
                        "collection on one device group overlaps the "
                        "minibatch update on another, coupled by a "
                        "bounded device-side trajectory queue "
                        "(Sebulba split). Single-run configs only; "
                        "--staleness-bound 0 reproduces the sync loop "
                        "bit-identically")
    p.add_argument("--actor-devices", default=None, metavar="N|I,J,..",
                   help="with --async: actor group as a device COUNT "
                        "(taken from the front of the visible list) or "
                        "explicit comma-separated device indices. "
                        "Default: first half (one device: shared group)")
    p.add_argument("--learner-devices", default=None, metavar="N|I,J,..",
                   help="with --async: learner group (count from the "
                        "back, or explicit indices; must be disjoint "
                        "from the actor group unless identical)")
    p.add_argument("--staleness-bound", type=int, default=1,
                   help="with --async: max update-steps the policy that "
                        "collected a batch may lag the learner at "
                        "consume time (0 = lock-step, bit-identical to "
                        "the sync path; default 1). Bounds >= 4 run the "
                        "queue deep enough to hide slow actors but bias "
                        "the clip-only surrogate — pair them with "
                        "--correction vtrace")
    p.add_argument("--queue-capacity", type=int, default=2,
                   help="with --async: trajectory-queue slots; a full "
                        "queue blocks the actor (backpressure, no drops)")
    p.add_argument("--mesh", default="off", metavar="off|auto|PxDxM",
                   help="rule-table sharding for the single-run path: "
                        "build the unified Mesh(pop x data x model) and "
                        "jit the train step with in/out shardings "
                        "resolved from the model family's partition-rule "
                        "table (parallel.sharding). 'auto' picks the "
                        "largest data axis dividing both n_envs and the "
                        "device count (model axis 1 — bit-identical "
                        "layout to replication); an explicit PxDxM "
                        "triple (e.g. 1x2x2) also engages the model "
                        "axis. 'off' (default) is the plain jit path")
    # population / PBT (config 5)
    p.add_argument("--pbt", action="store_true",
                   help="train a PBT population instead of a single run")
    p.add_argument("--n-pop", type=int, default=4)
    p.add_argument("--pbt-ready", type=int, default=10,
                   help="iterations between exploit/explore rounds")
    # logging / checkpointing / profiling
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--eval-every", type=int, default=0,
                   help="every N iterations, replay the policy greedily on "
                        "a small HELD-OUT window batch and log avg JCT + "
                        "vs_tiresias (the in-training quality probe; "
                        "single-run configs). Rows go to <log-csv>.eval.csv")
    p.add_argument("--eval-windows", type=int, default=4,
                   help="held-out windows per --eval-every probe")
    p.add_argument("--eval-seed", type=int, default=None,
                   help="seed of the held-out eval trace (default: "
                        "training seed + 1000)")
    p.add_argument("--eval-probe", default="auto",
                   choices=["auto", "drain", "stream"],
                   help="probe regime: auto = drain for drain-curriculum "
                        "configs else streaming. Use 'stream' when the "
                        "deliverable is a streaming/full-trace table — "
                        "measured: drain-probe checkpoint selection does "
                        "not rank streaming quality")
    p.add_argument("--keep-best", action="store_true",
                   help="with --eval-every and --ckpt-dir: whenever the "
                        "held-out probe's avg JCT improves (at full "
                        "completion), save a checkpoint under "
                        "<ckpt-dir>/best — automated model selection "
                        "against late-training collapse")
    p.add_argument("--log-csv", default=None)
    p.add_argument("--tb-dir", default=None,
                   help="also write scalar curves as a TensorBoard event "
                        "file under this directory")
    p.add_argument("--ckpt-dir", default=None)
    p.add_argument("--ckpt-every", type=int, default=50)
    p.add_argument("--ckpt-keep", type=int, default=None,
                   help="retain the last N periodic checkpoints (default "
                        "3). Measured round 5: per-window probes do not "
                        "rank full-trace quality, so keep a SERIES and "
                        "select post-hoc with select_checkpoint against "
                        "a held-out validation stream instead of "
                        "trusting the probe's single best")
    p.add_argument("--resume", action="store_true",
                   help="restore the latest checkpoint from --ckpt-dir")
    # continual training from served traffic (ISSUE 19 data flywheel)
    p.add_argument("--continual", default=None, metavar="LOGDIR",
                   help="continual-training mode: instead of simulator "
                        "rollouts, ingest the crc-verified served-traffic "
                        "flight log under LOGDIR (serve --flight-log) and "
                        "run --iterations V-trace-corrected updates over "
                        "its pseudo-trajectories (flywheel.continual; "
                        "default 1 iteration). Policy lag is measured per "
                        "shard (staleness + importance-ratio gauges) and "
                        "shards outside the trust region are refused. "
                        "Composes with --ckpt-dir/--resume (restore the "
                        "incumbent, retrain, save the candidate)")
    p.add_argument("--continual-trust", type=float, default=2.0,
                   help="ingest trust region: refuse shards whose mean "
                        "importance ratio leaves [1/T, T]")
    p.add_argument("--continual-rho-max", type=float, default=8.0,
                   help="ingest trust region: refuse shards whose max "
                        "importance ratio exceeds this")
    p.add_argument("--fused-chunk", type=int, default=1,
                   help="dispatch N train steps as one on-device scan "
                        "between hook boundaries (every active log/eval/"
                        "ckpt/resample cadence must be a multiple of N). "
                        "Under the TPU tunnel each dispatch is a remote "
                        "RPC — chunking amortizes it. Single-run configs "
                        "only (--pbt is refused: its exploit/explore "
                        "interleaves host-side between steps)")
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler trace of the run")
    # observability (obs/): structured event bus + metrics snapshot +
    # production alarms — the run's post-mortem surface
    p.add_argument("--obs-dir", default=None,
                   help="unified telemetry: append structured events "
                        "(JSONL event bus, schema-versioned, rank/pid/"
                        "monotonic-stamped) and a Prometheus-text "
                        "metrics snapshot (metrics.prom) under this "
                        "directory; post-mortem via "
                        "python -m rlgpuschedule_tpu.obs.report <dir>")
    p.add_argument("--alarms", action="store_true",
                   help="production alarms (requires --obs-dir): a "
                        "post-warmup dispatch that traces/compiles emits "
                        "a recompile event (the silent throughput killer "
                        "the test-only CompileCounter gate catches only "
                        "in CI), and an implicit host<->device transfer "
                        "in the dispatch emits a transfer event and "
                        "fails fast")
    p.add_argument("--alarm-slow-iter", type=float, default=None,
                   metavar="SECONDS",
                   help="with --alarms: an iteration slower than this "
                        "emits a slow_iteration event and auto-captures "
                        "a one-shot jax.profiler trace of the NEXT "
                        "iteration under <obs-dir>/profile")
    p.add_argument("--trace-spans", action="store_true",
                   help="flight recorder (requires --obs-dir): record "
                        "nested phase spans (iteration/step/sync/... and "
                        "the async engine's actor/learner/queue-wait "
                        "lanes) on the event bus; export with "
                        "obs.report --trace-out trace.json (Perfetto). "
                        "NOT --trace, which picks the workload trace "
                        "source")
    p.add_argument("--debug-nans", action="store_true",
                   help="run under jax_debug_nans (sanitizer hook — the "
                        "functional design has no data races to detect, so "
                        "NaN-poisoning is the remaining numeric hazard; "
                        "fails fast with a traceback at the first NaN)")
    # resilience (SURVEY.md §5 "Failure detection"): the divergence
    # watchdog + deterministic fault injection, demonstrable end to end
    p.add_argument("--max-rollbacks", type=int, default=None,
                   help="attach the divergence watchdog: a non-finite or "
                        "exploding iteration rolls the run back to the "
                        "last good checkpoint with a decayed LR, giving "
                        "up cleanly after N rollbacks (requires "
                        "--ckpt-dir; see resilience.DivergenceWatchdog)")
    p.add_argument("--fault", action="append", default=None,
                   metavar="KIND@N[:rank=R]",
                   help="deterministic fault injection (repeatable): "
                        "nan-grad@K poisons params+metrics at iteration "
                        "K (PBT: rank=M selects the member), "
                        "corrupt-ckpt@K truncates the checkpoint saved "
                        "at iteration K. kill-rank/lose-rank are refused "
                        "here (multihost only — drive them with "
                        "__graft_entry__.dryrun_multihost_supervised / "
                        "dryrun_multihost_elastic)")
    p.add_argument("--report", action="store_true",
                   help="print the JCT-vs-baselines table after training "
                        "(single-run, non-hierarchical configs)")
    return p


def apply_overrides(cfg: ExperimentConfig,
                    args: argparse.Namespace) -> ExperimentConfig:
    fields = {"iterations": args.iterations, "seed": args.seed,
              "n_envs": args.n_envs, "n_nodes": args.n_nodes,
              "gpus_per_node": args.gpus_per_node,
              "window_jobs": args.window_jobs, "horizon": args.horizon,
              "queue_len": args.queue_len, "obs_kind": args.obs_kind,
              "trace": args.trace, "trace_path": args.trace_path,
              "trace_load": args.trace_load,
              "source_jobs": args.source_jobs,
              "resample_every": args.resample_every,
              "drain_frac": args.drain_frac, "faults": args.faults,
              "domains": args.domains}
    cfg = dataclasses.replace(
        cfg, **{k: v for k, v in fields.items() if v is not None})
    algo_fields = {"lr": args.lr, "ent_coef": args.ent_coef,
                   "n_steps": args.n_steps,
                   # both algorithms run the shared minibatch-geometry
                   # engine (algos.update); A2C's preset 1x1 geometry is
                   # the classic full-batch update
                   "n_epochs": args.n_epochs,
                   "n_minibatches": args.n_minibatches,
                   "minibatch_size": args.minibatch_size,
                   "bf16_update": args.bf16_update,
                   # both algo configs carry the fused-pipeline knobs...
                   "reward_norm": args.reward_norm,
                   "bf16_advantages": args.bf16_advantages}
    over = {k: v for k, v in algo_fields.items() if v is not None}
    # ...but only PPO has an off-policy correction (A2C's single-epoch
    # full-batch update consumes each batch once, at its own policy)
    if args.correction is not None:
        if cfg.algo != "ppo":
            sys.exit("--correction selects the PPO advantage pipeline "
                     "(algos.vtrace); the A2C update has no importance-"
                     "corrected variant")
        over["correction"] = args.correction
    if over:
        algo = "ppo" if cfg.algo == "ppo" else "a2c"
        cfg = dataclasses.replace(
            cfg, **{algo: dataclasses.replace(getattr(cfg, algo), **over)})
    return cfg


def make_eval_probe(cfg: ExperimentConfig, exp, n_windows: int,
                    eval_seed: int | None, regime: str = "auto"):
    """The --eval-every in-training quality probe: a greedy replay on a
    held-out window batch (fresh trace seed, so never trained on), scored
    against oracle baselines computed ONCE. Returns ``eval_fn(i) -> dict``
    for :meth:`Experiment.run`. The replay program compiles on the first
    probe and is reused after (fixed shapes).

    ``regime``: "auto" probes all-drain for drain-curriculum configs and
    all-streaming otherwise; "drain"/"stream" force one. Measured round 5:
    a drain-probe-selected config-1 "best" checkpoint read 1.08 vs
    Tiresias on the STREAMING full-trace where round 3's comparable run
    read 0.80 — drain quality does not rank streaming quality, so a run
    whose deliverable is the full-trace table must probe (and keep-best
    on) the streaming regime it will be judged in."""
    from . import eval as eval_lib
    from .env import env as env_lib
    from .experiment import load_source_trace, make_env_windows
    from .sim.core import validate_trace

    import sys

    if cfg.trace in ("philly", "pai"):
        # CSV loaders take no seed: there is no second trace to hold out,
        # so the probe replays leading windows of the TRAINING csv —
        # on-distribution, not held-out. Refuse a seed that would
        # otherwise be a silent no-op, and say what the number means.
        if eval_seed is not None:
            sys.exit("--eval-seed has no effect for csv traces "
                     "(philly/pai load a file, not a seeded generator)")
        print("note: --eval-every probe windows come from the training "
              "CSV (csv traces have no held-out seed); treat the curve "
              "as on-distribution quality, not generalization",
              file=sys.stderr)
    seed = cfg.seed + 1000 if eval_seed is None else eval_seed
    # probe one regime, not a mix: a fractional drain_frac would pool two
    # incomparable regimes into one number
    if regime == "auto":
        regime = "drain" if cfg.drain_frac > 0 else "stream"
    if regime not in ("drain", "stream"):
        raise ValueError(f"unknown probe regime {regime!r}")
    # source_jobs=None: the probe's trace is sized to its own window
    # batch — inheriting a pinned 100k-job source would generate and
    # validate the whole thing just to cut n_windows leading windows
    ecfg = dataclasses.replace(cfg, n_envs=n_windows, seed=seed,
                               source_jobs=None,
                               drain_frac=1.0 if regime == "drain"
                               else 0.0)
    sim_params = (exp.env_params.sim
                  if hasattr(exp.env_params, "sim") else
                  exp.env_params.pod_sim)
    source = validate_trace(sim_params, load_source_trace(ecfg),
                            clamp=True)
    windows = make_env_windows(ecfg, source)
    traces = env_lib.stack_traces(windows, sim_params)
    baselines = eval_lib.baseline_jct_table(
        windows, cfg.n_nodes, cfg.gpus_per_node,
        names=("fifo", "tiresias"))

    def eval_fn(_i: int) -> dict:
        res = eval_lib.replay(exp.apply_fn, exp.train_state.params,
                              exp.env_params, traces)
        jct, completion = eval_lib.pooled_avg_jct(res)
        out = {"eval_avg_jct": jct, "eval_completion": completion,
               **{f"eval_{k}": v for k, v in baselines.items()}}
        if baselines.get("tiresias"):
            out["eval_vs_tiresias"] = jct / baselines["tiresias"]
        return out

    return eval_fn


class FittestMemberView:
    """Experiment-like adapter over a :class:`PopulationExperiment` for
    :func:`make_eval_probe`: ``train_state.params`` resolves to the
    FITTEST member's params at probe time (the controller has recorded
    fitness by then — the population run fires eval hooks after the
    iteration's record), so the in-training probe and ``--keep-best``
    track the population's best member rather than a fixed index. The
    population-drift failure mode this closes has cost a best-population
    twice (VERDICT r5 weak #2)."""

    def __init__(self, pop):
        self._pop = pop

    @property
    def env_params(self):
        return self._pop.env_params

    @property
    def apply_fn(self):
        return self._pop.apply_fn

    @property
    def train_state(self):
        return self._pop.member_eval_view().train_state


def make_pop_mesh(n_pop: int):
    """Best unified mesh for a population run: the largest pop axis that
    divides both the population and the device count (1 device → no
    mesh), remaining devices on the data axis, model axis free at 1.
    Built through the SAME ``make_unified_mesh`` every other entry point
    resolves placements from."""
    import jax
    from .parallel import make_unified_mesh
    n_dev = jax.device_count()
    if n_dev == 1:
        return None
    pop_axis = 1
    for c in range(min(n_pop, n_dev), 0, -1):
        if n_pop % c == 0 and n_dev % c == 0:
            pop_axis = c
            break
    return make_unified_mesh(n_pop=pop_axis)


def make_run_mesh(spec: str, n_envs: int):
    """Resolve ``--mesh`` into a unified mesh (or None for the plain
    path). ``auto`` puts the largest data axis that divides both the env
    batch and the device count, model axis 1; an explicit ``PxDxM``
    triple engages exactly P*D*M devices."""
    import jax
    from .parallel import make_unified_mesh
    if spec == "off":
        return None
    devices = jax.devices()
    if spec == "auto":
        n_dev = len(devices)
        data = 1
        for c in range(min(n_envs, n_dev), 0, -1):
            if n_envs % c == 0 and n_dev % c == 0:
                data = c
                break
        if data == 1 and n_dev == 1:
            return None
        return make_unified_mesh(devices=devices[:data])
    p, d, m = (int(x) for x in spec.split("x"))
    if p * d * m == 0:
        sys.exit(f"bad --mesh {spec!r}: every axis must be >= 1")
    if p * d * m > len(devices):
        sys.exit(f"--mesh {spec} asks for {p * d * m} devices but only "
                 f"{len(devices)} are visible")
    if n_envs % d:
        sys.exit(f"--mesh {spec}: data axis {d} does not divide "
                 f"n_envs={n_envs}")
    return make_unified_mesh(n_pop=p, n_model=m,
                             devices=devices[:p * d * m])


def main(argv: list[str] | None = None) -> dict:
    args = build_parser().parse_args(argv)
    if args.list_configs:
        for name, c in CONFIGS.items():
            print(f"{name:20s} algo={c.algo} obs={c.obs_kind} "
                  f"cluster={c.n_nodes}x{c.gpus_per_node} trace={c.trace}"
                  f"{' pods=' + str(c.n_pods) if c.n_pods > 1 else ''}")
        return {}
    if args.config not in CONFIGS:
        sys.exit(f"unknown config {args.config!r}; try --list-configs")
    if args.keep_best and not (args.eval_every and args.ckpt_dir):
        sys.exit("--keep-best requires --eval-every (the probe that "
                 "defines 'best') and --ckpt-dir (where best/ lives)")
    if args.eval_probe != "auto" and not args.eval_every:
        sys.exit("--eval-probe selects the --eval-every probe's regime; "
                 "without --eval-every no probe runs and the flag would "
                 "be a silent no-op")
    if args.ckpt_keep is not None:
        if args.ckpt_keep < 1:
            sys.exit("--ckpt-keep must be >= 1")
        if not args.ckpt_dir:
            sys.exit("--ckpt-keep requires --ckpt-dir (nothing is "
                     "retained without one)")
    faults = []
    if args.fault:
        from .resilience import parse_fault
        try:
            faults = [parse_fault(s) for s in args.fault]
        except ValueError as e:
            sys.exit(str(e))
        if any(f.kind in ("kill-rank", "lose-rank") for f in faults):
            sys.exit("kill-rank/lose-rank are multihost faults and this "
                     "CLI is one process; drive them with __graft_entry__"
                     ".dryrun_multihost_supervised / "
                     "dryrun_multihost_elastic")
        if any(f.kind == "corrupt-ckpt" for f in faults) \
                and not args.ckpt_dir:
            sys.exit("--fault corrupt-ckpt requires --ckpt-dir (no "
                     "checkpoint is ever written without one)")
    if args.max_rollbacks is not None:
        if args.max_rollbacks < 0:
            sys.exit("--max-rollbacks must be >= 0")
        if not args.ckpt_dir:
            sys.exit("--max-rollbacks requires --ckpt-dir (rollback "
                     "restores the last good checkpoint)")
    if args.faults is not None:
        from .sim.faults import FAULT_REGIMES
        if args.faults not in FAULT_REGIMES:
            sys.exit(f"unknown --faults regime {args.faults!r}; known: "
                     f"{sorted(FAULT_REGIMES)}")
    if args.domains is not None:
        from .domains import DOMAIN_REGIMES
        if args.domains not in DOMAIN_REGIMES:
            sys.exit(f"unknown --domains regime {args.domains!r}; known: "
                     f"{sorted(DOMAIN_REGIMES)}")
    if args.mesh != "off" and args.mesh != "auto" \
            and not re.fullmatch(r"\d+x\d+x\d+", args.mesh):
        sys.exit(f"bad --mesh {args.mesh!r}: expected off, auto, or an "
                 f"explicit PxDxM axis triple like 1x2x1")
    if not args.async_run:
        for flag, val, default in (("--actor-devices",
                                    args.actor_devices, None),
                                   ("--learner-devices",
                                    args.learner_devices, None),
                                   ("--staleness-bound",
                                    args.staleness_bound, 1),
                                   ("--queue-capacity",
                                    args.queue_capacity, 2)):
            if val != default:
                sys.exit(f"{flag} configures the async engine; pass "
                         f"--async with it (refusing the silent no-op)")
    else:
        if args.staleness_bound < 0:
            sys.exit("--staleness-bound must be >= 0")
        if args.queue_capacity < 1:
            sys.exit("--queue-capacity must be >= 1")
    if args.continual is None:
        for flag, val, default in (
                ("--continual-trust", args.continual_trust, 2.0),
                ("--continual-rho-max", args.continual_rho_max, 8.0)):
            if val != default:
                sys.exit(f"{flag} tunes the --continual ingest trust "
                         f"region; pass --continual LOGDIR with it "
                         f"(refusing the silent no-op)")
    else:
        if args.continual_trust < 1.0:
            sys.exit("--continual-trust must be >= 1.0 (the region is "
                     "[1/T, T])")
        if args.continual_rho_max <= 0:
            sys.exit("--continual-rho-max must be positive")
    if args.alarms and not args.obs_dir:
        sys.exit("--alarms requires --obs-dir (alarm events need an "
                 "event stream to land in)")
    if args.trace_spans and not args.obs_dir:
        sys.exit("--trace-spans requires --obs-dir (span events need an "
                 "event stream to land in)")
    if args.alarm_slow_iter is not None:
        if not args.alarms:
            sys.exit("--alarm-slow-iter is an alarm trigger; pass "
                     "--alarms (and --obs-dir) with it")
        if args.alarm_slow_iter <= 0:
            sys.exit("--alarm-slow-iter must be positive")
    cfg = apply_overrides(CONFIGS[args.config], args)
    # the ONE mode-combination gate: every pairwise refusal lives in
    # configs.MODE_REFUSALS (one validated table, one error format)
    # instead of per-flag checks scattered through this function
    try:
        validate_mode_combination({
            "async": args.async_run,
            "pbt": args.pbt,
            "faults": args.faults is not None,
            "domains": cfg.domains is not None,
            "fault_injection": bool(faults),
            "fused_chunk": args.fused_chunk > 1,
            "rollbacks": args.max_rollbacks is not None,
            "hier": cfg.n_pods > 1,
            "mesh": args.mesh != "off",
            # resolved AFTER overrides so a preset with
            # correction="vtrace" is gated the same as the flag
            "vtrace": cfg.algo == "ppo" and cfg.ppo.correction == "vtrace",
            "sync": not args.async_run,
            # NOT the "vtrace" flag: continual FORCES the correction
            # internally against measured serving lag, which is exactly
            # the case the vtrace x sync refusal (ratios == 1 on-policy)
            # does not cover
            "continual": args.continual is not None,
        })
    except ModeCombinationError as e:
        sys.exit(str(e))
    if args.continual is not None and cfg.algo != "ppo":
        sys.exit("--continual retrains through the V-trace-corrected "
                 "PPO pipeline; the A2C update has no importance-"
                 "corrected variant")
    if args.source_jobs is not None:
        if args.source_jobs <= 0:
            sys.exit("--source-jobs must be positive")
        if cfg.trace in ("philly", "pai"):
            sys.exit("--source-jobs sizes GENERATED traces; a CSV trace "
                     "is its file's own size (refusing the silent no-op)")

    import contextlib

    from .utils import MetricsLogger, profiling
    from .utils.platform import enable_compile_cache

    enable_compile_cache()

    with contextlib.ExitStack() as stack:
        # telemetry first: its event bus threads through the checkpoint
        # store, watchdog and injector below (and the ExitStack closes
        # it LAST, so their teardown events still have a live bus)
        telemetry = None
        bus = None
        if args.obs_dir:
            import os

            from .obs import RunTelemetry
            telemetry = stack.enter_context(RunTelemetry(
                os.path.abspath(args.obs_dir), rank=0,
                alarms=args.alarms, slow_iter_s=args.alarm_slow_iter,
                trace=args.trace_spans))
            bus = telemetry.bus
        ckpt = None
        if args.ckpt_dir:
            from .checkpoint import Checkpointer
            import os
            ckpt = Checkpointer(os.path.abspath(args.ckpt_dir),
                                max_to_keep=args.ckpt_keep or 3, bus=bus)
        # --resume APPENDS to the existing metrics CSV (header re-read +
        # schema-validated) instead of truncating the history a relaunch
        # is trying to continue
        csv_logger = stack.enter_context(
            MetricsLogger(args.log_csv, echo=args.log_every > 0,
                          append=args.resume))
        logger = csv_logger
        if args.tb_dir:
            from .utils import TensorBoardWriter
            tb = stack.enter_context(TensorBoardWriter(args.tb_dir))

            def logger(i, m, _csv=csv_logger, _tb=tb):
                _csv(i, m)
                _tb(i, m)
        if args.profile_dir:
            stack.enter_context(profiling.trace(args.profile_dir))
        if args.debug_nans:
            stack.enter_context(profiling.debug_checks())
        if ckpt is not None:
            stack.enter_context(ckpt)

        run_mesh = None
        if args.pbt:
            from .experiment import PopulationExperiment
            from .parallel import PBTConfig
            # the async population runner owns placement (member stacks
            # replicated on the actor/learner group meshes), so the
            # unified pop mesh stays a sync-path construct
            run_mesh = None if args.async_run else make_pop_mesh(args.n_pop)
            exp = PopulationExperiment.build(
                cfg, n_pop=args.n_pop, mesh=run_mesh,
                pbt_cfg=PBTConfig(ready_iters=args.pbt_ready,
                                  seed=cfg.seed))
        else:
            from .experiment import Experiment
            run_mesh = make_run_mesh(args.mesh, cfg.n_envs)
            exp = Experiment.build(cfg, mesh=run_mesh)
        if run_mesh is not None:
            from .parallel import rule_table_hash, rules_for
            print(f"mesh: {dict(run_mesh.shape)} rules="
                  f"{rule_table_hash(rules_for(cfg))}", file=sys.stderr)
        if args.resume:
            if ckpt is None:
                sys.exit("--resume requires --ckpt-dir")
            meta = exp.restore_checkpoint(ckpt)
            # last_restored_step, not latest_step: the integrity fallback
            # may have restored an older retained step than the newest dir
            print(f"resumed from step {ckpt.last_restored_step} ({meta})",
                  file=sys.stderr)

        if args.continual is not None:
            import os

            from .flywheel import FlightLogError, run_continual
            from .obs import Registry
            registry = (telemetry.registry if telemetry is not None
                        else Registry())
            try:
                summary = run_continual(
                    exp, os.path.abspath(args.continual),
                    iterations=(args.iterations
                                if args.iterations is not None else 1),
                    trust=args.continual_trust,
                    rho_max_cap=args.continual_rho_max,
                    registry=registry, ckpt=ckpt)
            except FlightLogError as e:
                sys.exit(f"continual ingest refused: {e}")
            print(f"continual: {summary['shards_accepted']}/"
                  f"{summary['shards_seen']} shards admitted "
                  f"({summary['shards_refused']} refused by the trust "
                  f"region), {summary['rows_trained']} rows as "
                  f"{summary['pseudo_steps']} pseudo-steps x "
                  f"{summary['iterations']} iterations -> step "
                  f"{summary['final_step']}", file=sys.stderr)
            print(json.dumps(summary))
            return summary

        eval_kw = {}
        if args.eval_every:
            probe_exp = FittestMemberView(exp) if args.pbt else exp
            probe = make_eval_probe(cfg, probe_exp, args.eval_windows,
                                    args.eval_seed, regime=args.eval_probe)
            if args.keep_best:
                from .checkpoint import Checkpointer
                import os
                best_ckpt = stack.enter_context(Checkpointer(
                    os.path.join(os.path.abspath(args.ckpt_dir), "best"),
                    max_to_keep=1, bus=bus))
                best = {"jct": float("inf")}
                if best_ckpt.latest_step() is not None:
                    # a resumed run must not rotate out a prior run's
                    # genuinely-best checkpoint with its own first probe:
                    # recover the bar from the saved meta
                    best["jct"] = float(best_ckpt.read_meta().get(
                        "eval_avg_jct", float("inf")))
                    print(f"keep-best: prior best eval_avg_jct="
                          f"{best['jct']:.1f}", file=sys.stderr)

                def probe(i, _inner=probe):
                    m = dict(_inner(i))
                    improved = (m["eval_completion"] >= 1.0 and
                                m["eval_avg_jct"] < best["jct"])
                    if improved:
                        # force: a resumed run can revisit a step number
                        # the best dir already holds; a silently-skipped
                        # save would leave stale params labeled with the
                        # new probe result
                        exp.save_checkpoint(
                            best_ckpt,
                            meta={"iteration": i,
                                  "eval_avg_jct": m["eval_avg_jct"]},
                            force=True)
                        best["jct"] = m["eval_avg_jct"]
                    m["eval_is_best"] = float(improved)
                    return m
            eval_kw = dict(
                eval_every=args.eval_every, eval_fn=probe,
                eval_logger=stack.enter_context(
                    MetricsLogger(args.log_csv + ".eval.csv"
                                  if args.log_csv else None, echo=True,
                                  append=args.resume)))

        run_kw = {}
        if args.fused_chunk > 1:
            run_kw["fused_chunk"] = args.fused_chunk
        if args.max_rollbacks is not None:
            from .resilience import DivergenceWatchdog
            run_kw["watchdog"] = DivergenceWatchdog(
                max_rollbacks=args.max_rollbacks, bus=bus)
        if faults:
            from .resilience import FaultInjector
            run_kw["injector"] = FaultInjector(faults, bus=bus)
        if telemetry is not None:
            run_kw["telemetry"] = telemetry
        from .resilience import DivergenceError
        try:
            if args.async_run:
                from .parallel import split_devices
                groups = split_devices(actor=args.actor_devices,
                                       learner=args.learner_devices)
                print(f"async actor-learner: {groups.describe()} "
                      f"staleness_bound={args.staleness_bound} "
                      f"queue_capacity={args.queue_capacity}",
                      file=sys.stderr)
                out = exp.run_async(
                    groups=groups, staleness_bound=args.staleness_bound,
                    queue_capacity=args.queue_capacity,
                    log_every=args.log_every, logger=logger,
                    ckpt=ckpt, ckpt_every=args.ckpt_every, **eval_kw,
                    **run_kw)
            else:
                out = exp.run(log_every=args.log_every, logger=logger,
                              ckpt=ckpt, ckpt_every=args.ckpt_every,
                              **eval_kw, **run_kw)
        except DivergenceError as e:
            # the watchdog's clean give-up: budget exhausted, state rolled
            # back — a non-zero exit with the reason, not a traceback
            sys.exit(f"divergence watchdog gave up: {e}")

        summary = {k: v for k, v in out.items() if k != "history"}
        if run_mesh is not None:
            from .parallel import rule_table_hash, rules_for
            summary["mesh"] = {
                "shape": {k: int(v) for k, v in run_mesh.shape.items()},
                "rule_table_hash": rule_table_hash(rules_for(cfg))}
        if args.report and not args.pbt and cfg.n_pods == 1:
            from .eval import format_report, jct_report
            report = jct_report(exp)
            print(format_report(report), file=sys.stderr)
            summary["jct_report"] = {k: v for k, v in report.items()
                                     if isinstance(v, (int, float))}
        print(json.dumps(summary))
        return summary


if __name__ == "__main__":
    main()
