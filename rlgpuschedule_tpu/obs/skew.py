"""Clock-skew handshake: one corrected time axis for cross-host merges.

``merge_dir`` orders events by ``mono`` (CLOCK_MONOTONIC), which is
correct on ONE host — every process shares the boot-relative clock —
but each host's monotonic epoch is its own boot time, so a true
multi-host merge interleaves incomparable axes (a named ROADMAP
residual: "wall-clock-skew annotation for cross-host timeline merges").

The handshake: every event the bus stamps already carries BOTH clocks
``(wall, mono)`` read back-to-back — i.e. every event is an offset
sample of ``wall - mono`` for its rank. Ranks additionally stamp
explicit ``clock_skew`` events (:func:`stamp`) at worker start and each
heartbeat, so the offset is sampled across the run's whole lifetime
even on ranks that emit little else. :func:`learn_offsets` takes the
median ``wall - mono`` per rank (the median rejects NTP steps and
scheduling outliers); :func:`correct_events` rewrites each event's
``mono`` onto the reference rank's axis by the learned offset *delta*,
re-sorts, and annotates the shift and the residual uncertainty (the
per-rank sample spread — wall-clock sync error between hosts cannot be
observed from inside, so the spread is the honest error bar).

On a single host the learned offsets agree to microseconds, so the
correction degrades to a no-op — the dryrun topology is untouched.
"""
from __future__ import annotations

import dataclasses
import statistics
from typing import Any, Iterable

from .events import EventBus, merge_dir, merge_events

# the dedicated offset-sample event kind (worker start + heartbeats)
CLOCK_SKEW = "clock_skew"


def stamp(bus: EventBus, source: str = "heartbeat",
          **fields: Any) -> dict:
    """Emit one explicit offset sample: the bus's own ``(wall, mono)``
    stamp pair IS the measurement (read back-to-back in ``emit``), so
    the event needs no payload beyond provenance."""
    return bus.emit(CLOCK_SKEW, source=source, **fields)


@dataclasses.dataclass
class RankSkew:
    """One rank's learned clock offset: ``offset_s`` is the median
    ``wall - mono``; ``residual_s`` the sample spread (max - min) —
    the uncertainty left after correction."""

    rank: int
    offset_s: float
    residual_s: float
    n_samples: int
    dedicated: bool     # from clock_skew events (vs all-event fallback)


def learn_offsets(events: Iterable[dict]) -> dict[int, RankSkew]:
    """Per-rank offset estimates. Dedicated ``clock_skew`` samples are
    preferred; a rank that never stamped one falls back to the implicit
    samples every bus event carries."""
    dedicated: dict[int, list[float]] = {}
    implicit: dict[int, list[float]] = {}
    for e in events:
        if "mono" not in e or "wall" not in e:
            continue
        rank = int(e.get("rank", 0))
        sample = float(e["wall"]) - float(e["mono"])
        implicit.setdefault(rank, []).append(sample)
        if e.get("kind") == CLOCK_SKEW:
            dedicated.setdefault(rank, []).append(sample)
    out: dict[int, RankSkew] = {}
    for rank, fallback in implicit.items():
        samples = dedicated.get(rank, fallback)
        out[rank] = RankSkew(
            rank=rank,
            offset_s=statistics.median(samples),
            residual_s=(max(samples) - min(samples)),
            n_samples=len(samples),
            dedicated=rank in dedicated)
    return out


def correct_events(events: list[dict],
                   skews: dict[int, RankSkew] | None = None,
                   reference_rank: int | None = None,
                   ) -> tuple[list[dict], dict]:
    """Rewrite a merged timeline onto one corrected ``mono`` axis.

    Each rank's events shift by ``offset_rank - offset_reference`` (the
    reference defaults to the lowest non-negative rank, so rank 0's
    axis is the run's axis). Shifted events keep the raw stamp as
    ``mono_raw`` and carry ``skew_shift_s``. Returns the re-sorted
    timeline plus an info dict (``applied``, per-rank offsets/shifts/
    residuals, ``max_residual_s``). With fewer than two ranks sampled
    the correction is an honest no-op (``applied: False``) — there is
    nothing to align."""
    if skews is None:
        skews = learn_offsets(events)
    info: dict = {"applied": False, "reference_rank": None, "ranks": {}}
    if len(skews) < 2:
        return list(events), info
    if reference_rank is None:
        nonneg = [r for r in skews if r >= 0]
        reference_rank = min(nonneg) if nonneg else min(skews)
    elif reference_rank not in skews:
        raise ValueError(f"reference rank {reference_rank} has no "
                         f"offset samples (ranks: {sorted(skews)})")
    ref = skews[reference_rank].offset_s
    out = []
    for e in events:
        rank = int(e.get("rank", 0))
        sk = skews.get(rank)
        shift = (sk.offset_s - ref) if sk is not None else 0.0
        if "mono" in e and shift != 0.0:
            e = dict(e, mono=e["mono"] + shift, mono_raw=e["mono"],
                     skew_shift_s=round(shift, 9))
        out.append(e)
    info = {
        "applied": True,
        "reference_rank": reference_rank,
        "max_residual_s": round(max(s.residual_s
                                    for s in skews.values()), 9),
        "ranks": {str(r): {"offset_s": round(s.offset_s, 9),
                           "shift_s": round(s.offset_s - ref, 9),
                           "residual_s": round(s.residual_s, 9),
                           "n_samples": s.n_samples,
                           "dedicated": s.dedicated}
                  for r, s in sorted(skews.items())},
    }
    return merge_events(out), info


def merge_dir_corrected(directory: str) -> tuple[list[dict], dict]:
    """:func:`.events.merge_dir`, then learn per-rank offsets and
    rewrite the merged timeline onto the corrected axis."""
    return correct_events(merge_dir(directory))
