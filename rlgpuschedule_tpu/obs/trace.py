"""Span-tracing flight recorder on the event bus (Perfetto-ready).

The event bus records *points* ("this happened at t"); this module
records *extents with causality*: a :class:`Tracer` opens nestable,
thread-aware spans (``with tracer.span("actor", iteration=i):``) that
land on the SAME JSONL stream as every other event — paired
``span_begin`` / ``span_end`` records whose track is ``(rank, thread)``.
Because spans ride the bus, they merge, skew-correct
(:mod:`.skew`) and post-mortem (:mod:`.report`) exactly like any other
event, and one exporter (:func:`to_chrome_trace`) turns any run into a
Chrome-trace JSON that Perfetto / ``chrome://tracing`` opens directly.

Design constraints, in order:

- **Zero host syncs.** Span emission touches host clocks and a file
  only — never a device value. The device_get-counting test in
  tests/test_obs.py runs with tracing ON and still counts exactly one
  batched ``device_get`` per *logged* iteration.
- **Near-zero overhead when disabled.** ``span()`` on a disabled tracer
  returns one shared reusable no-op context — no generator, no
  allocation, no lock. Run loops thread a :data:`NULL_TRACER` when no
  telemetry is attached, so the hot path never branches on ``None``.
- **Thread-aware.** The async engine's actor thread and the learner
  (caller) thread emit on one rank's bus concurrently; the bus write is
  serialized by :class:`.events.EventBus`'s emit lock, and each thread
  gets a stable small ``tid`` so stack discipline (B/E pairing) holds
  *per track*, which is exactly the Chrome trace format's contract.

A crash mid-span leaves a ``span_begin`` with no ``span_end`` (a *torn*
span): :func:`build_span_tree` renders it as an open span (counted,
flagged) instead of corrupting the tree, and :func:`to_chrome_trace`
closes it at the track's last timestamp with ``"torn": true``.
"""
from __future__ import annotations

import threading
from typing import Any, Iterable

from .events import RESERVED_FIELDS, EventBus, merge_events

# the bus kinds the tracer owns
SPAN_BEGIN = "span_begin"
SPAN_END = "span_end"
SPAN_POINT = "span_point"
SPAN_KINDS = (SPAN_BEGIN, SPAN_END, SPAN_POINT)


class _Span:
    """One live span: begin on enter, end on exit. Exceptions propagate
    (the end event still lands — a failed span is still an extent)."""

    __slots__ = ("_tracer", "_name", "_attrs")

    def __init__(self, tracer: "Tracer", name: str, attrs: dict):
        self._tracer = tracer
        self._name = name
        self._attrs = attrs

    def __enter__(self) -> "_Span":
        self._tracer._begin(self._name, self._attrs)
        return self

    def __exit__(self, *exc) -> None:
        self._tracer._end(self._name)


class _NullSpan:
    """Shared reusable no-op context for the disabled path."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


_NULL_SPAN = _NullSpan()


class Tracer:
    """Thread-aware span emitter over one rank's :class:`EventBus`.

    >>> tracer = Tracer(bus, enabled=True)
    >>> with tracer.span("iteration", iteration=3):
    ...     with tracer.span("step"):
    ...         ...

    ``tid`` is a small per-process thread index (0 = first emitting
    thread), stamped on every span event so the merged timeline keeps
    one B/E stack per ``(rank, tid)`` track; the thread's *name* rides
    the begin event for Perfetto track labels. Attrs must be
    JSON-serializable and are carried under one ``attrs`` key so they
    can never shadow the bus's stamp fields.
    """

    def __init__(self, bus: EventBus | None, enabled: bool = True):
        self.bus = bus
        self.enabled = bool(enabled) and bus is not None
        self._lock = threading.Lock()          # protects _tids only
        self._tids: dict[int, int] = {}
        self._local = threading.local()

    def _track(self) -> int:
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            with self._lock:
                tid = self._tids.setdefault(ident, len(self._tids))
        return tid

    def _depth(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def span(self, name: str, **attrs: Any) -> Any:
        """Context manager for one span; no-op (one shared object, no
        allocation) when the tracer is disabled."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, attrs)

    def instant(self, name: str, **attrs: Any) -> None:
        """A zero-duration mark on this thread's track (Chrome ``i``
        event) — e.g. a serve request's enqueue point."""
        if not self.enabled:
            return
        assert self.bus is not None
        self.bus.emit(SPAN_POINT, span=name, tid=self._track(),
                      **({"attrs": attrs} if attrs else {}))

    def _begin(self, name: str, attrs: dict) -> None:
        assert self.bus is not None
        stack = self._depth()
        self.bus.emit(SPAN_BEGIN, span=name, tid=self._track(),
                      depth=len(stack),
                      thread=threading.current_thread().name,
                      **({"attrs": attrs} if attrs else {}))
        stack.append(name)

    def _end(self, name: str) -> None:
        assert self.bus is not None
        stack = self._depth()
        if stack and stack[-1] == name:
            stack.pop()
        self.bus.emit(SPAN_END, span=name, tid=self._track(),
                      depth=len(stack))

    def lane(self, label: str) -> "TracerLane":
        """A named VIRTUAL track on this tracer — a dedicated ``tid``
        that is not any OS thread's, labeled ``label`` in Perfetto.

        The router gives every inference engine its own lane (PR 13):
        engine spans (``pad``/``dispatch``) land on per-engine tracks,
        so a routed timeline shows which chip served which batch even
        though the dispatching happens from whichever pump thread won
        the request — exactly the track-per-resource (not
        track-per-thread) layout GPU rows use in Chrome traces. Each
        call returns a NEW lane (one per engine, allocated at router
        construction, never per dispatch — tids must stay stable).
        Disabled tracers return the shared no-op lane."""
        if not self.enabled:
            return NULL_LANE
        with self._lock:
            # virtual lanes share the tid space with real threads; the
            # key can never collide with threading.get_ident() values
            tid = len(self._tids)
            self._tids[("lane", label, tid)] = tid
        return TracerLane(self, label, tid)


class TracerLane:
    """One virtual track of a :class:`Tracer` (see :meth:`Tracer.lane`).

    Mirrors the ``span``/``instant`` API; B/E pairing discipline holds
    per lane via the lane's own depth stack (lock-guarded — concurrent
    pump threads may dispatch on one engine's lane under queue
    pressure)."""

    def __init__(self, tracer: Tracer, label: str, tid: int):
        self._tracer = tracer
        self.label = label
        self.tid = tid
        self._stack: list[str] = []
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self._tracer.enabled

    def span(self, name: str, **attrs: Any) -> Any:
        if not self._tracer.enabled:
            return _NULL_SPAN
        return _LaneSpan(self, name, attrs)

    def instant(self, name: str, **attrs: Any) -> None:
        if not self._tracer.enabled:
            return
        assert self._tracer.bus is not None
        self._tracer.bus.emit(SPAN_POINT, span=name, tid=self.tid,
                              **({"attrs": attrs} if attrs else {}))

    def _begin(self, name: str, attrs: dict) -> None:
        assert self._tracer.bus is not None
        with self._lock:
            depth = len(self._stack)
            self._stack.append(name)
        self._tracer.bus.emit(SPAN_BEGIN, span=name, tid=self.tid,
                              depth=depth, thread=self.label,
                              **({"attrs": attrs} if attrs else {}))

    def _end(self, name: str) -> None:
        assert self._tracer.bus is not None
        with self._lock:
            if self._stack and self._stack[-1] == name:
                self._stack.pop()
            depth = len(self._stack)
        self._tracer.bus.emit(SPAN_END, span=name, tid=self.tid,
                              depth=depth)


class _LaneSpan:
    """One live span on a virtual lane (same contract as :class:`_Span`)."""

    __slots__ = ("_lane", "_name", "_attrs")

    def __init__(self, lane: TracerLane, name: str, attrs: dict):
        self._lane = lane
        self._name = name
        self._attrs = attrs

    def __enter__(self) -> "_LaneSpan":
        self._lane._begin(self._name, self._attrs)
        return self

    def __exit__(self, *exc) -> None:
        self._lane._end(self._name)


class _NullLane:
    """Shared no-op lane for disabled tracers."""

    __slots__ = ()
    enabled = False
    label = ""
    tid = 0

    def span(self, name: str, **attrs: Any) -> Any:
        return _NULL_SPAN

    def instant(self, name: str, **attrs: Any) -> None:
        pass


NULL_LANE = _NullLane()


# the always-available disabled tracer: run loops hold it when no
# telemetry (or no --trace) is attached, so call sites never branch
NULL_TRACER = Tracer(None, enabled=False)


def tracer_of(telemetry: Any) -> Tracer:
    """The run loops' one accessor: ``telemetry.tracer`` when present,
    :data:`NULL_TRACER` otherwise (bare runs, legacy fakes)."""
    t = getattr(telemetry, "tracer", None)
    return t if isinstance(t, Tracer) else NULL_TRACER


# -- post-processing: span tree --------------------------------------------

def build_span_tree(events: Iterable[dict]) -> list[dict]:
    """Aggregate span events into a preorder tree of phase rows.

    Each row: ``{"path": "iteration/step", "name", "depth", "count",
    "total_s", "self_s", "open"}`` — ``self_s`` is total minus child
    time, ``open`` counts torn spans (begin, no end), which are closed
    at their track's last seen timestamp instead of corrupting the
    tree. Pairing is per ``(rank, tid)`` track, so concurrent threads
    cannot steal each other's ends.
    """
    nodes: dict[tuple, dict] = {}
    stacks: dict[tuple, list] = {}     # track -> [(path, t_begin), ...]
    last_ts: dict[tuple, float] = {}

    def node(path: tuple) -> dict:
        n = nodes.get(path)
        if n is None:
            n = nodes[path] = {"path": "/".join(path), "name": path[-1],
                               "depth": len(path) - 1, "count": 0,
                               "total_s": 0.0, "child_s": 0.0, "open": 0}
        return n

    def close(track: tuple, path: tuple, t0: float, t1: float,
              torn: bool) -> None:
        n = node(path)
        n["count"] += 1
        n["total_s"] += max(t1 - t0, 0.0)
        if torn:
            n["open"] += 1
        if len(path) > 1:
            node(path[:-1])["child_s"] += max(t1 - t0, 0.0)

    for e in merge_events(events):
        kind = e.get("kind")
        if kind not in (SPAN_BEGIN, SPAN_END) or "mono" not in e:
            continue
        track = (e.get("rank", 0), e.get("tid", 0))
        ts = e["mono"]
        last_ts[track] = ts
        stack = stacks.setdefault(track, [])
        if kind == SPAN_BEGIN:
            parent = stack[-1][0] if stack else ()
            stack.append((parent + (str(e.get("span")),), ts))
        else:
            # pop to the matching name: a torn INNER span is closed at
            # the outer end's timestamp rather than poisoning the stack;
            # an end whose begin was lost entirely is ignored
            name = str(e.get("span"))
            if not any(path[-1] == name for path, _ in stack):
                continue
            while stack:
                path, t0 = stack.pop()
                if path[-1] == name:
                    close(track, path, t0, ts, torn=False)
                    break
                close(track, path, t0, ts, torn=True)
    for track, stack in stacks.items():
        t1 = last_ts.get(track, 0.0)
        while stack:                       # crash mid-span: open spans
            path, t0 = stack.pop()
            close(track, path, t0, t1, torn=True)

    out = [nodes[p] for p in sorted(nodes)]
    for n in out:
        n["total_s"] = round(n["total_s"], 6)
        n["self_s"] = round(n["total_s"] - n.pop("child_s"), 6)
    return out


# -- post-processing: measured async overlap -------------------------------

def _lane_intervals(events: Iterable[dict],
                    lanes: tuple[str, ...]) -> dict[str, list]:
    opened: dict[tuple, float] = {}
    iv: dict[str, list] = {lane: [] for lane in lanes}
    for e in merge_events(events):
        name = e.get("span")
        if e.get("kind") not in (SPAN_BEGIN, SPAN_END) or name not in iv:
            continue
        key = (e.get("rank", 0), e.get("tid", 0), name)
        if e["kind"] == SPAN_BEGIN:
            opened[key] = e.get("mono", 0.0)
        elif key in opened:
            iv[name].append((opened.pop(key), e.get("mono", 0.0)))
    return iv


def _union(intervals: list) -> list:
    merged: list = []
    for lo, hi in sorted(intervals):
        if merged and lo <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], hi))
        else:
            merged.append((lo, hi))
    return merged


def _total(intervals: list) -> float:
    return sum(hi - lo for lo, hi in intervals)


def async_overlap_summary(events: Iterable[dict]) -> dict | None:
    """Measured actor/learner occupancy from the span timeline — the
    number PR 8 could only project from phase sums. Over the window
    spanned by actor∪learner spans: ``busy`` is the union of the two
    lanes' spans, ``idle = window - busy``, ``concurrent`` the lanes'
    intersection, and ``async_overlap_measured = 1 - idle/window`` (the
    occupancy of the actor∪learner timeline). None when either lane
    recorded no spans (not an async traced run)."""
    iv = _lane_intervals(events, ("actor", "learner"))
    if not iv["actor"] or not iv["learner"]:
        return None
    actor, learner = _union(iv["actor"]), _union(iv["learner"])
    both = _union(actor + learner)
    window = (max(hi for _, hi in both) - min(lo for lo, _ in both))
    busy = _total(both)
    concurrent = _total(actor) + _total(learner) - busy
    idle = max(window - busy, 0.0)
    return {
        "async_overlap_measured": round(1.0 - idle / window, 6)
        if window > 0 else 1.0,
        "window_s": round(window, 6),
        "actor_busy_s": round(_total(actor), 6),
        "learner_busy_s": round(_total(learner), 6),
        "concurrent_s": round(concurrent, 6),
        "idle_s": round(idle, 6),
    }


# -- Chrome trace export ---------------------------------------------------

def to_chrome_trace(events: Iterable[dict]) -> dict:
    """Chrome Trace Event Format JSON (the Perfetto/chrome://tracing
    lingua franca): spans become paired ``B``/``E`` duration events on
    ``pid=rank, tid=thread`` tracks, ``span_point`` marks and every
    non-span bus event become ``i`` instants, and metadata events name
    each rank/thread. Timestamps are the (possibly skew-corrected)
    ``mono`` clock in microseconds. Torn spans are closed at their
    track's last timestamp with ``args.torn = true``."""
    trace: list[dict] = []
    stacks: dict[tuple, list] = {}
    last_ts: dict[tuple, float] = {}
    named_procs: set = set()
    named_threads: set = set()
    for e in merge_events(events):
        if "mono" not in e:
            continue
        kind = e.get("kind")
        pid = e.get("rank", 0)
        ts = e["mono"] * 1e6
        if pid not in named_procs:
            named_procs.add(pid)
            trace.append({"ph": "M", "name": "process_name", "pid": pid,
                          "tid": 0, "args": {"name": f"rank {pid}"}})
        if kind in (SPAN_BEGIN, SPAN_END, SPAN_POINT):
            tid = e.get("tid", 0)
            track = (pid, tid)
            last_ts[track] = ts
            if e.get("thread") and track not in named_threads:
                named_threads.add(track)
                trace.append({"ph": "M", "name": "thread_name",
                              "pid": pid, "tid": tid,
                              "args": {"name": e["thread"]}})
            name = str(e.get("span"))
            if kind == SPAN_BEGIN:
                stacks.setdefault(track, []).append(name)
                trace.append({"ph": "B", "name": name, "cat": "span",
                              "pid": pid, "tid": tid, "ts": ts,
                              "args": e.get("attrs") or {}})
            elif kind == SPAN_END:
                stack = stacks.get(track) or []
                if not stack:
                    continue           # torn end (begin lost): drop
                stack.pop()
                trace.append({"ph": "E", "name": name, "cat": "span",
                              "pid": pid, "tid": tid, "ts": ts})
            else:
                trace.append({"ph": "i", "name": name, "cat": "span",
                              "pid": pid, "tid": tid, "ts": ts, "s": "t",
                              "args": e.get("attrs") or {}})
        else:
            args = {k: v for k, v in e.items()
                    if k not in RESERVED_FIELDS}
            trace.append({"ph": "i", "name": str(kind), "cat": "event",
                          "pid": pid, "tid": 0, "ts": ts, "s": "p",
                          "args": args})
    for (pid, tid), stack in stacks.items():
        ts = last_ts.get((pid, tid), 0.0)
        while stack:                   # close torn spans at track end
            trace.append({"ph": "E", "name": stack.pop(), "cat": "span",
                          "pid": pid, "tid": tid, "ts": ts,
                          "args": {"torn": True}})
    return {"traceEvents": trace, "displayTimeUnit": "ms"}
