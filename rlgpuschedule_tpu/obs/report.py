"""Multihost merge + run post-mortem CLI.

``python -m rlgpuschedule_tpu.obs.report <obs-dir>`` merges every
per-rank event stream under ``<obs-dir>`` into one monotonic-ordered
timeline and prints the run's post-mortem:

- header: schema versions, emitting ranks, event count, time span;
- phase-time table (host wall seconds per run-loop phase, from the
  ``iteration`` spans);
- span tree (``--trace`` runs): per-phase self/child time from the
  flight recorder's nested ``span_begin``/``span_end`` extents, torn
  (crash-open) spans flagged; plus the MEASURED async actor/learner
  occupancy (``async_overlap_measured``) replacing PR 8's phase-sum
  projection;
- clock-skew annotation: with ≥2 sampled ranks the merged timeline is
  rewritten onto rank 0's corrected monotonic axis (``obs.skew``) and
  the per-rank offsets/residuals are reported;
- restart / rollback history: supervisor launch→failure→relaunch
  decisions, watchdog rollbacks, checkpoint save/restore/reject events
  and fault injections, in timeline order;
- steps/s curve (one row per logged iteration);
- chaos story (``env_fault`` events from ``evaluate --chaos``): the
  regime × scheduler degradation cells, in one table;
- alarm summary (``recompile`` / ``transfer`` / ``slow_iteration``).

Exit codes: 0 ok, 1 no events under the directory (an empty post-mortem
must fail loudly), 2 usage. ``--strict-alarms`` additionally exits 1
when any post-warmup alarm event fired — the CI hook: a geometry-stable
smoke run must produce a merged timeline with ZERO ``recompile`` events
(ci.sh smoke stage).
"""
from __future__ import annotations

import argparse
import json
import sys

from .events import merge_dir
from .skew import correct_events
from .trace import (SPAN_KINDS, async_overlap_summary, build_span_tree,
                    to_chrome_trace)

# event kinds that are production alarms (Alarms emissions; ``compile``
# is the blessed warmup/amnesty record, not an alarm)
ALARM_KINDS = ("recompile", "transfer", "slow_iteration")

# the restart/rollback/fault story, in one timeline
_HISTORY_KINDS = (
    "gang_launch", "rank_failure", "gang_restart", "gang_shrink",
    "supervisor_done", "rollback", "fault", "ckpt_reject",
    "ckpt_crc_reject", "ckpt_elastic_restore", "worker_resumed",
)


def build_report(events: list[dict]) -> dict:
    """Aggregate a merged timeline into the post-mortem's sections."""
    ranks = sorted({e.get("rank", 0) for e in events})
    versions = sorted({e.get("v", 0) for e in events})
    monos = [e["mono"] for e in events if "mono" in e]
    span_s = (max(monos) - min(monos)) if monos else 0.0
    t0 = min(monos) if monos else 0.0

    phases: dict[str, float] = {}
    curve = []
    for e in events:
        if e.get("kind") != "iteration":
            continue
        for phase, secs in (e.get("phases") or {}).items():
            phases[phase] = phases.get(phase, 0.0) + secs
        curve.append({"iteration": e.get("iteration"),
                      "rank": e.get("rank", 0),
                      "steps_per_sec": e.get("steps_per_sec"),
                      "wall_s": e.get("wall_s")})

    history = [e for e in events if e.get("kind") in _HISTORY_KINDS]
    restores = [e for e in events if e.get("kind") == "ckpt_restore"]
    chaos = [{"regime": e.get("regime"), "scheduler": e.get("scheduler"),
              "avg_jct": e.get("avg_jct"),
              "completion": e.get("completion"),
              "degradation": e.get("degradation"),
              "n_drains": e.get("fault_n_drains"),
              "chaos_seed": e.get("chaos_seed")}
             for e in events if e.get("kind") == "env_fault"]
    alarms = {k: sum(1 for e in events if e.get("kind") == k)
              for k in ALARM_KINDS}
    counts: dict[str, int] = {}
    for e in events:
        k = str(e.get("kind"))
        counts[k] = counts.get(k, 0) + 1
    has_spans = any(e.get("kind") in SPAN_KINDS for e in events)
    span_tree = build_span_tree(events) if has_spans else []
    return {"schema_versions": versions, "ranks": ranks,
            "n_events": len(events), "span_s": span_s, "t0_mono": t0,
            "phase_seconds": phases, "steps_curve": curve,
            "history": history, "ckpt_restores": restores,
            "chaos": chaos, "alarms": alarms, "kind_counts": counts,
            "span_tree": span_tree,
            "torn_spans": sum(n["open"] for n in span_tree),
            "async_overlap": (async_overlap_summary(events)
                              if has_spans else None)}


def _fmt_history_line(e: dict, t0: float) -> str:
    t = e.get("mono", t0) - t0
    rank = e.get("rank", "?")
    detail = {k: v for k, v in e.items()
              if k not in ("v", "kind", "rank", "pid", "seq", "mono",
                           "wall")}
    body = " ".join(f"{k}={v}" for k, v in sorted(detail.items())
                    if v is not None)
    return f"  +{t:9.3f}s  rank {rank:>3}  {e.get('kind'):<22s} {body}"


def format_report(rep: dict) -> str:
    """The human post-mortem. Sections keyed to build_report's dict."""
    lines = [
        f"run post-mortem: {rep['n_events']} events from "
        f"{len(rep['ranks'])} emitter(s) (ranks {rep['ranks']}), "
        f"schema v{rep['schema_versions']}, span {rep['span_s']:.3f}s",
        "",
    ]
    if rep["phase_seconds"]:
        total = sum(rep["phase_seconds"].values()) or 1.0
        lines.append("phase-time table (host wall, from iteration spans):")
        lines.append(f"  {'phase':<12s} {'seconds':>10s} {'share':>7s}")
        for phase, secs in sorted(rep["phase_seconds"].items(),
                                  key=lambda kv: -kv[1]):
            lines.append(f"  {phase:<12s} {secs:>10.3f} "
                         f"{100.0 * secs / total:>6.1f}%")
        lines.append("")
    if rep.get("span_tree"):
        lines.append("span tree (flight recorder, self/child time):")
        lines.append(f"  {'span':<28s} {'count':>6s} {'total s':>10s} "
                     f"{'self s':>10s}")
        for n in rep["span_tree"]:
            label = "  " * n["depth"] + n["name"] + \
                (f"  [open x{n['open']}]" if n["open"] else "")
            lines.append(f"  {label:<28s} {n['count']:>6d} "
                         f"{n['total_s']:>10.3f} {n['self_s']:>10.3f}")
        if rep.get("torn_spans"):
            lines.append(f"  ({rep['torn_spans']} torn span(s): begin "
                         f"with no end — writer died mid-span)")
        lines.append("")
    if rep.get("async_overlap"):
        ov = rep["async_overlap"]
        lines.append(
            f"async occupancy (measured from actor/learner spans): "
            f"async_overlap_measured={ov['async_overlap_measured']:.3f} "
            f"(window {ov['window_s']:.3f}s, actor busy "
            f"{ov['actor_busy_s']:.3f}s, learner busy "
            f"{ov['learner_busy_s']:.3f}s, concurrent "
            f"{ov['concurrent_s']:.3f}s, idle {ov['idle_s']:.3f}s)")
        lines.append("")
    if rep.get("skew", {}).get("applied"):
        sk = rep["skew"]
        ranks = ", ".join(
            f"rank {r}: shift {v['shift_s']*1e3:+.3f}ms "
            f"(±{v['residual_s']*1e3:.3f}ms, n={v['n_samples']})"
            for r, v in sk["ranks"].items())
        lines.append(
            f"clock skew: timeline rewritten onto rank "
            f"{sk['reference_rank']}'s monotonic axis — {ranks}; "
            f"max residual {sk['max_residual_s']*1e3:.3f}ms")
        lines.append("")
    if rep["history"]:
        lines.append("restart / rollback / fault history:")
        for e in rep["history"]:
            lines.append(_fmt_history_line(e, rep["t0_mono"]))
        lines.append("")
    if rep["steps_curve"]:
        lines.append("steps/s curve (logged iterations):")
        lines.append(f"  {'iter':>6s} {'rank':>4s} {'steps/s':>12s} "
                     f"{'iter wall s':>12s}")
        for row in rep["steps_curve"]:
            sps = row.get("steps_per_sec")
            wall = row.get("wall_s")
            lines.append(
                f"  {row.get('iteration', '?'):>6} "
                f"{row.get('rank', 0):>4} "
                f"{(f'{sps:.1f}' if sps is not None else '?'):>12s} "
                f"{(f'{wall:.4f}' if wall is not None else '?'):>12s}")
        lines.append("")
    if rep.get("chaos"):
        lines.append("chaos story (env_fault events, evaluate --chaos):")
        lines.append(f"  {'regime':<12s} {'scheduler':<10s} "
                     f"{'avg JCT s':>10s} {'done':>6s} {'vs clean':>9s} "
                     f"{'drains':>7s}")
        for c in rep["chaos"]:
            deg = c.get("degradation")
            done = c.get("completion")
            jct = c.get("avg_jct")
            lines.append(
                f"  {str(c.get('regime')):<12s} "
                f"{str(c.get('scheduler')):<10s} "
                f"{(f'{jct:.1f}' if jct is not None else '?'):>10s} "
                f"{(f'{done:.0%}' if done is not None else '?'):>6s} "
                f"{(f'x{deg:.2f}' if deg is not None else '—'):>9s} "
                f"{str(c.get('n_drains', '?')):>7s}")
        lines.append("")
    alarm_total = sum(rep["alarms"].values())
    lines.append(
        "alarms: " + ", ".join(f"{k}={n}"
                               for k, n in sorted(rep["alarms"].items()))
        + ("" if alarm_total else "  (clean)"))
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="rlgpuschedule_tpu.obs.report",
        description="Merge per-rank event streams into one timeline and "
                    "print a run post-mortem.")
    p.add_argument("obs_dir", help="directory holding events.*.jsonl "
                                   "streams (--obs-dir of the run)")
    p.add_argument("--json", action="store_true",
                   help="print the structured report as JSON instead of "
                        "the human tables")
    p.add_argument("--out", default=None,
                   help="also write the merged ordered timeline to this "
                        "JSONL file")
    p.add_argument("--trace-out", default=None,
                   help="write the timeline as Chrome-trace JSON "
                        "(open in Perfetto / chrome://tracing)")
    p.add_argument("--no-skew-correct", action="store_true",
                   help="keep each rank's raw monotonic axis instead of "
                        "rewriting onto the learned corrected axis")
    p.add_argument("--strict-alarms", action="store_true",
                   help="exit 1 if any post-warmup alarm event "
                        f"({'/'.join(ALARM_KINDS)}) fired")
    args = p.parse_args(argv)
    try:
        events = merge_dir(args.obs_dir)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 1
    if not events:
        print(f"event streams under {args.obs_dir} hold no decodable "
              f"events", file=sys.stderr)
        return 1
    skew_info: dict = {"applied": False}
    if not args.no_skew_correct:
        events, skew_info = correct_events(events)
    if args.out:
        with open(args.out, "w") as f:
            for e in events:
                f.write(json.dumps(e, sort_keys=True) + "\n")
    if args.trace_out:
        with open(args.trace_out, "w") as f:
            json.dump(to_chrome_trace(events), f)
    rep = build_report(events)
    rep["skew"] = skew_info
    if args.json:
        print(json.dumps(rep, sort_keys=True))
    else:
        print(format_report(rep))
    if args.strict_alarms and sum(rep["alarms"].values()) > 0:
        print(f"strict-alarms: {rep['alarms']} alarm event(s) in the "
              f"timeline", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
