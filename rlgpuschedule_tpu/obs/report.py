"""Multihost merge + run post-mortem CLI.

``python -m rlgpuschedule_tpu.obs.report <obs-dir>`` merges every
per-rank event stream under ``<obs-dir>`` into one monotonic-ordered
timeline and prints the run's post-mortem:

- header: schema versions, emitting ranks, event count, time span;
- phase-time table (host wall seconds per run-loop phase, from the
  ``iteration`` spans);
- span tree (``--trace`` runs): per-phase self/child time from the
  flight recorder's nested ``span_begin``/``span_end`` extents, torn
  (crash-open) spans flagged; plus the MEASURED async actor/learner
  occupancy (``async_overlap_measured``) replacing PR 8's phase-sum
  projection;
- clock-skew annotation: with ≥2 sampled ranks the merged timeline is
  rewritten onto rank 0's corrected monotonic axis (``obs.skew``) and
  the per-rank offsets/residuals are reported;
- restart / rollback history: supervisor launch→failure→relaunch
  decisions, watchdog rollbacks, checkpoint save/restore/reject events
  and fault injections, in timeline order;
- steps/s curve (one row per logged iteration);
- chaos story (``env_fault`` events from ``evaluate --chaos``): the
  regime × scheduler degradation cells, in one table;
- flywheel & fleet health (ISSUE 20): promotion verdicts
  (``promote_blocked`` / ``promote_apply`` / ``promote_rollback``),
  serving-fleet lifecycle (``serve_fault`` / ``engine_eject`` /
  ``engine_readmit`` / ``serve_retry``) and SLO burn alerts
  (``slo_burn_alert`` / ``slo_burn_clear``), in timeline order;
- alarm summary (``recompile`` / ``transfer`` / ``slow_iteration``).

``--request ID`` switches to the single-request post-mortem: the
request id (as minted by the server or carried on the ``X-Request-Id``
header / v2 frame field) is joined across the serve instants
(``enqueue`` → ``served``/``shed``/``dispatch_failed``, with queue wait
and end-to-end latency from the dispatch record), the flight log
(``--flight-log`` — which sealed shard/row holds the logged decision
and its deadline outcome), and the promotion ledger in the same
directory (which canary verdicts replayed a window covering that row).
Exit 1 when the id appears nowhere.

Exit codes: 0 ok, 1 no events under the directory (an empty post-mortem
must fail loudly), 2 usage. ``--strict-alarms`` additionally exits 1
when any post-warmup alarm event fired — the CI hook: a geometry-stable
smoke run must produce a merged timeline with ZERO ``recompile`` events
(ci.sh smoke stage).
"""
from __future__ import annotations

import argparse
import json
import sys

from .events import merge_dir
from .skew import correct_events
from .trace import (SPAN_KINDS, async_overlap_summary, build_span_tree,
                    to_chrome_trace)

# event kinds that are production alarms (Alarms emissions; ``compile``
# is the blessed warmup/amnesty record, not an alarm)
ALARM_KINDS = ("recompile", "transfer", "slow_iteration")

# the restart/rollback/fault story, in one timeline
_HISTORY_KINDS = (
    "gang_launch", "rank_failure", "gang_restart", "gang_shrink",
    "supervisor_done", "rollback", "fault", "ckpt_reject",
    "ckpt_crc_reject", "ckpt_elastic_restore", "worker_resumed",
)

# the serving-fleet + flywheel story: promotion verdicts, engine
# lifecycle, SLO burn alerts (none are alarm kinds)
_FLEET_KINDS = (
    "promote_blocked", "promote_apply", "promote_rollback",
    "serve_fault", "engine_eject", "engine_readmit", "serve_retry",
    "slo_burn_alert", "slo_burn_clear",
)


def build_report(events: list[dict]) -> dict:
    """Aggregate a merged timeline into the post-mortem's sections."""
    ranks = sorted({e.get("rank", 0) for e in events})
    versions = sorted({e.get("v", 0) for e in events})
    monos = [e["mono"] for e in events if "mono" in e]
    span_s = (max(monos) - min(monos)) if monos else 0.0
    t0 = min(monos) if monos else 0.0

    phases: dict[str, float] = {}
    curve = []
    for e in events:
        if e.get("kind") != "iteration":
            continue
        for phase, secs in (e.get("phases") or {}).items():
            phases[phase] = phases.get(phase, 0.0) + secs
        curve.append({"iteration": e.get("iteration"),
                      "rank": e.get("rank", 0),
                      "steps_per_sec": e.get("steps_per_sec"),
                      "wall_s": e.get("wall_s")})

    history = [e for e in events if e.get("kind") in _HISTORY_KINDS]
    fleet = [e for e in events if e.get("kind") in _FLEET_KINDS]
    restores = [e for e in events if e.get("kind") == "ckpt_restore"]
    chaos = [{"regime": e.get("regime"), "scheduler": e.get("scheduler"),
              "avg_jct": e.get("avg_jct"),
              "completion": e.get("completion"),
              "degradation": e.get("degradation"),
              "n_drains": e.get("fault_n_drains"),
              "chaos_seed": e.get("chaos_seed")}
             for e in events if e.get("kind") == "env_fault"]
    alarms = {k: sum(1 for e in events if e.get("kind") == k)
              for k in ALARM_KINDS}
    counts: dict[str, int] = {}
    for e in events:
        k = str(e.get("kind"))
        counts[k] = counts.get(k, 0) + 1
    has_spans = any(e.get("kind") in SPAN_KINDS for e in events)
    span_tree = build_span_tree(events) if has_spans else []
    return {"schema_versions": versions, "ranks": ranks,
            "n_events": len(events), "span_s": span_s, "t0_mono": t0,
            "phase_seconds": phases, "steps_curve": curve,
            "history": history, "fleet": fleet,
            "ckpt_restores": restores,
            "chaos": chaos, "alarms": alarms, "kind_counts": counts,
            "span_tree": span_tree,
            "torn_spans": sum(n["open"] for n in span_tree),
            "async_overlap": (async_overlap_summary(events)
                              if has_spans else None)}


# flight-log deadline-outcome codes (flywheel.flightlog schema)
_OUTCOME_NAMES = {0: "no-deadline", 1: "met", 2: "served-late"}


def build_request_report(events: list[dict], req_id: int,
                         flight_dir: "str | None" = None) -> dict:
    """Join one request id across the serve instants, the flight log,
    and the promotion ledger — the single-request timeline.

    Stages come from the batching tier's ``span_point`` instants:
    ``enqueue`` (admission), then exactly one of ``served`` (with the
    per-row queue wait and end-to-end latency the dispatch recorded),
    ``shed`` (admission or in-queue expiry), or ``dispatch_failed``.
    With ``flight_dir`` the id is also looked up in the sealed shards'
    ``req_id`` column (which shard/row logged the decision) and — via
    the row's global position — matched against ledger entries whose
    canary window covered it."""
    req_id = int(req_id)
    stages = []

    def stage(name, e, **extra):
        stages.append(dict({"stage": name, "mono": e.get("mono"),
                            "rank": e.get("rank", 0)}, **extra))

    for e in events:
        if e.get("kind") != "span_point":
            continue
        a = e.get("attrs") or {}
        span = e.get("span")
        if span == "enqueue" and a.get("req_id") == req_id:
            stage("enqueue", e, stall=a.get("stall"))
        elif span == "shed" and a.get("req_id") == req_id:
            stage("shed", e, reason=a.get("reason"))
        elif span in ("served", "dispatch_failed"):
            rids = a.get("req_ids") or []
            if req_id not in rids:
                continue
            if span == "served":
                i = rids.index(req_id)
                waits = a.get("wait_ms") or []
                lats = a.get("lat_ms") or []
                stage("served", e, bucket=a.get("bucket"),
                      batch_rows=len(rids),
                      queue_wait_ms=waits[i] if i < len(waits) else None,
                      latency_ms=lats[i] if i < len(lats) else None)
            else:
                stage("dispatch_failed", e, error=a.get("error"))

    flight = None
    verdicts: list[dict] = []
    if flight_dir:
        import numpy as np

        from ..flywheel.canary import read_ledger
        from ..flywheel.flightlog import read_flight_log
        data = read_flight_log(flight_dir)
        preceding = 0
        for s in data.shards:
            if s.req_id is not None:
                for i in np.flatnonzero(s.req_id == req_id):
                    i = int(i)
                    flight = {"shard_seq": s.seq, "path": s.path,
                              "row": i, "global_row": preceding + i,
                              "outcome": int(s.outcome[i]),
                              "outcome_name": _OUTCOME_NAMES.get(
                                  int(s.outcome[i]), "?")}
            preceding += s.rows
        if flight is not None:
            sealed, tail = read_ledger(flight_dir)
            for entry in sealed + tail:
                rows = entry.get("window_rows")
                if rows is not None and int(rows) > flight["global_row"]:
                    verdicts.append(
                        {"action": entry.get("action"),
                         "verdict": entry.get("verdict"),
                         "candidate": entry.get("candidate"),
                         "window_rows": int(rows),
                         "sealed": entry in sealed})
    return {"req_id": req_id, "stages": stages, "flight": flight,
            "verdicts": verdicts,
            "found": bool(stages or flight is not None)}


def format_request_report(rep: dict) -> str:
    """The human single-request timeline."""
    rid = rep["req_id"]
    lines = [f"request 0x{rid:016x} ({rid}):"]
    if not rep["found"]:
        lines.append("  not found: no serve instant, flight-log row, or "
                     "ledger verdict carries this id")
        return "\n".join(lines)
    t0 = min((s["mono"] for s in rep["stages"]
              if s.get("mono") is not None), default=0.0)
    for s in rep["stages"]:
        t = (s["mono"] - t0) if s.get("mono") is not None else 0.0
        detail = " ".join(
            f"{k}={v}" for k, v in sorted(s.items())
            if k not in ("stage", "mono", "rank") and v is not None)
        lines.append(f"  +{t:9.3f}s  rank {s.get('rank', '?'):>3}  "
                     f"{s['stage']:<16s} {detail}")
    if rep["flight"] is not None:
        f = rep["flight"]
        lines.append(
            f"  logged: shard {f['shard_seq']:06d} row {f['row']} "
            f"(global row {f['global_row']}, outcome "
            f"{f['outcome_name']}) — {f['path']}")
    elif not rep["verdicts"]:
        lines.append("  logged: no flight-log row (shed, failed, "
                     "unsealed tail, or no --flight-log given)")
    for v in rep["verdicts"]:
        seal = "sealed" if v["sealed"] else "unsealed tail"
        lines.append(
            f"  replayed: ledger {v['action']} "
            f"(verdict={v['verdict']}, candidate={v['candidate']}, "
            f"window={v['window_rows']} rows, {seal})")
    if rep["flight"] is not None and not rep["verdicts"]:
        lines.append("  replayed: no canary window covered this row yet")
    return "\n".join(lines)


def _fmt_history_line(e: dict, t0: float) -> str:
    t = e.get("mono", t0) - t0
    rank = e.get("rank", "?")
    detail = {k: v for k, v in e.items()
              if k not in ("v", "kind", "rank", "pid", "seq", "mono",
                           "wall")}
    body = " ".join(f"{k}={v}" for k, v in sorted(detail.items())
                    if v is not None)
    return f"  +{t:9.3f}s  rank {rank:>3}  {e.get('kind'):<22s} {body}"


def format_report(rep: dict) -> str:
    """The human post-mortem. Sections keyed to build_report's dict."""
    lines = [
        f"run post-mortem: {rep['n_events']} events from "
        f"{len(rep['ranks'])} emitter(s) (ranks {rep['ranks']}), "
        f"schema v{rep['schema_versions']}, span {rep['span_s']:.3f}s",
        "",
    ]
    if rep["phase_seconds"]:
        total = sum(rep["phase_seconds"].values()) or 1.0
        lines.append("phase-time table (host wall, from iteration spans):")
        lines.append(f"  {'phase':<12s} {'seconds':>10s} {'share':>7s}")
        for phase, secs in sorted(rep["phase_seconds"].items(),
                                  key=lambda kv: -kv[1]):
            lines.append(f"  {phase:<12s} {secs:>10.3f} "
                         f"{100.0 * secs / total:>6.1f}%")
        lines.append("")
    if rep.get("span_tree"):
        lines.append("span tree (flight recorder, self/child time):")
        lines.append(f"  {'span':<28s} {'count':>6s} {'total s':>10s} "
                     f"{'self s':>10s}")
        for n in rep["span_tree"]:
            label = "  " * n["depth"] + n["name"] + \
                (f"  [open x{n['open']}]" if n["open"] else "")
            lines.append(f"  {label:<28s} {n['count']:>6d} "
                         f"{n['total_s']:>10.3f} {n['self_s']:>10.3f}")
        if rep.get("torn_spans"):
            lines.append(f"  ({rep['torn_spans']} torn span(s): begin "
                         f"with no end — writer died mid-span)")
        lines.append("")
    if rep.get("async_overlap"):
        ov = rep["async_overlap"]
        lines.append(
            f"async occupancy (measured from actor/learner spans): "
            f"async_overlap_measured={ov['async_overlap_measured']:.3f} "
            f"(window {ov['window_s']:.3f}s, actor busy "
            f"{ov['actor_busy_s']:.3f}s, learner busy "
            f"{ov['learner_busy_s']:.3f}s, concurrent "
            f"{ov['concurrent_s']:.3f}s, idle {ov['idle_s']:.3f}s)")
        lines.append("")
    if rep.get("skew", {}).get("applied"):
        sk = rep["skew"]
        ranks = ", ".join(
            f"rank {r}: shift {v['shift_s']*1e3:+.3f}ms "
            f"(±{v['residual_s']*1e3:.3f}ms, n={v['n_samples']})"
            for r, v in sk["ranks"].items())
        lines.append(
            f"clock skew: timeline rewritten onto rank "
            f"{sk['reference_rank']}'s monotonic axis — {ranks}; "
            f"max residual {sk['max_residual_s']*1e3:.3f}ms")
        lines.append("")
    if rep["history"]:
        lines.append("restart / rollback / fault history:")
        for e in rep["history"]:
            lines.append(_fmt_history_line(e, rep["t0_mono"]))
        lines.append("")
    if rep["steps_curve"]:
        lines.append("steps/s curve (logged iterations):")
        lines.append(f"  {'iter':>6s} {'rank':>4s} {'steps/s':>12s} "
                     f"{'iter wall s':>12s}")
        for row in rep["steps_curve"]:
            sps = row.get("steps_per_sec")
            wall = row.get("wall_s")
            lines.append(
                f"  {row.get('iteration', '?'):>6} "
                f"{row.get('rank', 0):>4} "
                f"{(f'{sps:.1f}' if sps is not None else '?'):>12s} "
                f"{(f'{wall:.4f}' if wall is not None else '?'):>12s}")
        lines.append("")
    if rep.get("fleet"):
        by_kind = {}
        for e in rep["fleet"]:
            k = str(e.get("kind"))
            by_kind[k] = by_kind.get(k, 0) + 1
        summary = ", ".join(f"{k}={n}" for k, n in sorted(by_kind.items()))
        lines.append(f"flywheel & fleet health ({summary}):")
        for e in rep["fleet"]:
            lines.append(_fmt_history_line(e, rep["t0_mono"]))
        lines.append("")
    if rep.get("chaos"):
        lines.append("chaos story (env_fault events, evaluate --chaos):")
        lines.append(f"  {'regime':<12s} {'scheduler':<10s} "
                     f"{'avg JCT s':>10s} {'done':>6s} {'vs clean':>9s} "
                     f"{'drains':>7s}")
        for c in rep["chaos"]:
            deg = c.get("degradation")
            done = c.get("completion")
            jct = c.get("avg_jct")
            lines.append(
                f"  {str(c.get('regime')):<12s} "
                f"{str(c.get('scheduler')):<10s} "
                f"{(f'{jct:.1f}' if jct is not None else '?'):>10s} "
                f"{(f'{done:.0%}' if done is not None else '?'):>6s} "
                f"{(f'x{deg:.2f}' if deg is not None else '—'):>9s} "
                f"{str(c.get('n_drains', '?')):>7s}")
        lines.append("")
    alarm_total = sum(rep["alarms"].values())
    lines.append(
        "alarms: " + ", ".join(f"{k}={n}"
                               for k, n in sorted(rep["alarms"].items()))
        + ("" if alarm_total else "  (clean)"))
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="rlgpuschedule_tpu.obs.report",
        description="Merge per-rank event streams into one timeline and "
                    "print a run post-mortem.")
    p.add_argument("obs_dir", help="directory holding events.*.jsonl "
                                   "streams (--obs-dir of the run)")
    p.add_argument("--json", action="store_true",
                   help="print the structured report as JSON instead of "
                        "the human tables")
    p.add_argument("--out", default=None,
                   help="also write the merged ordered timeline to this "
                        "JSONL file")
    p.add_argument("--trace-out", default=None,
                   help="write the timeline as Chrome-trace JSON "
                        "(open in Perfetto / chrome://tracing)")
    p.add_argument("--no-skew-correct", action="store_true",
                   help="keep each rank's raw monotonic axis instead of "
                        "rewriting onto the learned corrected axis")
    p.add_argument("--strict-alarms", action="store_true",
                   help="exit 1 if any post-warmup alarm event "
                        f"({'/'.join(ALARM_KINDS)}) fired")
    p.add_argument("--request", default=None, metavar="ID",
                   help="print the single-request timeline for this "
                        "64-bit request id (decimal or 0x-hex) instead "
                        "of the run post-mortem; exit 1 if the id "
                        "appears nowhere")
    p.add_argument("--flight-log", default=None, metavar="DIR",
                   help="with --request: also join the id against this "
                        "flight-log directory's shards and promotion "
                        "ledger")
    args = p.parse_args(argv)
    try:
        events = merge_dir(args.obs_dir)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 1
    if not events:
        print(f"event streams under {args.obs_dir} hold no decodable "
              f"events", file=sys.stderr)
        return 1
    skew_info: dict = {"applied": False}
    if not args.no_skew_correct:
        events, skew_info = correct_events(events)
    if args.request is not None:
        try:
            req_id = int(args.request, 0)
        except ValueError:
            print(f"--request: {args.request!r} is not an integer id",
                  file=sys.stderr)
            return 2
        req = build_request_report(events, req_id,
                                   flight_dir=args.flight_log)
        if args.json:
            print(json.dumps(req, sort_keys=True))
        else:
            print(format_request_report(req))
        return 0 if req["found"] else 1
    if args.out:
        with open(args.out, "w") as f:
            for e in events:
                f.write(json.dumps(e, sort_keys=True) + "\n")
    if args.trace_out:
        with open(args.trace_out, "w") as f:
            json.dump(to_chrome_trace(events), f)
    rep = build_report(events)
    rep["skew"] = skew_info
    if args.json:
        print(json.dumps(rep, sort_keys=True))
    else:
        print(format_report(rep))
    if args.strict_alarms and sum(rep["alarms"].values()) > 0:
        print(f"strict-alarms: {rep['alarms']} alarm event(s) in the "
              f"timeline", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
