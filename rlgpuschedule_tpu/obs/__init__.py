"""Unified telemetry layer (L6 aux): event bus, metrics registry,
run-loop spans, production alarms, multihost merge + post-mortem.

Podracer's core observability argument — scalable RL stacks live or die
by cheap, always-on throughput/health telemetry — applied to this
codebase's production machinery: when a multihost run restarts, rolls
back, or silently recompiles, this package is what ties *what happened*
to *when and on which rank*.

- :mod:`.events` — the structured event bus: append-only JSONL, one
  stream per rank, every event stamped ``(v, kind, rank, pid, seq,
  mono, wall)``; reader tolerates a crashed writer's torn last line;
  :func:`merge_dir` orders interleaved per-rank streams into one
  timeline by the shared monotonic clock.
- :mod:`.metrics` — counters/gauges registry with an atomic
  Prometheus-text snapshot file (``metrics.prom``) and a live stdlib
  HTTP scrape endpoint (:func:`serve_http` — what the serving CLI's
  ``--metrics-port`` exposes).
- :mod:`.slo` — declarative SLO specs evaluated as multi-window burn
  rates over cumulative SLIs (ISSUE 20), refreshed by the registry's
  pre-scrape collector hook: ``slo_burn_rate`` /
  ``slo_error_budget_remaining`` gauges (the budget recovers as the
  window slides past an incident) and edge-triggered bus alerts.
- :mod:`.telemetry` — :class:`RunTelemetry` (what ``Experiment.run`` /
  ``PopulationExperiment.run`` hold: iteration spans with a
  rollout+update/sync/eval/ckpt phase breakdown, zero added host syncs)
  and :class:`Alarms` (``CompileCounter`` + transfer-guard promoted
  from test-only sentinels to production: ``recompile``/``transfer``
  events, optional slow-iteration ``jax.profiler`` auto-capture).
- :mod:`.report` — ``python -m rlgpuschedule_tpu.obs.report <dir>``:
  merged timeline post-mortem (phase-time table, span tree, restart/
  rollback history, steps/s curve, alarm summary; ``--strict-alarms``
  for CI, ``--trace-out`` for the Perfetto export).
- :mod:`.trace` — the span-tracing flight recorder: nestable,
  thread-aware :meth:`Tracer.span` extents on the same bus (track =
  ``(rank, thread)``), plus :func:`to_chrome_trace` so any run opens in
  Perfetto / ``chrome://tracing``.
- :mod:`.skew` — the cross-host clock-skew handshake: ranks stamp
  ``(wall, mono)`` offset samples; :func:`correct_events` rewrites a
  merged timeline onto one corrected monotonic axis with a residual-
  uncertainty annotation.

Event kinds by emitter:

== run loops (``experiment.py``): ``run_start``, ``iteration``,
   ``run_end``, ``pbt_exploit``
== tracer (any layer, ``--trace``): ``span_begin``, ``span_end``,
   ``span_point``
== alarms: ``compile`` (warmup/expected), ``recompile``, ``transfer``,
   ``slow_iteration``, ``profile_captured``
== checkpoint: ``ckpt_save``, ``ckpt_restore``, ``ckpt_reject``,
   ``ckpt_crc_reject``, ``ckpt_elastic_restore``
== resilience: ``rollback`` (watchdog), ``fault`` (injector)
== supervisor: ``gang_launch``, ``rank_failure``, ``gang_restart``,
   ``gang_shrink``, ``supervisor_done``
== multihost worker: ``worker_start``, ``worker_resumed``,
   ``worker_step``, ``worker_done``, ``clock_skew``
== data flywheel (``flywheel/``): ``flywheel_shard_seal`` (flight-log
   writer), ``promote_blocked`` (canary gate), ``promote_apply`` (serve
   CLI promotion driver), ``promote_rollback`` (SLO watchdog) — none
   are alarm kinds, so a healthy promotion keeps ``--strict-alarms``
   green
== SLO engine (:mod:`.slo`): ``slo_burn_alert`` (every burn window of a
   spec over threshold — rising edge) and ``slo_burn_clear`` (falling
   edge, budget recovering) — deliberately not alarm kinds either:
   ``--strict-alarms`` stays a compile/transfer contract while SLO
   health alerts on its own channel
"""
from .events import (EventBus, SCHEMA_VERSION, event_streams, merge_dir,
                     merge_events, read_events)
from .metrics import (Counter, Gauge, Histogram, MetricsHTTPServer,
                      Registry, serve_http)
from .skew import (RankSkew, correct_events, learn_offsets,
                   merge_dir_corrected)
from .slo import DEFAULT_WINDOWS, SLOEngine, SLOSpec, histogram_sli
from .telemetry import AlarmError, Alarms, RunTelemetry
from .trace import (NULL_TRACER, Tracer, async_overlap_summary,
                    build_span_tree, to_chrome_trace, tracer_of)

__all__ = [
    "EventBus", "SCHEMA_VERSION", "event_streams", "merge_dir",
    "merge_events", "read_events",
    "Counter", "Gauge", "Histogram", "MetricsHTTPServer", "Registry",
    "serve_http",
    "AlarmError", "Alarms", "RunTelemetry",
    "NULL_TRACER", "Tracer", "async_overlap_summary", "build_span_tree",
    "to_chrome_trace", "tracer_of",
    "RankSkew", "correct_events", "learn_offsets", "merge_dir_corrected",
    "DEFAULT_WINDOWS", "SLOEngine", "SLOSpec", "histogram_sli",
]
